"""Memory connector: writable in-memory tables (device-resident pages).

Reference blueprint: plugin/trino-memory (MemoryConnector/MemoryMetadata/
MemoryPagesStore — SURVEY.md §2.9 "Benchmark/test connectors"). Tables live as
lists of device Pages; CREATE TABLE AS / INSERT append, scans concatenate.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Column, Page


@dataclass
class _StoredTable:
    columns: Tuple[ColumnMetadata, ...]
    pages: List[Page] = field(default_factory=list)
    # bucketed layout (ref: plugin/trino-memory has none; this mirrors
    # hive-style bucketed tables so the engine's co-located join path has a
    # first-class fixture): rows are hash-split on write, split i == bucket i
    bucketed_by: Tuple[str, ...] = ()
    bucket_count: int = 0

    def row_count(self) -> int:
        return sum(
            int(np.asarray(p.active).sum()) for p in self.pages if p is not None
        )


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: Dict[SchemaTableName, _StoredTable] = {}
        # warm-path cache plane: per-table mutation versions drawn from one
        # monotone counter (drop+recreate never repeats a version). The
        # nonce is per CONNECTOR INSTANCE: two memory connectors in one
        # process (or a restarted process reading a persisted cache) hold
        # different data at the same count — their tokens must never match
        self._versions: Dict[SchemaTableName, int] = {}
        self._version_seq = 0
        self._cache_nonce = uuid.uuid4().hex[:8]
        # reentrant: DML holds mutation_guard() across a read-compute-swap
        # that itself calls the locked replace_pages
        self._lock = threading.RLock()
        self._meta = _MemoryMetadata(self)
        self._splits = _MemorySplitManager(self)
        self._pages = _MemoryPageSourceProvider(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    # ------------------------------------------------------------------- DML

    def create_table(
        self,
        name: SchemaTableName,
        columns: Sequence[ColumnMetadata],
        bucketed_by: Sequence[str] = (),
        bucket_count: int = 0,
    ) -> None:
        with self._lock:
            if name in self._tables:
                raise ValueError(f"table already exists: {name}")
            if bucketed_by:
                known = {c.name for c in columns}
                missing = [c for c in bucketed_by if c not in known]
                if missing or bucket_count < 1:
                    raise ValueError(
                        f"bad bucketing spec: columns={missing or bucketed_by} "
                        f"count={bucket_count}"
                    )
            self._tables[name] = _StoredTable(
                tuple(columns), bucketed_by=tuple(bucketed_by),
                bucket_count=bucket_count if bucketed_by else 0,
            )
            self._bump(name)

    def drop_table(self, name: SchemaTableName, if_exists: bool = False) -> None:
        with self._lock:
            if name not in self._tables:
                if if_exists:
                    return
                raise ValueError(f"table not found: {name}")
            del self._tables[name]
            self._bump(name)

    def _bump(self, name: SchemaTableName) -> None:
        """Advance the table's mutation version (called under _lock)."""
        self._version_seq += 1
        self._versions[name] = self._version_seq

    def cache_table_version(self, schema: str, table: str):
        """Warm-path cache plane hook (runtime/cachestore.py): the mutation
        counter versions in-memory tables exactly — every create/drop/
        insert/replace advances it, so stale warm entries can never match.
        The instance nonce keeps tokens unique across connector INSTANCES
        and processes: a different memory connector (or a restarted
        process reading a persisted cache) holding different data at the
        same count must never alias."""
        with self._lock:
            n = self._versions.get(SchemaTableName(schema, table), 0)
        return f"mem{self._cache_nonce}-{n}"

    def insert(self, name: SchemaTableName, page: Page) -> int:
        """Append a page (the ConnectorPageSink.appendPage analogue).
        Bucketed tables hash-split the rows on write so split i holds
        exactly bucket i (hive bucketed-write analogue)."""
        with self._lock:
            table = self._tables.get(name)
            if table is None:
                raise ValueError(f"table not found: {name}")
            if page.num_columns != len(table.columns):
                raise ValueError(
                    f"column count mismatch: {page.num_columns} vs {len(table.columns)}"
                )
            rows = int(np.asarray(page.active).sum())
            self._bump(name)
            if not table.bucketed_by:
                table.pages.append(page)
                return rows
            from ..spi.host_pages import (
                host_partition_targets,
                page_to_host as _page_to_host,
                pages_from_host_rows as _pages_from_host_rows,
            )

            cols = _page_to_host(page)
            key_idx = [
                next(i for i, c in enumerate(table.columns) if c.name == k)
                for k in table.bucketed_by
            ]
            targets = host_partition_targets(cols, key_idx, table.bucket_count)
            while len(table.pages) < table.bucket_count:
                table.pages.append(None)
            for b in range(table.bucket_count):
                sel = targets == b
                if not sel.any():
                    continue
                newp = _pages_from_host_rows(cols, sel)
                old = table.pages[b]
                if old is None:
                    table.pages[b] = newp
                else:
                    from ..runtime.executor import _concat_pages

                    table.pages[b] = _concat_pages([old, newp])
            return rows

    def table(self, name: SchemaTableName) -> Optional[_StoredTable]:
        with self._lock:
            return self._tables.get(name)

    def mutation_guard(self):
        """Hold the table lock across a read-compute-swap so a concurrent
        INSERT can't land between reading ``pages`` and ``replace_pages``
        (rows it appended would be silently discarded)."""
        return self._lock

    def replace_pages(self, name: SchemaTableName, pages: List[Page]) -> None:
        """Swap a table's pages atomically (row-level DELETE/UPDATE/MERGE —
        the ConnectorMergeSink.storeMergedRows analogue for an in-memory
        store). Bucketed tables re-bucket the replacement rows so the
        split i == bucket i invariant survives DML."""
        with self._lock:
            table = self._tables.get(name)
            if table is None:
                raise ValueError(f"table not found: {name}")
            self._bump(name)
            if not table.bucketed_by:
                table.pages = list(pages)
                return
            table.pages = []
            for p in pages:
                if p is not None:
                    self.insert(name, p)


class _MemoryMetadata(ConnectorMetadata):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def list_schemas(self):
        return sorted({n.schema for n in self.connector._tables} | {"default"})

    def list_tables(self, schema: Optional[str] = None):
        return sorted(
            (n for n in self.connector._tables if schema is None or n.schema == schema),
            key=str,
        )

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        t = self.connector.table(name)
        if t is None:
            return None
        return TableMetadata(name, t.columns)

    def table_partitioning(self, handle: TableHandle):
        from ..spi.connector import TablePartitioning

        t = self.connector.table(handle.schema_table)
        if t is None or not t.bucketed_by:
            return None
        return TablePartitioning(
            columns=t.bucketed_by, bucket_count=t.bucket_count
        )

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        t = self.connector.table(handle.schema_table)
        return TableStatistics(row_count=float(t.row_count()) if t else 0.0)


class _MemorySplitManager(ConnectorSplitManager):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        t = self.connector.table(handle.schema_table)
        if t is None:
            return []
        if t.bucketed_by:
            # split i IS bucket i; empty buckets still get a split so the
            # co-located join's bucket alignment holds on both sides
            return [
                Split(handle, i, t.bucket_count) for i in range(t.bucket_count)
            ]
        if not t.pages:
            return []
        return [Split(handle, i, len(t.pages)) for i in range(len(t.pages))]


class _MemoryPageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        t = self.connector.table(split.table.schema_table)
        page = (
            t.pages[split.split_id] if split.split_id < len(t.pages) else None
        )
        if page is None:  # empty bucket of a bucketed table
            from ..spi.host_pages import empty_page_for

            names = [t.columns[i].name for i in column_indexes]
            types = {t.columns[i].name: t.columns[i].type for i in column_indexes}
            return empty_page_for(names, types)
        cols = tuple(page.columns[i] for i in column_indexes)
        return Page(cols, page.active)


class BlackHoleConnector(Connector):
    """plugin/trino-blackhole analogue: accepts writes, reads return nothing."""

    name = "blackhole"

    def __init__(self):
        self._schemas: Dict[SchemaTableName, Tuple[ColumnMetadata, ...]] = {}
        self._meta = _BlackHoleMetadata(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        class _NoSplits(ConnectorSplitManager):
            def get_splits(self, handle, desired_splits=1):
                return []

        return _NoSplits()

    def page_source_provider(self):
        class _NoPages(ConnectorPageSourceProvider):
            def create_page_source(self, split, column_indexes):
                raise RuntimeError("blackhole has no data")

        return _NoPages()

    def create_table(self, name, columns):
        self._schemas[name] = tuple(columns)

    def drop_table(self, name, if_exists=False):
        if name not in self._schemas and not if_exists:
            raise ValueError(f"table not found: {name}")
        self._schemas.pop(name, None)

    def insert(self, name, page) -> int:
        return int(np.asarray(page.active).sum())  # swallowed


class _BlackHoleMetadata(ConnectorMetadata):
    def __init__(self, connector: BlackHoleConnector):
        self.connector = connector

    def list_schemas(self):
        return ["default"]

    def list_tables(self, schema=None):
        return sorted(self.connector._schemas, key=str)

    def get_table_metadata(self, name):
        cols = self.connector._schemas.get(name)
        return TableMetadata(name, cols) if cols else None
