from .mesh import make_mesh, device_count
from . import exchange
