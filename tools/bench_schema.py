"""BENCH_*.json schema audit: every checked-in bench record must say what
hardware, what code, and what schema produced it — and carry result
fingerprints so a perf number can never drift apart from the answer it
measured.

Four requirements per file:

- ``schema_version`` — top-level int >= 1 (>= 3 engages the strict v3
  shape: ``bench`` in the v3 family ("ladder", "hostpath_ab"),
  platform/device labels, per-entry median/MAD/samples/fingerprint — the
  contract tools/bench_regress.py compares).
- ``git_sha`` — non-empty commit label.
- ``platform`` — an accelerator-platform label. The historical files
  disagree on spelling, so ``platform`` or ``backend`` is accepted, at the
  top level or under ``detail``/``result`` (r10+ put a host string in
  "platform" and the jax backend in "backend" — the backend is the label
  that matters).
- ``fingerprints`` — at least one result-fingerprint field anywhere in the
  record (key matching ``fingerprint``, case-insensitive).

The r01–r16 files predate one or more of these rules.  Their gaps are
WAIVED file-by-file in ``LEGACY_EXCEPTIONS`` below — an audit record, not a
loophole: the table is keyed by exact filename, so every NEW file gets full
enforcement, and deleting a legacy file retires its waiver with it.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import FrozenSet, List, Optional

REQUIREMENTS = ("schema_version", "git_sha", "platform", "fingerprints")

_ALL = frozenset(REQUIREMENTS)

# filename -> requirements waived for that file (the round-19 audit of every
# checked-in record; see module docstring). Nothing else is ever waived.
LEGACY_EXCEPTIONS: dict = {
    "BENCH_r01.json": _ALL,
    "BENCH_r02.json": _ALL,
    "BENCH_r03.json": _ALL,
    "BENCH_r04.json": _ALL,
    "BENCH_r05.json": _ALL,
    "BENCH_r06_ooc_ab.json": _ALL,
    "BENCH_r07_exchange_ab.json": _ALL,
    "BENCH_r09_concurrency.json": frozenset({"platform", "fingerprints"}),
    "BENCH_r10_stats_ab.json": frozenset({"git_sha", "fingerprints"}),
    "BENCH_r11_cache_ab.json": frozenset({"fingerprints"}),
    "BENCH_r12_sanity_ab.json": frozenset({"fingerprints"}),
    "BENCH_r14_megakernel_ab.json": _ALL,
    "BENCH_r15_vector_ab.json": frozenset({"fingerprints"}),
}

_FP_KEY = re.compile("fingerprint", re.IGNORECASE)

# the v3 bench family: a schema_version >= 3 record must declare which v3
# bench produced it and satisfy the same strict per-entry shape (median/MAD
# dispersion, raw samples, a result fingerprint) — "ladder" is bench.py
# run_ladder, "hostpath_ab" is bench.py run_hostpath_ab (r19), "fleet_ab"
# is bench.py run_fleet_ab (r20: the multi-process coordinator fleet
# scaling replay)
V3_BENCH_FAMILY = ("ladder", "hostpath_ab", "fleet_ab")


def _has_fingerprint(obj) -> bool:
    if isinstance(obj, dict):
        return any(
            _FP_KEY.search(k) or _has_fingerprint(v) for k, v in obj.items()
        )
    if isinstance(obj, list):
        return any(_has_fingerprint(v) for v in obj)
    return False


def _platform_label(record: dict) -> Optional[str]:
    scopes = [record]
    for key in ("detail", "result"):
        if isinstance(record.get(key), dict):
            scopes.append(record[key])
    for scope in scopes:
        for key in ("backend", "platform"):
            v = scope.get(key)
            if isinstance(v, str) and v:
                return v
    return None


def _ladder_problems(record: dict) -> List[str]:
    """The strict v3+ shape (bench.py run_ladder / run_hostpath_ab)."""
    problems = []
    if record.get("bench") not in V3_BENCH_FAMILY:
        problems.append(
            f"schema_version >= 3 requires bench in {V3_BENCH_FAMILY} "
            f"(got {record.get('bench')!r})"
        )
    for key in ("platform", "device"):
        if not isinstance(record.get(key), str) or not record.get(key):
            problems.append(f"missing hardware label {key!r}")
    if "hardware_verified" not in record:
        problems.append("missing 'hardware_verified'")
    results = record.get("results")
    if not isinstance(results, dict) or not results:
        problems.append("missing 'results'")
        return problems
    for name, r in sorted(results.items()):
        if not isinstance(r, dict):
            problems.append(f"results[{name!r}] not an object")
            continue
        for field in ("median_secs", "mad_secs"):
            if not isinstance(r.get(field), (int, float)):
                problems.append(f"results[{name!r}] missing {field!r}")
        if not isinstance(r.get("samples"), list) or not r.get("samples"):
            problems.append(f"results[{name!r}] missing 'samples'")
        if not isinstance(r.get("fingerprint"), str) or not r.get("fingerprint"):
            problems.append(f"results[{name!r}] missing 'fingerprint'")
    return problems


def validate_record(record, waived: FrozenSet[str] = frozenset()) -> List[str]:
    if not isinstance(record, dict):
        return ["not a JSON object"]
    problems = []
    sv = record.get("schema_version")
    if "schema_version" not in waived and (
        not isinstance(sv, int) or sv < 1
    ):
        problems.append(f"missing/invalid schema_version (got {sv!r})")
    if "git_sha" not in waived and not (
        isinstance(record.get("git_sha"), str) and record.get("git_sha")
    ):
        problems.append("missing git_sha")
    if "platform" not in waived and _platform_label(record) is None:
        problems.append("missing platform label ('platform' or 'backend')")
    if "fingerprints" not in waived and not _has_fingerprint(record):
        problems.append("no result fingerprints anywhere in the record")
    if isinstance(sv, int) and sv >= 3:
        problems.extend(_ladder_problems(record))
    return problems


def validate_file(path: str) -> List[str]:
    name = os.path.basename(path)
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable ({e})"]
    waived = LEGACY_EXCEPTIONS.get(name, frozenset())
    return [f"{name}: {p}" for p in validate_record(record, waived)]


def bench_files(root: Optional[str] = None) -> List[str]:
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(glob.glob(os.path.join(root, "BENCH_*.json")))


def main(argv: Optional[List[str]] = None) -> int:
    paths = list(sys.argv[1:] if argv is None else argv) or bench_files()
    problems: List[str] = []
    for p in paths:
        problems.extend(validate_file(p))
    for p in problems:
        print(p)
    if not problems:
        print(f"bench_schema: {len(paths)} file(s) ok")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
