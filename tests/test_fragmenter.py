"""AddExchanges + PlanFragmenter + DistributedQueryRunner tests.

Coverage model: Trino's fragmenter/scheduler tests plus DistributedQueryRunner
result parity against the single-node engine (SURVEY.md §4).
"""

import pytest

from trino_tpu.planner.fragmenter import (
    ExchangeType,
    Partitioning,
    RemoteSourceNode,
    add_exchanges,
    create_fragments,
)
from trino_tpu.planner.plan import (
    AggregationNode,
    AggregationStep,
    visit_plan,
)

SCALE = 0.0005


@pytest.fixture(scope="module")
def local():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def dist():
    from trino_tpu.parallel.runner import DistributedQueryRunner

    return DistributedQueryRunner.tpch(scale=SCALE, n_workers=4, split_target_rows=512)


def _subplan(local, sql):
    plan = local.plan_sql(sql)
    plan = add_exchanges(plan, local.metadata, local.session)
    return create_fragments(plan)


class TestFragmenter:
    def test_groupby_splits_into_partial_final(self, local):
        sub = _subplan(local, "SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1")
        steps = []

        for f in sub.fragments:
            visit_plan(
                f.root,
                lambda n: steps.append(n.step) if isinstance(n, AggregationNode) else None,
            )
        assert AggregationStep.PARTIAL in steps
        assert AggregationStep.FINAL in steps
        # partial agg lives in the SOURCE fragment, final in FIXED_HASH
        parts = {f.partitioning for f in sub.fragments}
        assert Partitioning.SOURCE in parts
        assert Partitioning.FIXED_HASH in parts

    def test_join_repartitions_both_sides(self, local):
        local.session.set("join_distribution_type", "PARTITIONED")
        try:
            sub = _subplan(
                local,
                "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey",
            )
        finally:
            local.session.properties.pop("join_distribution_type", None)
        remotes = []
        for f in sub.fragments:
            visit_plan(
                f.root,
                lambda n: remotes.append(n) if isinstance(n, RemoteSourceNode) else None,
            )
        repart = [r for r in remotes if r.exchange_type == ExchangeType.REPARTITION]
        assert len(repart) >= 2  # both join inputs hash-partitioned

    def test_broadcast_join(self, local):
        # nation is tiny -> AUTO chooses broadcast
        sub = _subplan(
            local,
            "SELECT count(*) FROM customer JOIN nation ON c_nationkey = n_nationkey",
        )
        remotes = []
        for f in sub.fragments:
            visit_plan(
                f.root,
                lambda n: remotes.append(n) if isinstance(n, RemoteSourceNode) else None,
            )
        assert any(r.exchange_type == ExchangeType.BROADCAST for r in remotes)

    def test_fragments_children_first(self, local):
        sub = _subplan(local, "SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1 ORDER BY 2")
        seen = set()
        for f in sub.fragments:
            for dep in f.input_fragments:
                assert dep in seen
            seen.add(f.fragment_id)


class TestDistributedParity:
    QUERIES = [
        "SELECT count(*), sum(l_quantity) FROM lineitem",
        "SELECT l_returnflag, count(*) c, avg(l_quantity) a FROM lineitem GROUP BY 1 ORDER BY 1",
        "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey WHERE l_quantity < 10",
        "SELECT o_orderpriority, count(*) FROM orders GROUP BY 1 ORDER BY 2 DESC, 1 LIMIT 3",
        "SELECT c_mktsegment, count(*) FROM customer JOIN nation ON c_nationkey = n_nationkey GROUP BY 1 ORDER BY 1",
        "SELECT max(l_extendedprice), min(l_shipdate), stddev(l_quantity) FROM lineitem",
        "SELECT count(*) FROM lineitem WHERE l_orderkey IN (SELECT o_orderkey FROM orders WHERE o_totalprice > 200000)",
    ]

    @pytest.mark.parametrize("sql", QUERIES)
    def test_matches_local(self, local, dist, sql):
        a = dist.execute(sql).rows
        b = local.execute(sql).rows
        assert len(a) == len(b)
        for ra, rb in zip(a, b):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    assert abs(va - vb) <= 1e-9 * max(1.0, abs(vb))
                else:
                    assert va == vb


class TestExchangeWire:
    def test_parity_with_compression(self, local, dist):
        """Exchanged pages survive the serialize->LZ4->deserialize wire path."""
        dist.session.set("exchange_compression", True)
        try:
            sql = ("SELECT l_returnflag, count(*) c, sum(l_extendedprice) s "
                   "FROM lineitem GROUP BY 1 ORDER BY 1")
            assert dist.execute(sql).rows == local.execute(sql).rows
        finally:
            dist.session.properties.pop("exchange_compression", None)


class TestCrossJoinElimination:
    def test_disconnected_from_order_reordered(self, local):
        # part x supplier share no direct edge; the join graph must route
        # through lineitem instead of materializing a cross product
        plan_text = local.explain(
            "SELECT count(*) FROM part, supplier, lineitem "
            "WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey"
        )
        assert "CROSS" not in plan_text
        assert plan_text.count("Join[INNER") == 2

    def test_reordered_results_match(self, local):
        a = local.execute(
            "SELECT count(*) FROM part, supplier, lineitem "
            "WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey"
        ).rows
        b = local.execute(
            "SELECT count(*) FROM lineitem JOIN part ON p_partkey = l_partkey "
            "JOIN supplier ON s_suppkey = l_suppkey"
        ).rows
        assert a == b

    def test_true_cross_join_still_works(self, local):
        res = local.execute("SELECT count(*) FROM nation, region")
        assert res.rows == [(125,)] or res.rows == [(25 * 5,)]
