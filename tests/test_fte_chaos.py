"""Chaos harness: the event-driven FTE scheduler under injected faults.

ref: BaseFailureRecoveryTest (SURVEY.md §4) — for every injection site
(task crash mid-execute, crash after commit, torn commit, corrupt committed
frame, refused/hung worker RPC), a distributed TPC-H query under
retry_policy=TASK must return results BIT-IDENTICAL to the no-fault run;
EventDrivenFaultTolerantQueryScheduler.java:209 (concurrent dispatch,
classified retry, speculation); HeartbeatFailureDetector + per-query node
blacklist. USER-category failures must fail the query immediately and
consume ZERO retries.
"""

import time

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.metadata import CatalogManager, Session
from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime.failure import (
    ChaosInjector,
    ErrorCategory,
    InjectedFailure,
    RetryableQueryError,
    TaskDeadlineExceeded,
    classify_error,
    execute_with_retry,
    retry_backoff,
)
from trino_tpu.runtime.metrics import REGISTRY
from trino_tpu.runtime.observability import RECORDER
from trino_tpu.server.worker import TaskFailedError, WorkerServer

SCALE = 0.0005
SECRET = "fte-chaos-secret"

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q13 = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c_custkey, count(o_orderkey) AS c_count
  FROM customer LEFT JOIN orders ON c_custkey = o_custkey
    AND o_comment NOT LIKE '%special%requests%'
  GROUP BY c_custkey
) AS c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""


def _fte_runner(n_workers: int = 4) -> DistributedQueryRunner:
    runner = DistributedQueryRunner.tpch(scale=SCALE, n_workers=n_workers)
    runner.session.set("retry_policy", "TASK")
    # tiny test tables would collapse to one partition — force fan-out so
    # stages really run at width (the concurrency the tentpole is about)
    runner.session.set("join_distribution_type", "PARTITIONED")
    runner.session.set("target_partition_rows", 200)
    return runner


@pytest.fixture(scope="module")
def expected():
    """The no-fault FTE runs every chaos result must be bit-identical to
    (also warms the XLA compile caches, de-flaking deadline tests)."""
    runner = _fte_runner()
    return {sql: runner.execute(sql).rows for sql in (Q3, Q13)}


def _retries_counter():
    return REGISTRY.counter(
        "trino_tpu_task_retries_total",
        help="FTE task retries after classified retryable failures",
    )


class TestClassification:
    def test_user_error_types(self):
        from trino_tpu.ops.compiler import CompileError
        from trino_tpu.planner.logical_planner import SemanticError

        assert classify_error(CompileError("bad")) is ErrorCategory.USER
        assert classify_error(SemanticError("bad")) is ErrorCategory.USER

    def test_transport_and_default(self):
        assert classify_error(OSError("boom")) is ErrorCategory.EXTERNAL
        assert classify_error(RuntimeError("boom")) is ErrorCategory.INTERNAL
        assert classify_error(TaskDeadlineExceeded("t")) is ErrorCategory.EXTERNAL

    def test_remote_task_failures_classify_from_text(self):
        # workers serialize failures as "TypeName: message" — a worker-side
        # CompileError must fail the query as fast as a local one
        assert classify_error(
            TaskFailedError("t1", "CompileError: sequence step 0")
        ) is ErrorCategory.USER
        assert classify_error(
            TaskFailedError("t1", "URLError: <urlopen error refused>")
        ) is ErrorCategory.EXTERNAL
        assert classify_error(
            TaskFailedError("t1", "RuntimeError: boom")
        ) is ErrorCategory.INTERNAL

    def test_injected_category_wins(self):
        exc = InjectedFailure("x", category=ErrorCategory.USER)
        assert classify_error(exc) is ErrorCategory.USER

    def test_resource_pressure_is_retryable(self):
        # per-query OOM is TRANSIENT (ref: INSUFFICIENT_RESOURCES): a retry
        # on a less-loaded worker can succeed, so it must never
        # short-circuit the retry budget the way USER errors do
        from trino_tpu.runtime.memory import ExceededMemoryLimitError

        assert classify_error(
            ExceededMemoryLimitError("query limit 1GB exceeded")
        ) is ErrorCategory.INTERNAL
        assert classify_error(
            TaskFailedError("t1", "ExceededMemoryLimitError: limit exceeded")
        ) is ErrorCategory.INTERNAL

    def test_shedding_decisions_never_retry(self):
        # queue-full and administrative/low-memory kills are DELIBERATE
        # shedding decisions (ref: QUERY_QUEUE_FULL / CLUSTER_OUT_OF_MEMORY /
        # ADMINISTRATIVELY_KILLED): FTE retrying them would re-submit the
        # very load the arbitration plane just rejected — zero retries
        from trino_tpu.runtime.memory import QueryKilledError
        from trino_tpu.runtime.resource_groups import QueryQueueFullError

        assert classify_error(
            QueryQueueFullError("queue full")
        ) is ErrorCategory.USER
        assert classify_error(
            QueryKilledError("killed by the low-memory killer")
        ) is ErrorCategory.USER
        assert classify_error(
            TaskFailedError("t1", "QueryKilledError: cluster out of memory")
        ) is ErrorCategory.USER
        assert classify_error(
            TaskFailedError("t1", "AdministrativelyKilled: shed")
        ) is ErrorCategory.USER

    def test_backoff_capped_and_jittered(self):
        for n in range(1, 12):
            d = retry_backoff(n, initial=0.05, cap=2.0)
            base = min(2.0, 0.05 * 2 ** (n - 1))
            assert 0.5 * base <= d <= 1.5 * base

    def test_query_retry_never_retries_user_errors(self):
        calls = []

        def run(sql):
            calls.append(sql)
            raise InjectedFailure("semantic", category=ErrorCategory.USER)

        with pytest.raises(InjectedFailure):
            execute_with_retry(run, "SELECT 1", retry_policy="QUERY")
        assert len(calls) == 1  # failed fast, no re-run

    def test_query_retry_still_retries_internal(self):
        calls = []

        def run(sql):
            calls.append(sql)
            raise RetryableQueryError("worker died")

        with pytest.raises(RetryableQueryError):
            execute_with_retry(run, "SELECT 1", retry_policy="QUERY")
        assert len(calls) == 2


class TestNodeBlacklist:
    def test_hard_and_soft_strikes(self):
        from trino_tpu.runtime.nodes import NodeBlacklist

        bl = NodeBlacklist(ttl=30.0, max_strikes=2)
        assert not bl.strike("http://w1", hard=False)  # first soft strike
        assert not bl.is_blacklisted("http://w1")
        assert bl.strike("http://w1", hard=False)      # second -> blacklisted
        assert bl.is_blacklisted("http://w1")
        assert bl.strike("http://w2", "died", hard=True)
        assert bl.is_blacklisted("http://w2/")  # trailing-slash normalized
        assert bl.filter(["http://w1", "http://w2", "http://w3"]) == ["http://w3"]
        assert bl.blacklisted_total == 2

    def test_timed_readmission(self):
        from trino_tpu.runtime.nodes import NodeBlacklist

        bl = NodeBlacklist(ttl=0.05)
        bl.strike("http://w1", hard=True)
        assert bl.is_blacklisted("http://w1")
        time.sleep(0.08)
        assert not bl.is_blacklisted("http://w1")  # ttl re-admission

    def test_explicit_readmit(self):
        from trino_tpu.runtime.nodes import NodeBlacklist

        bl = NodeBlacklist()
        bl.strike("http://w1", hard=True)
        bl.readmit("http://w1")
        assert not bl.is_blacklisted("http://w1")

    def test_heartbeat_expiry_feeds_blacklist(self):
        from trino_tpu.runtime.nodes import InternalNodeManager, NodeBlacklist

        mgr = InternalNodeManager(heartbeat_timeout=0.01)
        mgr.announce("w1", "http://w1")
        mgr.announce("w2", "http://w2")
        time.sleep(0.05)
        mgr.announce("w2", "http://w2")  # w2 stays fresh
        bl = NodeBlacklist()
        assert bl.sync_nodes(mgr) == 1
        assert bl.is_blacklisted("http://w1")
        assert not bl.is_blacklisted("http://w2")


class TestChaosLocalFte:
    """Every exchange/task-layer injection site, local FTE mode:
    bit-identical results to the no-fault run, recovery via task
    re-attempts (never a query restart)."""

    @pytest.mark.parametrize("site", [
        "task_crash_mid_execute",
        "task_crash_after_commit",
        "exchange_torn_commit",
    ])
    def test_fault_recovers_bit_identical(self, expected, site):
        runner = _fte_runner()
        before = _retries_counter().value
        with ChaosInjector() as chaos:
            chaos.arm(site, times=1)
            rows = runner.execute(Q3).rows
        assert chaos.fired.get(site) == 1, f"{site} never fired"
        assert rows == expected[Q3]
        sched = runner.last_fte_scheduler
        assert sched.stats["retries"] >= 1
        assert _retries_counter().value > before
        # the recovery was a TASK re-attempt: some task reached attempt >= 1
        assert max(runner.last_task_attempts.values()) >= 1

    def test_corrupt_committed_frame_triggers_reattempt(self, expected):
        """A committed-but-undecodable producer attempt must be quarantined
        and RE-PRODUCED under a new attempt number — not fail the query,
        and not loop a consumer retry over the same corrupt bytes."""
        runner = _fte_runner()
        with ChaosInjector() as chaos:
            chaos.arm("exchange_corrupt_frame", times=1)
            rows = runner.execute(Q13).rows
        assert chaos.fired.get("exchange_corrupt_frame") == 1
        assert rows == expected[Q13]
        sched = runner.last_fte_scheduler
        assert sched.stats["corruption_recoveries"] >= 1
        # the producer re-ran under a NEW attempt number
        assert max(runner.last_task_attempts.values()) >= 1

    def test_root_output_corruption_recovers(self, expected):
        """Corruption on the ROOT fragment's committed output is read by
        the COORDINATOR (no consumer task exists to fail), so recovery runs
        coordinator-side: quarantine + producer re-run, bit-identical."""
        runner = _fte_runner()
        runner.execute(Q13)  # learn the plan's root fragment id
        root_fid = runner.last_fte_root_fid
        with ChaosInjector() as chaos:
            chaos.arm(
                "exchange_corrupt_frame", times=1, match=f"/{root_fid}/p0/"
            )
            rows = runner.execute(Q13).rows
        assert chaos.fired.get("exchange_corrupt_frame") == 1
        assert rows == expected[Q13]
        sched = runner.last_fte_scheduler
        assert sched.stats["corruption_recoveries"] >= 1
        # the root producer re-ran under a NEW attempt number
        assert runner.last_task_attempts[(root_fid, 0)] >= 1

    def test_range_edge_corruption_recovers(self):
        """REPARTITION_RANGE edges are materialized by the COORDINATOR (the
        one exchange kind it still reads, for global quantile cuts) — same
        coordinator-side recovery contract as the root output."""
        runner = _fte_runner()
        sql = ("SELECT o_orderkey, o_totalprice FROM orders "
               "ORDER BY o_totalprice DESC, o_orderkey")
        want = runner.execute(sql).rows
        assert runner.fte_coordinator_payload_bytes > 0  # range edge exists
        with ChaosInjector() as chaos:
            chaos.arm("exchange_corrupt_frame", times=1)
            got = runner.execute(sql).rows
        assert chaos.fired.get("exchange_corrupt_frame") == 1
        assert got == want
        assert runner.last_fte_scheduler.stats["corruption_recoveries"] >= 1

    def test_user_error_fails_fast_zero_retries(self):
        """Acceptance: zero retries consumed by an injected USER-category
        error — re-running a semantically wrong query cannot succeed."""
        runner = _fte_runner()
        before = _retries_counter().value
        with ChaosInjector() as chaos:
            chaos.arm("task_crash_mid_execute", times=1, category="USER")
            with pytest.raises(InjectedFailure):
                runner.execute(Q3)
        assert chaos.fired.get("task_crash_mid_execute") == 1
        sched = runner.last_fte_scheduler
        assert sched.stats["retries"] == 0
        assert sched.stats["user_failures"] == 1
        assert _retries_counter().value == before
        # no task ever went past attempt 0
        assert set(runner.last_task_attempts.values()) == {0}

    def test_stage_tasks_dispatch_concurrently(self, expected):
        """Acceptance: >= 2 task attempts in flight at once, proven by
        flight-recorder span overlap (the round-5 loop ran one at a time)."""
        runner = _fte_runner()
        RECORDER.clear()
        RECORDER.enable()
        try:
            rows = runner.execute(Q3).rows
        finally:
            RECORDER.disable()
        assert rows == expected[Q3]
        events = RECORDER.chrome_trace()["traceEvents"]
        RECORDER.clear()
        spans = []
        open_by_tid = {}
        for ev in events:
            if ev.get("name") != "task_attempt":
                continue
            if ev["ph"] == "B":
                open_by_tid.setdefault(ev["tid"], []).append(ev["ts"])
            elif ev["ph"] == "E":
                start = open_by_tid.get(ev["tid"], [None]).pop()
                if start is not None:
                    spans.append((start, ev["ts"]))
        assert len(spans) >= 2, "expected multiple task_attempt spans"
        overlaps = sum(
            1
            for i, (s1, e1) in enumerate(spans)
            for (s2, e2) in spans[i + 1:]
            if s1 < e2 and s2 < e1
        )
        assert overlaps >= 1, f"no overlapping task attempts in {spans}"

    def test_speculative_attempt_rescues_straggler(self, expected):
        """A stalled task past the percentile threshold gets a speculative
        sibling; the first durable commit wins and results stay exact."""
        runner = _fte_runner()
        runner.session.set("fte_speculation_min_secs", 0.3)
        runner.session.set("fte_speculation_quantile", 0.0)
        runner.session.set("fte_speculation_multiplier", 1.0)
        spec_counter = REGISTRY.counter(
            "trino_tpu_speculative_attempts_total",
            help="speculative FTE task attempts launched for stragglers",
        )
        before = spec_counter.value
        with ChaosInjector() as chaos:
            # stall ONE first-attempt task long enough to trip the
            # straggler threshold derived from its siblings' durations
            chaos.arm("task_stall", times=1, match="_p0_a0", delay=6.0)
            rows = runner.execute(Q3).rows
        assert chaos.fired.get("task_stall") == 1
        assert rows == expected[Q3]
        sched = runner.last_fte_scheduler
        assert sched.stats["speculative"] >= 1
        assert spec_counter.value > before
        # drain the abandoned stalled sibling: its daemon thread wakes after
        # the stall and would emit task_attempt flight spans into a LATER
        # test's recorder window (unpaired/non-monotonic smoke flakes)
        import threading

        deadline = time.time() + 30
        for t in threading.enumerate():
            if t.name.startswith("fte-") and t is not threading.current_thread():
                t.join(max(0.0, deadline - time.time()))

    def test_attempts_visible_in_system_catalog(self):
        """The scheduler's attempt history is SQL-queryable
        (system.runtime.task_attempts), failed and ok outcomes both."""
        from trino_tpu.runtime import LocalQueryRunner

        runner = _fte_runner()
        with ChaosInjector() as chaos:
            chaos.arm("task_crash_mid_execute", times=1)
            runner.execute(Q3)
        local = LocalQueryRunner.tpch(scale=SCALE)
        res = local.execute(
            "SELECT outcome, count(*) FROM system.runtime.task_attempts "
            "GROUP BY 1"
        )
        outcomes = dict(res.rows)
        assert outcomes.get("ok", 0) >= 1
        assert outcomes.get("failed", 0) >= 1


class TestSchedulerBudget:
    def test_speculative_failure_never_burns_primary_budget(self):
        """Ordering regression: primary fails first (deferring to its live
        speculative sibling), then the sibling fails — the task must still
        have a real retry left, not die with zero genuine retries."""
        from trino_tpu.runtime.fte_scheduler import (
            EventDrivenFteScheduler,
            TaskSpec,
            _Attempt,
            _TaskState,
        )

        sched = EventDrivenFteScheduler(
            workers=[], session=Session(catalog="tpch", schema="sf0_0005")
        )
        key = (0, 0)
        spec = TaskSpec(0, 0, lambda a, w, d: None)
        sched._specs[key] = spec
        state = _TaskState(spec)
        sched._states[key] = state
        primary = _Attempt(key, 0, None, None, speculative=False)
        sibling = _Attempt(key, 1, None, None, speculative=True)
        state.live = {1: sibling}  # the sibling is live as the primary fails
        assert sched._handle_failure(
            primary, RuntimeError("boom"), ErrorCategory.INTERNAL
        ) is None
        assert state.failures == 1  # real failure counted, retry deferred
        state.live = {}
        # the speculative sibling now fails too: no budget burned, a REAL
        # retry gets scheduled instead of the query dying
        assert sched._handle_failure(
            sibling, RuntimeError("boom"), ErrorCategory.INTERNAL
        ) is None
        assert state.failures == 1
        assert sched._retry_heap, "no retry scheduled after sibling failure"


def _worker_catalogs():
    c = CatalogManager()
    c.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return c


def _remote_runner(urls, n_workers=3):
    dist = DistributedQueryRunner(
        Session(catalog="tpch", schema="sf0_0005"),
        n_workers=n_workers,
        worker_urls=urls,
        secret=SECRET,
    )
    dist.catalogs.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    dist.session.set("retry_policy", "TASK")
    return dist


class TestChaosRemoteTransport:
    """Transport-layer injection sites over real WorkerServers: refused and
    hung RPCs must cost one classified task retry on a surviving worker."""

    def test_refused_rpc_retries_on_survivor(self, expected):
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _remote_runner([f"http://{w.address}" for w in ws], n_workers=2)
            want = dist.execute(Q13).rows  # no-fault remote baseline (warm)
            with ChaosInjector() as chaos:
                # drop the first task-creation POST unanswered: the
                # coordinator sees a connection reset, exactly like a
                # worker crashing mid-task
                chaos.arm("transport_refuse", times=1, match="_p0_a0")
                rows = dist.execute(Q13).rows
            assert chaos.fired.get("transport_refuse") == 1
            assert rows == want == expected[Q13]
            sched = dist.last_fte_scheduler
            assert sched.stats["retries"] >= 1
            # EXTERNAL failure -> the node sat out on the blacklist
            assert sched.blacklist.blacklisted_total >= 1
        finally:
            for w in ws:
                w.stop()

    def test_hung_rpc_deadline_bounded_and_retried(self, expected):
        """satellite: the completion wait is BOUNDED — a worker that hangs
        mid-RPC fails the ATTEMPT at task_completion_timeout instead of
        stalling the query forever."""
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _remote_runner([f"http://{w.address}" for w in ws], n_workers=2)
            dist.execute(Q13)  # warm worker-side compiles first
            dist.session.set("task_completion_timeout", 6.0)
            dist.session.set("task_retry_attempts", 4)
            with ChaosInjector() as chaos:
                chaos.arm("transport_hang", times=1, match="_p0_a0", delay=60.0)
                t0 = time.monotonic()
                rows = dist.execute(Q13).rows
                elapsed = time.monotonic() - t0
            assert chaos.fired.get("transport_hang") == 1
            assert rows == expected[Q13]
            assert elapsed < 50, "query waited for the hung RPC"
            sched = dist.last_fte_scheduler
            assert sched.stats["retries"] >= 1
        finally:
            for w in ws:
                w.stop()


class TestFteSmokeCheck:
    """The tier-1 FTE smoke check (satellite: CI/tooling) — a distributed
    query under injected worker failure leaves paired/monotonic
    ``task_attempt`` flight spans with outcome labels and incremented
    retry metrics."""

    def test_fte_smoke_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke_fte", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_fte_smoke() == []


class TestZombieCommit:
    def test_zombie_commit_after_sweep_stays_rejected(self, tmp_path):
        """A task attempt committing AFTER the query's exchange sweep must
        observe the tombstone and abort — never resurrect the directory
        (exchange_spi ZombieCommit path)."""
        from trino_tpu.runtime.exchange_spi import (
            ExchangeManager,
            QueryExchangeRemoved,
        )

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("qz", 0)
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"frame-bytes", rows=1)
        mgr.remove_query("qz")  # sweep lands before the commit
        with pytest.raises(QueryExchangeRemoved):
            sink.commit()
        assert ex.committed_parts_attempt(0) is None

    def test_torn_commit_leaves_attempt_invisible(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("qt", 0)
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"frame-bytes", rows=1)
        with ChaosInjector() as chaos:
            chaos.arm("exchange_torn_commit", times=1)
            with pytest.raises(InjectedFailure):
                sink.commit()
        assert ex.committed_parts_attempt(0) is None
        # the retry commits cleanly under a NEW attempt number; the torn
        # tmpdir stays invisible until query-end sweep
        retry = ex.part_sink(0, 1)
        retry.add_part(0, b"frame-bytes", rows=1)
        retry.commit()
        assert ex.committed_parts_attempt(0) == 1

    def test_quarantined_attempt_loses_first_committed_wins(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("qq", 0)
        s0 = ex.part_sink(0, 0)
        s0.add_part(0, b"corrupt", rows=1)
        s0.commit()
        s1 = ex.part_sink(0, 1)
        s1.add_part(0, b"fresh", rows=1)
        s1.commit()
        assert ex.committed_parts_attempt(0) == 0  # first committed wins...
        assert ex.quarantine_attempt(0, 0)
        assert ex.committed_parts_attempt(0) == 1  # ...until quarantined

    def test_quarantine_racing_reader_surfaces_corruption(self, tmp_path):
        """A consumer that selected attempt N just before a sibling
        quarantined it must see CORRUPTION (and recover onto the fresh
        attempt) — NOT the 'missing part = no rows' convention, which
        would durably commit a wrong result."""
        from trino_tpu.runtime.exchange_spi import (
            ExchangeDataCorruption,
            ExchangeManager,
        )

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("qr", 0)
        s0 = ex.part_sink(0, 0)
        s0.add_part(0, b"frame-bytes", rows=1)
        s0.commit()
        # freeze the selection this reader made, then quarantine behind its
        # back (the rename racing a concurrent consumer mid-stage)
        ex.committed_parts_attempt = lambda p: 0
        assert ex.quarantine_attempt(0, 0)
        with pytest.raises(ExchangeDataCorruption):
            ex.source_part(0, 0)

    def test_missing_part_with_live_attempt_is_still_empty(self, tmp_path):
        """Control: with the attempt dir PRESENT, a missing part file keeps
        meaning 'this consumer part got no rows' (empty parts are skipped
        at write time)."""
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("qe", 0)
        s0 = ex.part_sink(0, 0)
        s0.add_part(0, b"frame-bytes", rows=1)  # part 1 never written
        s0.commit()
        assert ex.source_part(0, 1) == []
