"""Canonical-text TPC-H queries (the ones round 1 carried as shapes or not at
all): Q2, Q8, Q19, Q20, Q21, Q22 — full fidelity vs vectorized pandas oracles.

Query texts follow the canonical forms in the reference's benchmark SQL
(testing/trino-benchmark-queries/src/main/resources/sql/trino/tpch/), with the
standard substitution parameters.
"""

import datetime

import numpy as np
import pandas as pd
import pytest

from tests.oracle import tpch_df, assert_rows_equal

SCALE = 0.004  # >= 25 suppliers so every nation (SAUDI ARABIA, CANADA) exists
EPOCH = datetime.date(1970, 1, 1)


def days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso) - EPOCH).days


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def test_q2(runner):
    res = runner.execute(
        """
        SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        FROM part, supplier, partsupp, nation, region
        WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
          AND p_size = 25 AND p_type LIKE '%BRASS'
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'EUROPE'
          AND ps_supplycost = (
              SELECT min(ps_supplycost)
              FROM partsupp, supplier, nation, region
              WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
                AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
                AND r_name = 'EUROPE')
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey LIMIT 100
        """
    )
    p = tpch_df("part", SCALE)
    s = tpch_df("supplier", SCALE)
    ps = tpch_df("partsupp", SCALE)
    n = tpch_df("nation", SCALE)
    r = tpch_df("region", SCALE)
    eu_nations = n.merge(r[r.r_name == "EUROPE"], left_on="n_regionkey", right_on="r_regionkey")
    eu_supp = s[s.s_nationkey.isin(eu_nations.n_nationkey)]
    ps_eu = ps[ps.ps_suppkey.isin(eu_supp.s_suppkey)]
    min_cost = ps_eu.groupby("ps_partkey")["ps_supplycost"].min()
    m = (
        ps_eu.merge(p[(p.p_size == 25) & p.p_type.str.endswith("BRASS")],
                    left_on="ps_partkey", right_on="p_partkey")
        .merge(eu_supp, left_on="ps_suppkey", right_on="s_suppkey")
        .merge(eu_nations[["n_nationkey", "n_name"]], left_on="s_nationkey",
               right_on="n_nationkey")
    )
    m = m[m.ps_supplycost == m.ps_partkey.map(min_cost)]
    exp = m.sort_values(
        ["s_acctbal", "n_name", "s_name", "p_partkey"],
        ascending=[False, True, True, True],
    ).head(100)
    assert_rows_equal(
        res.rows,
        [
            (x.s_acctbal, x.s_name, x.n_name, int(x.p_partkey), x.p_mfgr,
             x.s_address, x.s_phone, x.s_comment)
            for x in exp.itertuples()
        ],
        float_tol=1e-9,
    )
    assert len(res.rows) > 0, "parameter choice must produce rows at this scale"


def test_q8(runner):
    res = runner.execute(
        """
        SELECT o_year,
               sum(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
                 / sum(volume) AS mkt_share
        FROM (SELECT extract(YEAR FROM o_orderdate) AS o_year,
                     l_extendedprice * (1 - l_discount) AS volume,
                     n2.n_name AS nation
              FROM part, supplier, lineitem, orders, customer,
                   nation n1, nation n2, region
              WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey
                AND l_orderkey = o_orderkey AND o_custkey = c_custkey
                AND c_nationkey = n1.n_nationkey
                AND n1.n_regionkey = r_regionkey AND r_name = 'AMERICA'
                AND s_nationkey = n2.n_nationkey
                AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
                AND p_type = 'ECONOMY ANODIZED STEEL') AS all_nations
        GROUP BY o_year ORDER BY o_year
        """
    )
    p = tpch_df("part", SCALE)
    s = tpch_df("supplier", SCALE)
    li = tpch_df("lineitem", SCALE)
    o = tpch_df("orders", SCALE)
    c = tpch_df("customer", SCALE)
    n = tpch_df("nation", SCALE)
    r = tpch_df("region", SCALE)
    am = n.merge(r[r.r_name == "AMERICA"], left_on="n_regionkey", right_on="r_regionkey")
    m = (
        li.merge(p[p.p_type == "ECONOMY ANODIZED STEEL"], left_on="l_partkey",
                 right_on="p_partkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    )
    m = m[m.c_nationkey.isin(am.n_nationkey)]
    m = m[(m.o_orderdate >= days("1995-01-01")) & (m.o_orderdate <= days("1996-12-31"))]
    m = m.merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey", right_on="n_nationkey")
    m["o_year"] = ((pd.to_datetime(m.o_orderdate, unit="D")).dt.year).astype(int)
    m["volume"] = m.l_extendedprice * (1 - m.l_discount)
    m["brazil"] = np.where(m.n_name == "BRAZIL", m.volume, 0.0)
    g = m.groupby("o_year").agg(num=("brazil", "sum"), den=("volume", "sum"))
    exp = [(int(y), row.num / row.den) for y, row in g.sort_index().iterrows()]
    assert_rows_equal(res.rows, exp, float_tol=1e-9)
    assert len(res.rows) > 0


def test_q19(runner):
    res = runner.execute(
        """
        SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND ((p_brand = 'Brand#12'
                AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
                AND l_quantity >= 1 AND l_quantity <= 1 + 10
                AND p_size BETWEEN 1 AND 5
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON')
            OR (p_brand = 'Brand#23'
                AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
                AND l_quantity >= 10 AND l_quantity <= 10 + 10
                AND p_size BETWEEN 1 AND 10
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON')
            OR (p_brand = 'Brand#34'
                AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
                AND l_quantity >= 20 AND l_quantity <= 20 + 10
                AND p_size BETWEEN 1 AND 15
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON'))
        """
    )
    li = tpch_df("lineitem", SCALE)
    p = tpch_df("part", SCALE)
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    base = m.l_shipmode.isin(["AIR", "AIR REG"]) & (m.l_shipinstruct == "DELIVER IN PERSON")
    c1 = (
        (m.p_brand == "Brand#12")
        & m.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (m.l_quantity >= 1) & (m.l_quantity <= 11)
        & m.p_size.between(1, 5)
    )
    c2 = (
        (m.p_brand == "Brand#23")
        & m.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (m.l_quantity >= 10) & (m.l_quantity <= 20)
        & m.p_size.between(1, 10)
    )
    c3 = (
        (m.p_brand == "Brand#34")
        & m.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (m.l_quantity >= 20) & (m.l_quantity <= 30)
        & m.p_size.between(1, 15)
    )
    sel = m[base & (c1 | c2 | c3)]
    expected = (sel.l_extendedprice * (1 - sel.l_discount)).sum()
    got = res.rows[0][0]
    if len(sel) == 0:
        assert got is None
    else:
        assert abs(got - expected) <= 1e-9 * max(1.0, abs(expected))


def test_q20(runner):
    res = runner.execute(
        """
        SELECT s_name, s_address FROM supplier, nation
        WHERE s_suppkey IN (
            SELECT ps_suppkey FROM partsupp
            WHERE ps_partkey IN (SELECT p_partkey FROM part
                                 WHERE p_name LIKE 'forest%')
              AND ps_availqty > (
                  SELECT 0.5 * sum(l_quantity) FROM lineitem
                  WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
                    AND l_shipdate >= DATE '1994-01-01'
                    AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR))
          AND s_nationkey = n_nationkey AND n_name = 'CANADA'
        ORDER BY s_name
        """
    )
    s = tpch_df("supplier", SCALE)
    n = tpch_df("nation", SCALE)
    ps = tpch_df("partsupp", SCALE)
    p = tpch_df("part", SCALE)
    li = tpch_df("lineitem", SCALE)
    forest = set(p[p.p_name.str.startswith("forest")].p_partkey)
    lw = li[(li.l_shipdate >= days("1994-01-01")) & (li.l_shipdate < days("1995-01-01"))]
    half = lw.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum() * 0.5
    psf = ps[ps.ps_partkey.isin(forest)].copy()
    psf["thresh"] = [
        half.get((pk, sk), np.nan) for pk, sk in zip(psf.ps_partkey, psf.ps_suppkey)
    ]
    # NULL threshold (no matching lineitem) -> comparison is NULL -> excluded
    keep = psf[psf.ps_availqty > psf.thresh]
    suppkeys = set(keep.ps_suppkey)
    canada = n[n.n_name == "CANADA"]
    sel = s[s.s_nationkey.isin(canada.n_nationkey) & s.s_suppkey.isin(suppkeys)]
    exp = sel.sort_values("s_name")
    assert_rows_equal(
        res.rows, [(x.s_name, x.s_address) for x in exp.itertuples()]
    )


def test_q21(runner):
    res = runner.execute(
        """
        SELECT s_name, count(*) AS numwait
        FROM supplier, lineitem l1, orders, nation
        WHERE s_suppkey = l1.l_suppkey AND o_orderkey = l1.l_orderkey
          AND o_orderstatus = 'F' AND l1.l_receiptdate > l1.l_commitdate
          AND EXISTS (SELECT * FROM lineitem l2
                      WHERE l2.l_orderkey = l1.l_orderkey
                        AND l2.l_suppkey <> l1.l_suppkey)
          AND NOT EXISTS (SELECT * FROM lineitem l3
                          WHERE l3.l_orderkey = l1.l_orderkey
                            AND l3.l_suppkey <> l1.l_suppkey
                            AND l3.l_receiptdate > l3.l_commitdate)
          AND s_nationkey = n_nationkey AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name ORDER BY numwait DESC, s_name LIMIT 100
        """
    )
    s = tpch_df("supplier", SCALE)
    li = tpch_df("lineitem", SCALE)
    o = tpch_df("orders", SCALE)
    n = tpch_df("nation", SCALE)
    m = (
        li.merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o[o.o_orderstatus == "F"], left_on="l_orderkey", right_on="o_orderkey")
        .merge(n[n.n_name == "SAUDI ARABIA"], left_on="s_nationkey",
               right_on="n_nationkey")
    )
    m = m[m.l_receiptdate > m.l_commitdate]
    # EXISTS other-supplier row in the order: per-order min/max suppkey differs
    g_all = li.groupby("l_orderkey")["l_suppkey"].agg(["min", "max"])
    exists1 = (m.l_orderkey.map(g_all["min"]) != m.l_suppkey) | (
        m.l_orderkey.map(g_all["max"]) != m.l_suppkey
    )
    late = li[li.l_receiptdate > li.l_commitdate]
    g_late = late.groupby("l_orderkey")["l_suppkey"].agg(["min", "max"])
    mn = m.l_orderkey.map(g_late["min"])
    mx = m.l_orderkey.map(g_late["max"])
    exists2 = ((mn != m.l_suppkey) | (mx != m.l_suppkey)) & mn.notna()
    sel = m[exists1 & ~exists2]
    exp = (
        sel.groupby("s_name").size().reset_index(name="numwait")
        .sort_values(["numwait", "s_name"], ascending=[False, True]).head(100)
    )
    assert_rows_equal(
        res.rows, [(x.s_name, int(x.numwait)) for x in exp.itertuples()]
    )
    assert len(res.rows) > 0


def test_q22(runner):
    res = runner.execute(
        """
        SELECT cntrycode, count(*) AS numcust, sum(acctbal) AS totacctbal
        FROM (SELECT substr(c_phone, 1, 2) AS cntrycode, c_acctbal AS acctbal
              FROM customer
              WHERE substr(c_phone, 1, 2) IN ('13', '31', '23', '29', '30', '18', '17')
                AND c_acctbal > (SELECT avg(c_acctbal) FROM customer
                                 WHERE c_acctbal > 0.00
                                   AND substr(c_phone, 1, 2) IN
                                       ('13', '31', '23', '29', '30', '18', '17'))
                AND NOT EXISTS (SELECT * FROM orders
                                WHERE o_custkey = c_custkey)) AS custsale
        GROUP BY cntrycode ORDER BY cntrycode
        """
    )
    c = tpch_df("customer", SCALE)
    o = tpch_df("orders", SCALE)
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c[c.c_phone.str[:2].isin(codes)]
    avg_bal = cc[cc.c_acctbal > 0].c_acctbal.mean()
    has_order = set(o.o_custkey)
    sel = cc[(cc.c_acctbal > avg_bal) & ~cc.c_custkey.isin(has_order)].copy()
    sel["cntrycode"] = sel.c_phone.str[:2]
    g = sel.groupby("cntrycode").agg(numcust=("c_custkey", "count"),
                                     tot=("c_acctbal", "sum"))
    exp = [(i, int(r.numcust), r.tot) for i, r in g.sort_index().iterrows()]
    assert_rows_equal(res.rows, exp, float_tol=1e-9)
    assert len(res.rows) > 0
