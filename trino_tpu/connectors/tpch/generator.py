"""Deterministic TPC-H data generator (numpy, split-addressable).

Reference blueprint: plugin/trino-tpch (TpchConnectorFactory.java:30,
TpchPageSourceProvider.java:53 — "generates TPC-H data on the fly"). Like the
reference, data is generated deterministically per split so any worker can
produce any split without coordination; unlike dbgen we generate *dictionary
codes directly* (no string materialization on the generation path) — string
columns draw from fixed sorted vocabularies, so the device only ever sees int32
codes and generation is pure vectorized numpy.

Distributions follow dbgen's shapes (date ranges, returnflag/linestatus rules,
1..7 lineitems per order, discount 0..0.10, ...) but are not bit-identical to
dbgen; correctness tests compare against a pandas oracle over the same data.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

EPOCH = datetime.date(1970, 1, 1)


def _days(y: int, m: int, d: int) -> int:
    return (datetime.date(y, m, d) - EPOCH).days


MIN_ORDER_DATE = _days(1992, 1, 1)
MAX_ORDER_DATE = _days(1998, 8, 2)
CURRENT_DATE = _days(1995, 6, 17)  # dbgen's CURRENTDATE used for flags

# ---------------------------------------------------------------------------- #
# Vocabularies (sorted! — code order must equal string order)
# ---------------------------------------------------------------------------- #

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

NATIONS = [
    # (name, regionkey) — dbgen's 25 nations
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("CHINA", 2),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2),
    ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4),
    ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1),
    ("ROMANIA", 3), ("RUSSIA", 3), ("SAUDI ARABIA", 4), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1), ("VIETNAM", 2),
]

SEGMENTS = sorted(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"])
PRIORITIES = sorted(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"])
SHIP_MODES = sorted(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"])
SHIP_INSTRUCTS = sorted(["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"])
ORDER_STATUS = ["F", "O", "P"]
RETURN_FLAGS = ["A", "N", "R"]
LINE_STATUS = ["F", "O"]

TYPE_SYLL1 = ["ECONOMY", "LARGE", "MEDIUM", "PROMO", "SMALL", "STANDARD"]
TYPE_SYLL2 = ["ANODIZED", "BRUSHED", "BURNISHED", "PLATED", "POLISHED"]
TYPE_SYLL3 = ["BRASS", "COPPER", "NICKEL", "STEEL", "TIN"]
PART_TYPES = sorted(f"{a} {b} {c}" for a in TYPE_SYLL1 for b in TYPE_SYLL2 for c in TYPE_SYLL3)

CONTAINER_SYLL1 = ["JUMBO", "LG", "MED", "SM", "WRAP"]
CONTAINER_SYLL2 = ["BAG", "BOX", "CAN", "CASE", "DRUM", "JAR", "PACK", "PKG"]
CONTAINERS = sorted(f"{a} {b}" for a in CONTAINER_SYLL1 for b in CONTAINER_SYLL2)

BRANDS = sorted(f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6))
MFGRS = sorted(f"Manufacturer#{i}" for i in range(1, 6))

COLORS = sorted(
    """almond antique aquamarine azure beige bisque black blanched blue blush brown
    burlywood burnished chartreuse chiffon chocolate coral cornflower cornsilk cream
    cyan dark deep dim dodger drab firebrick floral forest frosted gainsboro ghost
    goldenrod green grey honeydew hot indian ivory khaki lace lavender lawn lemon
    light lime linen magenta maroon medium metallic midnight mint misty moccasin
    navajo navy olive orange orchid pale papaya peach peru pink plum powder puff
    purple red rose rosy royal saddle salmon sandy seashell sienna sky slate smoke
    snow spring steel tan thistle tomato turquoise violet wheat white yellow""".split()
)

# comment vocab: bounded pools so dictionaries stay small (see module docstring)
_COMMENT_WORDS = [
    "carefully", "quickly", "slyly", "furiously", "blithely", "silent", "final",
    "ironic", "pending", "regular", "express", "special", "unusual", "even", "bold",
    "requests", "deposits", "packages", "instructions", "accounts", "theodolites",
    "foxes", "pinto", "beans", "dependencies", "excuses", "platelets", "asymptotes",
    "courts", "dolphins", "multipliers", "sauternes", "warhorses", "sheaves",
]


def _make_comments(rng: np.random.Generator, count: int) -> List[str]:
    words = rng.choice(_COMMENT_WORDS, size=(count, 4))
    return [" ".join(row) for row in words]


# pre-built comment pools (deterministic, shared by all scale factors)
_POOL_RNG = np.random.default_rng(20260728)
COMMENT_POOL = sorted(set(_make_comments(_POOL_RNG, 2000)))
PART_NAME_POOL = sorted(
    {" ".join(_POOL_RNG.choice(COLORS, size=5)) for _ in range(2000)}
)


@dataclass(frozen=True)
class TpchColumn:
    name: str
    type_name: str  # parsed by spi.types.parse_type
    vocab: Optional[Tuple[str, ...]] = None  # for varchar columns


def _v(words) -> Tuple[str, ...]:
    return tuple(words)


TPCH_TABLES: Dict[str, List[TpchColumn]] = {
    "region": [
        TpchColumn("r_regionkey", "bigint"),
        TpchColumn("r_name", "varchar(25)", _v(REGIONS)),
        TpchColumn("r_comment", "varchar(152)", _v(COMMENT_POOL)),
    ],
    "nation": [
        TpchColumn("n_nationkey", "bigint"),
        TpchColumn("n_name", "varchar(25)", _v(sorted(n for n, _ in NATIONS))),
        TpchColumn("n_regionkey", "bigint"),
        TpchColumn("n_comment", "varchar(152)", _v(COMMENT_POOL)),
    ],
    "supplier": [
        TpchColumn("s_suppkey", "bigint"),
        TpchColumn("s_name", "varchar(25)", None),  # synthesized numbered names
        TpchColumn("s_address", "varchar(40)", _v(COMMENT_POOL)),
        TpchColumn("s_nationkey", "bigint"),
        TpchColumn("s_phone", "varchar(15)", None),
        TpchColumn("s_acctbal", "decimal(12,2)"),
        TpchColumn("s_comment", "varchar(101)", _v(COMMENT_POOL)),
    ],
    "customer": [
        TpchColumn("c_custkey", "bigint"),
        TpchColumn("c_name", "varchar(25)", None),
        TpchColumn("c_address", "varchar(40)", _v(COMMENT_POOL)),
        TpchColumn("c_nationkey", "bigint"),
        TpchColumn("c_phone", "varchar(15)", None),
        TpchColumn("c_acctbal", "decimal(12,2)"),
        TpchColumn("c_mktsegment", "varchar(10)", _v(SEGMENTS)),
        TpchColumn("c_comment", "varchar(117)", _v(COMMENT_POOL)),
    ],
    "part": [
        TpchColumn("p_partkey", "bigint"),
        TpchColumn("p_name", "varchar(55)", _v(PART_NAME_POOL)),
        TpchColumn("p_mfgr", "varchar(25)", _v(MFGRS)),
        TpchColumn("p_brand", "varchar(10)", _v(BRANDS)),
        TpchColumn("p_type", "varchar(25)", _v(PART_TYPES)),
        TpchColumn("p_size", "integer"),
        TpchColumn("p_container", "varchar(10)", _v(CONTAINERS)),
        TpchColumn("p_retailprice", "decimal(12,2)"),
        TpchColumn("p_comment", "varchar(23)", _v(COMMENT_POOL)),
    ],
    "partsupp": [
        TpchColumn("ps_partkey", "bigint"),
        TpchColumn("ps_suppkey", "bigint"),
        TpchColumn("ps_availqty", "integer"),
        TpchColumn("ps_supplycost", "decimal(12,2)"),
        TpchColumn("ps_comment", "varchar(199)", _v(COMMENT_POOL)),
    ],
    "orders": [
        TpchColumn("o_orderkey", "bigint"),
        TpchColumn("o_custkey", "bigint"),
        TpchColumn("o_orderstatus", "varchar(1)", _v(ORDER_STATUS)),
        TpchColumn("o_totalprice", "decimal(12,2)"),
        TpchColumn("o_orderdate", "date"),
        TpchColumn("o_orderpriority", "varchar(15)", _v(PRIORITIES)),
        TpchColumn("o_clerk", "varchar(15)", None),
        TpchColumn("o_shippriority", "integer"),
        TpchColumn("o_comment", "varchar(79)", _v(COMMENT_POOL)),
    ],
    "lineitem": [
        TpchColumn("l_orderkey", "bigint"),
        TpchColumn("l_partkey", "bigint"),
        TpchColumn("l_suppkey", "bigint"),
        TpchColumn("l_linenumber", "integer"),
        TpchColumn("l_quantity", "decimal(12,2)"),
        TpchColumn("l_extendedprice", "decimal(12,2)"),
        TpchColumn("l_discount", "decimal(12,2)"),
        TpchColumn("l_tax", "decimal(12,2)"),
        TpchColumn("l_returnflag", "varchar(1)", _v(RETURN_FLAGS)),
        TpchColumn("l_linestatus", "varchar(1)", _v(LINE_STATUS)),
        TpchColumn("l_shipdate", "date"),
        TpchColumn("l_commitdate", "date"),
        TpchColumn("l_receiptdate", "date"),
        TpchColumn("l_shipinstruct", "varchar(25)", _v(SHIP_INSTRUCTS)),
        TpchColumn("l_shipmode", "varchar(10)", _v(SHIP_MODES)),
        TpchColumn("l_comment", "varchar(44)", _v(COMMENT_POOL)),
    ],
}

BASE_ROW_COUNTS = {
    "region": 5,
    "nation": 25,
    "supplier": 10_000,
    "customer": 150_000,
    "part": 200_000,
    "partsupp": 800_000,
    "orders": 1_500_000,
    "lineitem": None,  # derived from orders (avg 4 per order)
}

MAX_LINES_PER_ORDER = 7


def row_count(table: str, scale: float) -> int:
    if table in ("region", "nation"):
        return BASE_ROW_COUNTS[table]
    if table == "lineitem":
        # upper bound; exact count is data-dependent (orders x 1..7)
        raise ValueError("lineitem row count is derived; use order count")
    return max(1, int(BASE_ROW_COUNTS[table] * scale))


def canonical_chunk_rows(total_rows: int) -> int:
    """Generation chunk size: the table's content is defined per canonical
    chunk (seeded by chunk index), NEVER per split — so the data is identical
    under any split layout (split = a contiguous range of chunks). Small scales
    get ~64 chunks for scheduling parallelism; large scales cap chunk size."""
    return int(min(max(total_rows // 64, 64), 262_144))


def chunk_range_for_split(total_rows: int, split: int, total_splits: int):
    """(first_chunk, end_chunk, chunk_rows, n_chunks) for a split."""
    chunk = canonical_chunk_rows(total_rows)
    n_chunks = (total_rows + chunk - 1) // chunk
    first = (n_chunks * split) // total_splits
    end = (n_chunks * (split + 1)) // total_splits
    return first, end, chunk, n_chunks


def _rng(table: str, scale: float, chunk: int) -> np.random.Generator:
    # stable across processes (Python's builtin hash() is salted per process)
    import hashlib

    key = f"{table}:{round(scale * 1_000_000)}:{chunk}".encode()
    seed = int.from_bytes(hashlib.blake2s(key, digest_size=8).digest(), "little")
    return np.random.default_rng(seed)


def _retail_price(partkey: np.ndarray) -> np.ndarray:
    """dbgen's retail price formula, in cents."""
    return 90000 + ((partkey // 10) % 20001) + 100 * (partkey % 1000)


def _numbered_vocab(prefix: str, count: int, width: int = 9) -> List[str]:
    return [f"{prefix}{i:0{width}d}" for i in range(1, count + 1)]


class TpchTableData:
    """Columnar numpy arrays for one split of one table (codes for varchars)."""

    def __init__(self, columns: Dict[str, np.ndarray], count: int):
        self.columns = columns
        self.count = count


def generate_split(
    table: str, scale: float, split: int, total_splits: int
) -> TpchTableData:
    """Rows of ``table`` belonging to ``split``: the concatenation of the
    split's canonical chunks (deterministic, independent of split layout)."""
    if table == "lineitem":
        return _gen_lineitem(scale, split, total_splits)
    n = row_count(table, scale)
    first, end_chunk, chunk, _ = chunk_range_for_split(n, split, total_splits)
    gen = {
        "region": _gen_region,
        "nation": _gen_nation,
        "supplier": _gen_supplier,
        "customer": _gen_customer,
        "part": _gen_part,
        "partsupp": _gen_partsupp,
        "orders": _gen_orders,
    }[table]
    pieces = []
    count = 0
    for c in range(first, end_chunk):
        start = c * chunk
        stop = min((c + 1) * chunk, n)
        keys = np.arange(start + 1, stop + 1, dtype=np.int64)
        rng = _rng(table, scale, c)
        pieces.append(gen(keys, rng, scale))
        count += stop - start
    if not pieces:
        cols = {k: np.zeros(0, dtype=v.dtype) for k, v in gen(
            np.arange(1, 2, dtype=np.int64), _rng(table, scale, 0), scale
        ).items()}
        return TpchTableData(cols, 0)
    cols = {
        k: np.concatenate([p[k] for p in pieces]) for k in pieces[0].keys()
    }
    return TpchTableData(cols, count)


def _comment_codes(rng, n) -> np.ndarray:
    return rng.integers(0, len(COMMENT_POOL), size=n, dtype=np.int32)


def _gen_region(keys, rng, scale):
    return {
        "r_regionkey": np.arange(len(REGIONS), dtype=np.int64)[keys - 1],
        "r_name": np.arange(len(REGIONS), dtype=np.int32)[keys - 1],
        "r_comment": _comment_codes(rng, len(keys)),
    }


def _gen_nation(keys, rng, scale):
    names = sorted(n for n, _ in NATIONS)
    name_code = {n: i for i, n in enumerate(names)}
    codes = np.array([name_code[NATIONS[k - 1][0]] for k in keys], dtype=np.int32)
    regionkeys = np.array([NATIONS[k - 1][1] for k in keys], dtype=np.int64)
    return {
        "n_nationkey": keys - 1,
        "n_name": codes,
        "n_regionkey": regionkeys,
        "n_comment": _comment_codes(rng, len(keys)),
    }


def _phone_codes(keys: np.ndarray, total: int) -> np.ndarray:
    """Codes into the phone vocab: phone = '<10+nation>-<key:011d>' with
    nation = (key-1) % 25 (TPC-H country-code semantics, spec 4.2.2.9), laid
    out class-major so code order == lexicographic order (sorted-dict
    invariant). Class m holds keys {m+1, m+26, ...}."""
    m = (keys - 1) % 25
    counts = np.array([(total - c - 1) // 25 + 1 if c < total else 0 for c in range(25)])
    class_start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    return (class_start[m] + (keys - 1) // 25).astype(np.int32)


def _phone_vocab(total: int) -> List[str]:
    vocab = []
    for m in range(25):
        prefix = 10 + m
        ck = m + 1
        while ck <= total:
            vocab.append(f"{prefix}-{ck:011d}")
            ck += 25
    return vocab


def _gen_supplier(keys, rng, scale):
    n = len(keys)
    total = row_count("supplier", scale)
    return {
        "s_suppkey": keys,
        "s_name": (keys - 1).astype(np.int32),  # code == key-1 into numbered vocab
        "s_address": _comment_codes(rng, n),
        # nation derived from key so the phone country code matches (Q22 shape)
        "s_nationkey": ((keys - 1) % 25).astype(np.int64),
        "s_phone": _phone_codes(keys, total),
        "s_acctbal": rng.integers(-99999, 999999, size=n, dtype=np.int64),
        "s_comment": _comment_codes(rng, n),
    }


def _gen_customer(keys, rng, scale):
    n = len(keys)
    total = row_count("customer", scale)
    return {
        "c_custkey": keys,
        "c_name": (keys - 1).astype(np.int32),
        "c_address": _comment_codes(rng, n),
        "c_nationkey": ((keys - 1) % 25).astype(np.int64),
        "c_phone": _phone_codes(keys, total),
        "c_acctbal": rng.integers(-99999, 999999, size=n, dtype=np.int64),
        "c_mktsegment": rng.integers(0, len(SEGMENTS), size=n, dtype=np.int32),
        "c_comment": _comment_codes(rng, n),
    }


def _gen_part(keys, rng, scale):
    n = len(keys)
    return {
        "p_partkey": keys,
        "p_name": rng.integers(0, len(PART_NAME_POOL), size=n, dtype=np.int32),
        "p_mfgr": ((keys - 1) % 5).astype(np.int32),
        "p_brand": rng.integers(0, len(BRANDS), size=n, dtype=np.int32),
        "p_type": rng.integers(0, len(PART_TYPES), size=n, dtype=np.int32),
        "p_size": rng.integers(1, 51, size=n, dtype=np.int32),
        "p_container": rng.integers(0, len(CONTAINERS), size=n, dtype=np.int32),
        "p_retailprice": _retail_price(keys),
        "p_comment": _comment_codes(rng, n),
    }


def _gen_partsupp(keys, rng, scale):
    n = len(keys)
    num_parts = row_count("part", scale)
    num_supps = row_count("supplier", scale)
    partkeys = (keys - 1) // 4 + 1
    partkeys = np.minimum(partkeys, num_parts)
    return {
        "ps_partkey": partkeys,
        "ps_suppkey": rng.integers(1, num_supps + 1, size=n, dtype=np.int64),
        "ps_availqty": rng.integers(1, 10000, size=n, dtype=np.int32),
        "ps_supplycost": rng.integers(100, 100001, size=n, dtype=np.int64),
        "ps_comment": _comment_codes(rng, n),
    }


def _gen_orders(keys, rng, scale):
    n = len(keys)
    num_cust = row_count("customer", scale)
    dates = rng.integers(MIN_ORDER_DATE, MAX_ORDER_DATE - 121, size=n, dtype=np.int32)
    status_code = np.where(
        dates + 100 < CURRENT_DATE,
        0,  # 'F'
        np.where(dates > CURRENT_DATE, 1, 2),  # 'O' / 'P'
    ).astype(np.int32)
    # spec 4.2.3: o_custkey skips custkey % 3 == 0 — one third of customers
    # never place orders (the population Q13/Q22 depend on). The i-th valid
    # key (0-based, skipping multiples of 3) is 3*(i//2) + i%2 + 1.
    num_valid = num_cust - num_cust // 3
    i = rng.integers(0, max(num_valid, 1), size=n, dtype=np.int64)
    custkeys = 3 * (i // 2) + (i % 2) + 1
    return {
        "o_orderkey": keys,
        "o_custkey": custkeys,
        "o_orderstatus": status_code,
        "o_totalprice": rng.integers(90000, 55555500, size=n, dtype=np.int64),
        "o_orderdate": dates,
        "o_orderpriority": rng.integers(0, len(PRIORITIES), size=n, dtype=np.int32),
        "o_clerk": rng.integers(0, max(1, int(1000 * scale)), size=n).astype(np.int32),
        "o_shippriority": np.zeros(n, dtype=np.int32),
        "o_comment": _comment_codes(rng, n),
    }


def lineitem_split_rows(scale: float, split: int, total_splits: int) -> int:
    """Exact lineitem row count of a split without generating the columns
    (draws only lines_per_order — the first draw of each chunk's rng stream)."""
    num_orders = row_count("orders", scale)
    first, end_chunk, chunk, _ = chunk_range_for_split(num_orders, split, total_splits)
    total = 0
    for c in range(first, end_chunk):
        start = c * chunk
        stop = min((c + 1) * chunk, num_orders)
        rng = _rng("lineitem", scale, c)
        total += int(rng.integers(1, MAX_LINES_PER_ORDER + 1, size=stop - start).sum())
    return total


def _gen_lineitem(scale: float, split: int, total_splits: int) -> TpchTableData:
    """Lineitems of the split's canonical chunks (consistent with _gen_orders)."""
    num_orders = row_count("orders", scale)
    first, end_chunk, chunk, _ = chunk_range_for_split(num_orders, split, total_splits)
    pieces = [
        _gen_lineitem_chunk(scale, c, chunk, num_orders) for c in range(first, end_chunk)
    ]
    if not pieces:
        ref = _gen_lineitem_chunk(scale, 0, chunk, num_orders)
        cols = {k: np.zeros(0, dtype=v.dtype) for k, v in ref.columns.items()}
        return TpchTableData(cols, 0)
    cols = {
        k: np.concatenate([p.columns[k] for p in pieces]) for k in pieces[0].columns
    }
    return TpchTableData(cols, sum(p.count for p in pieces))


def _gen_lineitem_chunk(
    scale: float, chunk_idx: int, chunk: int, num_orders: int
) -> TpchTableData:
    start = chunk_idx * chunk
    end = min((chunk_idx + 1) * chunk, num_orders)
    okeys = np.arange(start + 1, end + 1, dtype=np.int64)
    # regenerate the order dates exactly as _gen_orders does (same rng stream)
    orng = _rng("orders", scale, chunk_idx)
    n_orders = len(okeys)
    num_cust = row_count("customer", scale)
    odates = orng.integers(MIN_ORDER_DATE, MAX_ORDER_DATE - 121, size=n_orders, dtype=np.int32)

    rng = _rng("lineitem", scale, chunk_idx)
    lines_per_order = rng.integers(1, MAX_LINES_PER_ORDER + 1, size=n_orders)
    n = int(lines_per_order.sum())
    order_idx = np.repeat(np.arange(n_orders), lines_per_order)
    l_orderkey = okeys[order_idx]
    # linenumber within order
    first = np.zeros(n, dtype=bool)
    first[np.cumsum(lines_per_order)[:-1]] = True
    first[0] = True
    linenumber = (np.arange(n) - np.repeat(np.concatenate([[0], np.cumsum(lines_per_order)[:-1]]), lines_per_order) + 1).astype(np.int32)

    num_parts = row_count("part", scale)
    num_supps = row_count("supplier", scale)
    partkey = rng.integers(1, num_parts + 1, size=n, dtype=np.int64)
    suppkey = rng.integers(1, num_supps + 1, size=n, dtype=np.int64)
    quantity = rng.integers(1, 51, size=n, dtype=np.int64)
    extendedprice = quantity * _retail_price(partkey)
    discount = rng.integers(0, 11, size=n, dtype=np.int64)  # cents: 0.00..0.10
    tax = rng.integers(0, 9, size=n, dtype=np.int64)

    odate = odates[order_idx]
    shipdate = odate + rng.integers(1, 122, size=n, dtype=np.int32)
    commitdate = odate + rng.integers(30, 91, size=n, dtype=np.int32)
    receiptdate = shipdate + rng.integers(1, 31, size=n, dtype=np.int32)

    returned = receiptdate <= CURRENT_DATE
    rf = np.where(returned, np.where(rng.random(n) < 0.5, 0, 2), 1).astype(np.int32)  # A/R else N
    ls = np.where(shipdate > CURRENT_DATE, 1, 0).astype(np.int32)  # O else F

    return TpchTableData(
        {
            "l_orderkey": l_orderkey,
            "l_partkey": partkey,
            "l_suppkey": suppkey,
            "l_linenumber": linenumber,
            "l_quantity": quantity * 100,  # decimal(12,2) cents
            "l_extendedprice": extendedprice,
            "l_discount": discount,
            "l_tax": tax,
            "l_returnflag": rf,
            "l_linestatus": ls,
            "l_shipdate": shipdate,
            "l_commitdate": commitdate,
            "l_receiptdate": receiptdate,
            "l_shipinstruct": rng.integers(0, len(SHIP_INSTRUCTS), size=n, dtype=np.int32),
            "l_shipmode": rng.integers(0, len(SHIP_MODES), size=n, dtype=np.int32),
            "l_comment": _comment_codes(rng, n),
        },
        n,
    )


def vocab_for(table: str, column: str, scale: float) -> Optional[List[str]]:
    """The sorted dictionary for a varchar column (None for non-varchar)."""
    col = next(c for c in TPCH_TABLES[table] if c.name == column)
    if col.vocab is not None:
        return list(col.vocab)
    # numbered-name columns
    if column in ("s_name",):
        return _numbered_vocab("Supplier#", row_count("supplier", scale))
    if column in ("c_name",):
        return _numbered_vocab("Customer#", row_count("customer", scale))
    if column == "s_phone":
        return _phone_vocab(row_count("supplier", scale))
    if column == "c_phone":
        return _phone_vocab(row_count("customer", scale))
    if column == "o_clerk":
        return _numbered_vocab("Clerk#", max(1, int(1000 * scale)))
    return None
