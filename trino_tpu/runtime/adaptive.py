"""Adaptive capacity narrowing for whole-query traced programs.

Round-3 verdict: the traced single-program tier carries FULL static
capacities through every stage — a selective query (TPC-H Q18's HAVING
keeps 57 of 1.5M groups) pays padded gathers/sorts at 6M capacity in every
downstream operator, and the operator-at-a-time tier pays per-dispatch
tunnel syncs instead. This module closes that gap while keeping the whole
plan ONE XLA program (zero mid-plan host syncs):

- ``plan_capacities`` seeds per-node output capacities from the CBO
  estimator (planner/stats.py) — selectivity propagated into static shapes,
  the XLA analogue of the reference's DeterminePartitionCount /
  CostCalculator feeding physical planning (sql/planner/optimizations/
  DeterminePartitionCount.java:88, cost/CostCalculatorWithEstimatedExchanges).
- ``_AdaptiveTracedExecutor`` compacts relations *inside the trace* to
  those capacities (stable scatter-compaction, no sort) and records an
  (overflow, actual) pair per narrowing point.
- ``AdaptiveQuery.tune`` runs the program, host-checks only the tiny
  (overflow, actuals) vector, and recompiles with measured capacities:
  overflowed points grow to their true counts, over-provisioned points
  shrink. The fixpoint (usually 1-2 compiles, both persistent-cache-keyed)
  is a program whose every stage is shaped by ACTUAL cardinalities — the
  single-chip analogue of the reference's adaptive replanning
  (sql/planner/AdaptivePlanner.java:87), applied to shapes instead of
  exchange types.

Why capacities, not streaming: on TPU every operator is a static-shape XLA
program; the padded-capacity tax is gathers (~60ns/element on v5e) and sort
passes over dead rows. Tight capacities turn Q18's post-HAVING pipeline
from 6M-wide to 128-wide — the same effect pipelined paging has on the JVM
(operator/Driver.java:372) achieved the TPU-native way.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..metadata import Metadata, Session
from ..planner.plan import (
    AggregationNode,
    FilterNode,
    JoinNode,
    LogicalPlan,
    PlanNode,
    TableScanNode,
    UnnestNode,
    visit_plan,
)
from ..planner.stats import StatsEstimator
from ..spi.page import Column, Page
from . import capstore
from . import kernelcost
from .executor import (
    ExecutionError,
    Relation,
    _permute_column,
    _round_capacity,
)
from .traced import _TracedExecutor, _prepare_traced, is_traceable

# narrowing candidates: nodes whose OUTPUT row count the CBO can estimate
# and whose output the trace can compact. Joins narrow at their capacity
# choice (no extra gather); the rest compact post-node.
_COMPACT_NODES = (TableScanNode, FilterNode, AggregationNode, UnnestNode)

# never compact below this (tiny buffers churn the jit cache for no win)
_MIN_CAP = 1024
# compaction must at least halve the capacity to pay for its gather
_MIN_SHRINK = 2


def _mask_top_valid(c: Column, keep: jnp.ndarray) -> Column:
    """AND the top-level validity with ``keep`` (rows past the compacted
    count hold clamped-gather garbage; inactive rows must not look valid)."""
    return Column(
        c.type, c.data, c.valid & keep, c.dictionary,
        lengths=c.lengths, elem_valid=c.elem_valid, children=c.children,
    )


def trace_compact(new_cap: int, page: Page) -> Tuple[Page, jnp.ndarray, jnp.ndarray]:
    """Stable in-trace compaction: active rows move to the front of a
    ``new_cap``-row page. One int32 scatter at source capacity + one gather
    of ``new_cap`` rows per column — NOT a sort (the cosort-based
    ``_jit_compact`` moves every payload through a full sort network).

    Returns (page, overflow, true_count); rows past ``new_cap`` are dropped
    and counted in ``overflow`` (the caller retries with a larger capacity).
    """
    active = page.active
    n = active.shape[0]
    slots = jnp.cumsum(active.astype(jnp.int32)) - 1
    # cumsum yields -1 at the tail when nothing is active -> total 0
    total = (slots[-1] + 1).astype(jnp.int64)
    targets = jnp.where(active & (slots < new_cap), slots, new_cap)
    perm = (
        jnp.zeros((new_cap,), dtype=jnp.int32)
        .at[targets]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )
    count = jnp.minimum(total, new_cap).astype(jnp.int32)
    new_active = jnp.arange(new_cap, dtype=jnp.int32) < count
    cols = tuple(
        _mask_top_valid(_permute_column(c, perm), new_active) for c in page.columns
    )
    overflow = jnp.maximum(total - new_cap, 0)
    return Page(cols, new_active), overflow, total


class _AdaptiveTracedExecutor(_TracedExecutor):
    """Traced executor with per-node capacity hints: joins allocate their
    hinted output capacity directly; scan/filter/agg/unnest outputs compact
    to their hint when that at least halves the buffer. Every candidate
    point records (key, overflow, true_count) for the host-side tuner."""

    def __init__(
        self,
        plan,
        metadata,
        session,
        scan_pages: Dict[int, Page],
        capacities: Dict[int, int],
        records: List[Tuple[int, jnp.ndarray, jnp.ndarray]],
    ):
        super().__init__(plan, metadata, session, scan_pages)
        self.capacities = capacities
        self.records = records
        self._join_key: Optional[int] = None

    def eval(self, node: PlanNode) -> Relation:
        rel = super().eval(node)
        if isinstance(node, _COMPACT_NODES):
            key = id(node)
            actual = jnp.sum(rel.page.active.astype(jnp.int64))
            hint = self.capacities.get(key)
            cap = rel.capacity
            if (
                hint is not None
                and max(hint, _MIN_CAP) * _MIN_SHRINK <= cap
            ):
                new_cap = max(hint, _MIN_CAP)
                page, ovf, total = trace_compact(new_cap, rel.page)
                self.records.append((key, ovf, total))
                rel = Relation(page, rel.symbols, rel.sorted_by)
            else:
                self.records.append((key, jnp.int64(0), actual))
        return rel

    def _join_relations(self, node: JoinNode, left: Relation, right: Relation,
                        allow_fusion: bool = True):
        prev = self._join_key
        self._join_key = id(node)
        try:
            # allow_fusion is moot here: traced executors never host-sync,
            # so the megakernel gate (_fusion_enabled) is always off
            return super()._join_relations(node, left, right, allow_fusion)
        finally:
            self._join_key = prev

    def _choose_join_capacity(self, emit, probe_cap: int, build_cap: int) -> int:
        key = self._join_key
        hint = self.capacities.get(key) if key is not None else None
        if hint is not None:
            cap = _round_capacity(max(hint, _MIN_CAP))
        else:
            cap = _round_capacity(max(probe_cap, 1))
        actual = jnp.sum(emit).astype(jnp.int64)
        ovf = jnp.maximum(actual - cap, 0)
        # always keyed (key is the JoinNode id, set by _join_relations for
        # every join) so the tuner can grow ANY overflowing join — an
        # unkeyed overflow could never converge
        self.records.append((key, ovf, actual))
        return cap


def candidate_nodes(plan: LogicalPlan) -> List[PlanNode]:
    """Narrowing candidates in canonical preorder — the cross-process-stable
    ordering the persisted capacity vector (runtime/capstore) is keyed by."""
    nodes: List[PlanNode] = []

    def visit(node: PlanNode):
        if isinstance(node, _COMPACT_NODES + (JoinNode,)):
            nodes.append(node)

    visit_plan(plan.root, visit)
    return nodes


def plan_capacities(
    plan: LogicalPlan, metadata: Metadata, margin: float = 2.0
) -> Dict[int, int]:
    """CBO-estimated output capacity per narrowing candidate (keyed by node
    identity — stable for the lifetime of the plan object)."""
    est = StatsEstimator(metadata, plan.types)
    caps: Dict[int, int] = {}

    for node in candidate_nodes(plan):
        try:
            r = est.rows(node)
        except Exception:  # estimator gaps must never kill execution
            r = None
        if r is not None and np.isfinite(r):
            caps[id(node)] = _round_capacity(int(r * margin) + 16)
    return caps


def compile_query_adaptive(
    plan: LogicalPlan,
    metadata: Metadata,
    session: Session,
    capacities: Dict[int, int],
):
    """Build (jittable_fn, example_pages, names, keys): the whole plan as one
    program returning (page, total_overflow, per-point true counts). ``keys``
    lists the node ids in the exact order the actuals vector reports them
    (captured from an abstract eval_shape trace — no compile)."""
    if not is_traceable(plan, allow_joins=True):
        raise ExecutionError("plan contains non-traceable nodes")
    example_pages, root = _prepare_traced(plan, metadata, session)
    keys_holder: List[int] = []

    def run(*pages: Page):
        records: List[Tuple[int, jnp.ndarray, jnp.ndarray]] = []
        executor = _AdaptiveTracedExecutor(
            plan, metadata, session, dict(enumerate(pages)), capacities, records
        )
        rel = executor.eval(root.source)
        cols = [rel.column_for(s) for s in root.symbols]
        keys_holder.clear()
        keys_holder.extend(k for k, _, _ in records)
        overflow = jnp.int64(0)
        for _, o, _ in records:
            overflow = overflow + o.astype(jnp.int64)
        for o in executor.overflows:
            overflow = overflow + o.astype(jnp.int64)
        actuals = (
            jnp.stack([a for _, _, a in records])
            if records
            else jnp.zeros((0,), dtype=jnp.int64)
        )
        return Page(tuple(cols), rel.page.active), overflow, actuals

    jax.eval_shape(run, *example_pages)  # abstract trace: populates keys_holder
    return run, example_pages, list(root.column_names), list(keys_holder)


class AdaptiveQuery:
    """One query's adaptive-capacity lifecycle: CBO-seeded compile, then a
    measured-capacity fixpoint. ``tune()`` is the entry point; after it
    returns, ``self.jfn``/``self.pages`` hold the tuned program."""

    def __init__(
        self,
        plan: LogicalPlan,
        metadata: Metadata,
        session: Session,
        margin: float = 2.0,
        persist: bool = True,
    ):
        self.plan = plan
        self.metadata = metadata
        self.session = session
        self.margin = margin
        self.caps = plan_capacities(plan, metadata, margin)
        self.compiles = 0
        self.attempts = 0
        self.jfn: Optional[Callable] = None
        self.pages: List[Page] = []
        self.names: List[str] = []
        self.keys: List[int] = []
        # cross-query/session tuned-capacity reuse (runtime/capstore): a hit
        # seeds the exact fixpoint vector, so tune() is one (persistently
        # XLA-cached) compile + one verification run instead of a grow/shrink
        # loop — the round-5 answer to per-instance re-tuning cost.
        self._candidates = candidate_nodes(plan)
        self._persist = persist
        self.fingerprint = capstore.plan_fingerprint(plan) if persist else ""
        self.seeded_from_store = False
        if persist:
            vec = capstore.load(self.fingerprint)
            if vec is not None and len(vec) == len(self._candidates):
                for node, cap in zip(self._candidates, vec):
                    if cap is not None:
                        self.caps[id(node)] = int(cap)
                    else:
                        self.caps.pop(id(node), None)
                self.seeded_from_store = True

    def _store_tuned(self) -> None:
        if not self._persist:
            return
        capstore.save(
            self.fingerprint,
            [self.caps.get(id(n)) for n in self._candidates],
        )

    def _compile(self):
        fn, pages, names, keys = compile_query_adaptive(
            self.plan, self.metadata, self.session, self.caps
        )
        self.jfn = kernelcost.jit(fn, label="adaptive_query")
        self.pages, self.names, self.keys = pages, names, keys
        self.compiles += 1

    def tune(self, max_attempts: int = 6) -> Tuple[Page, List[str]]:
        """Run to the capacity fixpoint. Each retry fixes the first
        overflowing point permanently (its true count is exact once its
        inputs are exact), so the loop terminates in <= #points attempts;
        in practice CBO seeds converge in 1-2."""
        self._compile()
        for attempt in range(max_attempts):
            self.attempts += 1
            page, overflow, actuals = self.jfn(*self.pages)
            ovf = int(np.asarray(overflow))
            tuned: Dict[int, int] = {}
            for key, act in zip(self.keys, np.asarray(actuals)):
                tuned[key] = _round_capacity(int(act + (act >> 2)) + 16)
            if ovf == 0:
                # tight already? keep; otherwise one shrink recompile
                if all(self.caps.get(k) == c for k, c in tuned.items()):
                    self._store_tuned()
                    return page, self.names
                self.caps = {**self.caps, **tuned}
                self._compile()
                page, overflow, actuals = self.jfn(*self.pages)
                if int(np.asarray(overflow)) == 0:
                    self._store_tuned()
                    return page, self.names
                # data moved under us between runs — fall through to grow
            if attempt == max_attempts - 1:
                break  # raising next; don't pay a compile that never runs
            # overflow: grow every point to at least its observed count
            # (the first overflowed point's count is exact; downstream
            # undercounts get another attempt), escalating with attempts
            grown: Dict[int, int] = {}
            for key, act in zip(self.keys, np.asarray(actuals)):
                base = _round_capacity(int(act * (1.5 + attempt)) + 16)
                grown[key] = max(base, self.caps.get(key, 0))
            self.caps = {**self.caps, **grown}
            self._compile()
        raise ExecutionError(
            f"adaptive capacity tuning did not converge in {max_attempts} attempts"
        )

    def run(self) -> Page:
        """Steady-state dispatch of the tuned program (no host-side tuning)."""
        page, _, _ = self.jfn(*self.pages)
        return page


def execute_adaptive(
    plan: LogicalPlan, metadata: Metadata, session: Session
) -> Tuple[List[str], Page]:
    """One-shot adaptive execution (names, result page)."""
    q = AdaptiveQuery(plan, metadata, session)
    page, names = q.tune()
    return names, page
