"""Lint engine: rule runner, inline suppressions, baseline diffing.

A rule is a callable ``rule(tree, source_lines, path) -> List[Finding]``
with ``rule.id`` and ``rule.description`` attributes (see rules.py). The
engine parses each file once, runs every rule over the shared AST, drops
findings suppressed inline, and splits the rest into baselined vs NEW
against tools/lint/lint_baseline.json.

Baseline entries key on (file, rule, context) where context is the stripped
source line text — stable across unrelated edits that shift line numbers,
invalidated when the flagged line itself changes (so debt cannot silently
grow under a baselined line's name).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_baseline.json")

# inline suppression: `# lint: disable=rule-id -- reason` (reason REQUIRED —
# an unexplained suppression is itself a finding)
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([a-z0-9_,-]+)(?:\s*--\s*(.*))?"
)


@dataclass
class Finding:
    file: str  # repo-relative path
    line: int
    rule: str
    message: str

    def key(self, source_lines: Optional[Sequence[str]] = None) -> Tuple[str, str, str]:
        ctx = ""
        if source_lines and 1 <= self.line <= len(source_lines):
            ctx = source_lines[self.line - 1].strip()
        return (self.file, self.rule, ctx)

    def to_dict(self) -> Dict[str, object]:
        return {
            "file": self.file,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # new (non-baselined)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0


def _suppressions_for_line(source_lines: Sequence[str], line: int) -> Tuple[set, bool]:
    """(rule ids disabled on this line, has_reason)."""
    if not (1 <= line <= len(source_lines)):
        return set(), False
    m = _SUPPRESS_RE.search(source_lines[line - 1])
    if not m:
        return set(), False
    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
    reason = (m.group(2) or "").strip()
    return rules, bool(reason)


class LintEngine:
    def __init__(self, rules: Sequence, root: str = REPO_ROOT):
        self.rules = list(rules)
        self.root = root

    def target_files(self, subdir: str = "trino_tpu") -> List[str]:
        base = os.path.join(self.root, subdir)
        out: List[str] = []
        for dirpath, _dirnames, filenames in os.walk(base):
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
        return sorted(out)

    def lint_file(self, path: str) -> List[Finding]:
        with open(path, "r") as fh:
            source = fh.read()
        return self._lint_source(
            os.path.relpath(path, self.root), source, source.splitlines()
        )

    def _lint_source(
        self, rel: str, source: str, source_lines: List[str]
    ) -> List[Finding]:
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            return [Finding(rel, e.lineno or 0, "syntax-error", str(e))]
        findings: List[Finding] = []
        for rule in self.rules:
            findings.extend(rule(tree, source_lines, rel))
        kept: List[Finding] = []
        for f in findings:
            disabled, has_reason = _suppressions_for_line(source_lines, f.line)
            if f.rule in disabled or "all" in disabled:
                if not has_reason:
                    kept.append(Finding(
                        f.file, f.line, f.rule,
                        f"suppression without a reason string ({f.message})",
                    ))
                continue
            kept.append(f)
        return kept

    def run(
        self, subdir: str = "trino_tpu", baseline: Optional[dict] = None
    ) -> LintResult:
        result = LintResult()
        baseline_keys = set()
        for entry in (baseline or {}).get("findings", []):
            baseline_keys.add(
                (entry.get("file", ""), entry.get("rule", ""), entry.get("context", ""))
            )
        for path in self.target_files(subdir):
            with open(path, "r") as fh:
                source = fh.read()
            source_lines = source.splitlines()
            rel = os.path.relpath(path, self.root)
            for f in self._lint_source(rel, source, source_lines):
                if f.key(source_lines) in baseline_keys:
                    result.baselined.append(f)
                else:
                    result.findings.append(f)
        return result


def load_baseline(path: str = BASELINE_PATH) -> dict:
    if not os.path.exists(path):
        return {"findings": []}
    with open(path, "r") as fh:
        return json.load(fh)


def write_baseline(findings: List[Finding], engine: LintEngine,
                   path: str = BASELINE_PATH) -> None:
    entries = []
    for f in findings:
        full = os.path.join(engine.root, f.file)
        with open(full, "r") as fh:
            source_lines = fh.read().splitlines()
        file_, rule, ctx = f.key(source_lines)
        entries.append({
            "file": file_, "rule": rule, "context": ctx, "message": f.message,
        })
    with open(path, "w") as fh:
        json.dump({"findings": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


def run_lint(subdir: str = "trino_tpu", with_baseline: bool = True) -> LintResult:
    """The tier-1 entry point: lint ``subdir`` against the checked-in
    baseline; result.findings are the NEW (failing) ones."""
    from .rules import ALL_RULES

    engine = LintEngine(ALL_RULES)
    baseline = load_baseline() if with_baseline else None
    return engine.run(subdir, baseline)
