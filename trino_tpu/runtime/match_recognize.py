"""MATCH_RECOGNIZE runtime: row-pattern matching over sorted partitions.

Reference blueprint: operator/window/matcher/Matcher.java + Program.java (a
compiled-NFA instruction VM with backtracking) and
operator/window/PatternRecognitionPartition.java. Row-pattern matching is
inherently sequential and branchy — the one operator family that does NOT map
onto the MXU/VPU — so, like the engine's dictionary-LUT string transforms, it
runs on the host: DEFINE conditions are evaluated VECTORIZED over the whole
sorted input first (PREV/NEXT become partition-masked shifts), then a
backtracking matcher walks precomputed boolean masks, which is the
TPU-friendly split of the work (device does the data-parallel part, host does
the control flow).

v1 scope, documented: DEFINE conditions may navigate physically (PREV/NEXT of
any expression over the current row) but not logically (FIRST/LAST/other
variables' rows — Trino's dynamic classifier navigation); MEASURES support
FINAL/RUNNING navigation (FIRST/LAST/PREV/NEXT), CLASSIFIER(), MATCH_NUMBER()
and sum/avg/min/max/count over variable or universal row sets. AFTER MATCH
SKIP PAST LAST ROW / TO NEXT ROW / TO FIRST/LAST var. ONE and ALL ROWS PER
MATCH (empty matches produce a row with null measures, like the reference's
default SHOW EMPTY MATCHES)."""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..planner.plan import PatternRecognitionNode
from ..sql import tree as t
from ..sql.ir import Call, CastExpr, Constant, IrExpr, Reference
from ..spi.page import Column, Page, _scalar_from_pylist
from ..spi.types import BIGINT, BOOLEAN, DecimalType, Type, is_floating


class MatchError(ValueError):
    pass


_BACKTRACK_LIMIT = 10_000_000


# --------------------------------------------------------------------------- #
# vectorized static evaluation (DEFINE conditions)
# --------------------------------------------------------------------------- #


class _Columns:
    """Host materialization of the sorted relation: raw storage values
    (decimals stay scaled ints — exact), strings decoded to objects."""

    def __init__(self, rel, order: np.ndarray):
        self.rel = rel
        self.order = order  # active sorted row indices into the page
        self._cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    def get(self, symbol: str) -> Tuple[np.ndarray, np.ndarray]:
        if symbol not in self._cache:
            c = self.rel.column_for(symbol)
            data = np.asarray(c.data)[self.order]
            valid = np.asarray(c.valid)[self.order]
            if c.dictionary is not None:
                vals = c.dictionary.decode(
                    np.clip(data.astype(np.int64), 0, len(c.dictionary) - 1)
                )
                vals = np.where(valid, vals, None)
                self._cache[symbol] = (vals, valid)
            else:
                self._cache[symbol] = (data, valid)
        return self._cache[symbol]


def _eval_static(
    expr: IrExpr, cols: _Columns, pid: np.ndarray, own_var: str, subsets
) -> Tuple[np.ndarray, np.ndarray]:
    """DEFINE condition -> (values, valid) arrays over all sorted rows.
    $pat refs must resolve to the define's own variable (current row);
    $prev/$next are physical shifts masked at partition boundaries."""

    def ev(e: IrExpr) -> Tuple[np.ndarray, np.ndarray]:
        n = len(pid)
        if isinstance(e, Reference):
            return cols.get(e.symbol)
        if isinstance(e, Constant):
            if e.value is None:
                return np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool)
            return (
                np.full(n, e.value, dtype=object)
                if isinstance(e.value, str)
                else np.full(n, e.value)
            ), np.ones(n, dtype=bool)
        if isinstance(e, CastExpr):
            vals, valid = ev(e.value)
            return _cast_array(vals, e.value.type, e.type), valid
        if isinstance(e, Call):
            name = e.name
            if name == "$pat":
                var = e.args[0].value
                members = subsets.get(var, (var,))
                if own_var not in members:
                    raise MatchError(
                        f"DEFINE {own_var}: navigation to other pattern "
                        f"variables ({var}) is not supported yet"
                    )
                return ev(e.args[1])
            if name in ("$prev", "$next"):
                vals, valid = ev(e.args[0])
                k = int(e.args[1].value)
                if name == "$prev":
                    shifted = np.roll(vals, k)
                    v = np.roll(valid, k) & (np.roll(pid, k) == pid)
                    if k > 0:
                        v[:k] = False
                else:
                    shifted = np.roll(vals, -k)
                    v = np.roll(valid, -k) & (np.roll(pid, -k) == pid)
                    if k > 0:
                        v[len(v) - k:] = False
                return shifted, v
            if name in ("$classifier", "$match_number", "$first", "$last") or (
                name.startswith("$agg_")
            ):
                raise MatchError(
                    f"{name} is not supported in DEFINE conditions yet "
                    "(dynamic match-state navigation)"
                )
            return _eval_call_arrays(name, e, ev)
        raise MatchError(f"unsupported expression in DEFINE: {type(e).__name__}")

    return ev(expr)


def _cast_array(vals, from_t: Type, to_t: Type):
    if isinstance(from_t, DecimalType) and isinstance(to_t, DecimalType):
        shift = to_t.scale - from_t.scale
        return vals * (10 ** shift) if shift >= 0 else vals // (10 ** -shift)
    if isinstance(to_t, DecimalType):
        return (np.asarray(vals, dtype=np.float64) * 10**to_t.scale).round().astype(np.int64) \
            if is_floating(from_t) else np.asarray(vals) * 10**to_t.scale
    if isinstance(from_t, DecimalType):
        return np.asarray(vals, dtype=np.float64) / 10**from_t.scale
    if is_floating(to_t):
        return np.asarray(vals, dtype=np.float64)
    return vals


_CMP = {
    "$eq": lambda a, b: a == b,
    "$ne": lambda a, b: a != b,
    "$lt": lambda a, b: a < b,
    "$lte": lambda a, b: a <= b,
    "$gt": lambda a, b: a > b,
    "$gte": lambda a, b: a >= b,
}
_ARITH = {
    "$add": lambda a, b: a + b,
    "$subtract": lambda a, b: a - b,
    "$multiply": lambda a, b: a * b,
}


def _eval_call_arrays(name: str, e: Call, ev):
    if name in _CMP or name in _ARITH:
        av, avd = ev(e.args[0])
        bv, bvd = ev(e.args[1])
        fn = _CMP.get(name) or _ARITH[name]
        with np.errstate(invalid="ignore"):
            out = fn(av, bv)
        return out, avd & bvd
    if name == "$divide":
        av, avd = ev(e.args[0])
        bv, bvd = ev(e.args[1])
        valid = avd & bvd & (np.asarray(bv) != 0)
        with np.errstate(divide="ignore", invalid="ignore"):
            if isinstance(e.type, DecimalType):
                out = np.where(valid, av * 10**0 // np.where(bv == 0, 1, bv), 0)
            else:
                out = np.where(
                    valid,
                    np.asarray(av, dtype=np.float64)
                    / np.where(np.asarray(bv) == 0, 1, bv),
                    0.0,
                )
        return out, valid
    if name == "$and":
        av, avd = ev(e.args[0])
        bv, bvd = ev(e.args[1])
        av = np.asarray(av, dtype=bool) & avd
        bv = np.asarray(bv, dtype=bool) & bvd
        # 3VL: false wins over null
        return av & bv, (avd & bvd) | (avd & ~av) | (bvd & ~bv)
    if name == "$or":
        av, avd = ev(e.args[0])
        bv, bvd = ev(e.args[1])
        at = np.asarray(av, dtype=bool) & avd
        bt = np.asarray(bv, dtype=bool) & bvd
        return at | bt, (avd & bvd) | at | bt
    if name == "$not":
        av, avd = ev(e.args[0])
        return ~np.asarray(av, dtype=bool), avd
    if name == "$is_null":
        av, avd = ev(e.args[0])
        return ~avd, np.ones(len(avd), dtype=bool)
    if name == "$negate":
        av, avd = ev(e.args[0])
        return -av, avd
    raise MatchError(f"function {name} not supported in DEFINE conditions yet")


# --------------------------------------------------------------------------- #
# backtracking matcher (Matcher.java analogue, on boolean masks)
# --------------------------------------------------------------------------- #


class _Matcher:
    def __init__(self, pattern, conds: Dict[str, np.ndarray], lo: int, hi: int):
        self.pattern = pattern
        self.conds = conds
        self.lo = lo
        self.hi = hi  # exclusive partition end
        self.assign: Dict[int, str] = {}
        self.steps = 0

    def _gen(self, elem, pos: int):
        """Yield end positions in SQL preference order (leftmost-greedy)."""
        self.steps += 1
        if self.steps > _BACKTRACK_LIMIT:
            raise MatchError("row-pattern backtracking limit exceeded")
        if isinstance(elem, t.PatternVariable):
            cond = self.conds[elem.name]
            if pos < self.hi and cond[pos]:
                self.assign[pos] = elem.name
                yield pos + 1
                del self.assign[pos]
            return
        if isinstance(elem, t.PatternConcatenation):
            yield from self._gen_seq(elem.elements, 0, pos)
            return
        if isinstance(elem, t.PatternAlternation):
            for alt in elem.alternatives:
                yield from self._gen(alt, pos)
            return
        if isinstance(elem, t.PatternQuantified):
            yield from self._gen_quant(elem, pos, 0)
            return
        raise MatchError(f"unsupported pattern element: {elem}")

    def _gen_seq(self, elems, i: int, pos: int):
        if i == len(elems):
            yield pos
            return
        for q in self._gen(elems[i], pos):
            yield from self._gen_seq(elems, i + 1, q)

    def _gen_quant(self, q: t.PatternQuantified, pos: int, count: int):
        can_more = q.max is None or count < q.max
        if q.greedy:
            if can_more:
                for p in self._gen(q.element, pos):
                    if p == pos:
                        break  # zero-width repetition guard
                    yield from self._gen_quant(q, p, count + 1)
            if count >= q.min:
                yield pos
        else:
            if count >= q.min:
                yield pos
            if can_more:
                for p in self._gen(q.element, pos):
                    if p == pos:
                        break
                    yield from self._gen_quant(q, p, count + 1)

    def match_at(self, pos: int) -> Optional[Tuple[int, Dict[int, str]]]:
        """First (= preferred) match starting at pos: (end, assignment)."""
        for end in self._gen(self.pattern, pos):
            return end, dict(self.assign)
        return None


# --------------------------------------------------------------------------- #
# per-match measure evaluation
# --------------------------------------------------------------------------- #


class _MeasureEval:
    """Scalar evaluation of a measure over one match (rows start..end-1 of the
    sorted input), at `upto` for RUNNING semantics (ALL ROWS PER MATCH).
    ref: operator/window/pattern measure computation (MeasureComputation.java)."""

    def __init__(self, cols: _Columns, subsets, part_lo: int, part_hi: int):
        self.cols = cols
        self.subsets = subsets
        self.part_lo = part_lo
        self.part_hi = part_hi

    def setup(self, start, end, assign, match_no, upto):
        self.start, self.end = start, end
        self.assign = assign
        self.match_no = match_no
        self.upto = upto  # inclusive last visible row; start-1 for empty match

    def _var_rows(self, var: Optional[str]) -> List[int]:
        rows = [i for i in range(self.start, self.upto + 1)]
        if var is None:
            return rows
        members = set(self.subsets.get(var, (var,)))
        return [i for i in rows if self.assign.get(i) in members]

    def _value_at(self, e: IrExpr, row: Optional[int]):
        """Evaluate e with 'current row' = row (physical; None = NULL)."""
        if row is not None and not (self.part_lo <= row < self.part_hi):
            row = None
        if isinstance(e, Reference):
            # unqualified reference = RUNNING LAST of the universal row set
            if row is None:
                row = self.upto if self.upto >= self.start else None
            if row is None:
                return None
            vals, valid = self.cols.get(e.symbol)
            return vals[row] if valid[row] else None
        if isinstance(e, Constant):
            return e.value
        if isinstance(e, CastExpr):
            v = self._value_at(e.value, row)
            return _cast_scalar(v, e.value.type, e.type)
        if isinstance(e, Call):
            return self._call_at(e, row)
        raise MatchError(f"unsupported measure expression: {type(e).__name__}")

    def _nav_row(self, e: IrExpr, row: Optional[int]) -> Optional[int]:
        """The row an expression is anchored at (for PREV/NEXT wrapping)."""
        if isinstance(e, Call) and e.name == "$pat":
            rows = self._var_rows(e.args[0].value)
            return rows[-1] if rows else None
        if isinstance(e, Call) and e.name in ("$first", "$last"):
            return self._first_last_row(e)
        return row

    def _first_last_row(self, e: Call) -> Optional[int]:
        inner = e.args[0]
        k = int(e.args[1].value)
        var = None
        if isinstance(inner, Call) and inner.name == "$pat":
            var = inner.args[0].value
        rows = self._var_rows(var)
        if not rows:
            return None
        idx = k if e.name == "$first" else len(rows) - 1 - k
        return rows[idx] if 0 <= idx < len(rows) else None

    def _call_at(self, e: Call, row: Optional[int]):
        name = e.name
        if name == "$pat":
            rows = self._var_rows(e.args[0].value)
            return self._value_at(e.args[1], rows[-1] if rows else None)
        if name in ("$first", "$last"):
            target = self._first_last_row(e)
            inner = e.args[0]
            base = inner.args[1] if isinstance(inner, Call) and inner.name == "$pat" else inner
            return self._value_at(base, target)
        if name in ("$prev", "$next"):
            inner = e.args[0]
            k = int(e.args[1].value)
            anchor = self._nav_row(inner, row if row is not None else self.upto)
            if anchor is None:
                return None
            target = anchor - k if name == "$prev" else anchor + k
            base = inner
            if isinstance(inner, Call) and inner.name == "$pat":
                base = inner.args[1]
            elif isinstance(inner, Call) and inner.name in ("$first", "$last"):
                b = inner.args[0]
                base = b.args[1] if isinstance(b, Call) and b.name == "$pat" else b
            return self._value_at(base, target)
        if name == "$final":
            saved = self.upto
            self.upto = self.end - 1 if self.end > self.start else self.start - 1
            try:
                return self._value_at(e.args[0], row)
            finally:
                self.upto = saved
        if name == "$classifier":
            r = row if row is not None else self.upto
            return self.assign.get(r)
        if name == "$match_number":
            return self.match_no
        if name.startswith("$agg_"):
            return self._aggregate(name[5:], e.args[0])
        # scalar combination of sub-measures
        args = [self._value_at(a, row) for a in e.args]
        return _scalar_call(name, args, e)

    def _aggregate(self, kind: str, inner: IrExpr):
        var = None
        base = inner
        if isinstance(inner, Call) and inner.name == "$pat":
            var = inner.args[0].value
            base = inner.args[1]
        rows = self._var_rows(var)
        vals = [self._value_at(base, r) for r in rows]
        vals = [v for v in vals if v is not None]
        if kind == "count":
            return len(vals)
        if not vals:
            return None
        if kind == "sum":
            return sum(vals)
        if kind == "min":
            return min(vals)
        if kind == "max":
            return max(vals)
        if kind == "avg":
            return sum(vals) / len(vals)
        raise MatchError(f"unsupported pattern aggregate: {kind}")

    def evaluate(self, e: IrExpr):
        return self._value_at(e, None)


def _cast_scalar(v, from_t: Type, to_t: Type):
    if v is None:
        return None
    if isinstance(from_t, DecimalType) and isinstance(to_t, DecimalType):
        shift = to_t.scale - from_t.scale
        return int(v) * 10**shift if shift >= 0 else int(v) // 10 ** -shift
    if isinstance(to_t, DecimalType):
        return round(float(v) * 10**to_t.scale)
    if isinstance(from_t, DecimalType):
        return float(v) / 10**from_t.scale
    if is_floating(to_t):
        return float(v)
    return v


def _scalar_call(name: str, args, e: Call):
    if any(a is None for a in args):
        if name not in ("$and", "$or", "$is_null", "$not"):
            return None
    if name in _CMP:
        return bool(_CMP[name](args[0], args[1]))
    if name in _ARITH:
        return _ARITH[name](args[0], args[1])
    if name == "$divide":
        if args[1] == 0 or args[1] is None:
            return None
        if isinstance(e.type, DecimalType):
            return int(args[0]) // int(args[1])
        return args[0] / args[1]
    if name == "$negate":
        return -args[0]
    if name == "$not":
        return None if args[0] is None else not args[0]
    if name == "$is_null":
        return args[0] is None
    if name == "$and":
        a, b = args
        if a is False or b is False:
            return False
        if a is None or b is None:
            return None
        return True
    if name == "$or":
        a, b = args
        if a is True or b is True:
            return True
        if a is None or b is None:
            return None
        return False
    raise MatchError(f"function {name} not supported in MEASURES yet")


# --------------------------------------------------------------------------- #
# operator entry point
# --------------------------------------------------------------------------- #


def execute_match_recognize(executor, rel, node: PatternRecognitionNode):
    from .executor import Relation, _jit_sort

    # 1. sort by (partition keys, order keys) on device
    orderings = tuple(
        __import__("trino_tpu.planner.plan", fromlist=["Ordering"]).Ordering(s)
        for s in node.partition_by
    ) + tuple(node.order_by)
    if orderings:
        page = _jit_sort(orderings, rel.symbols, None, rel.page)
    else:
        page = rel.page
    srel = Relation(page, rel.symbols)

    active = np.asarray(page.active)
    order = np.nonzero(active)[0]  # sorted active rows, in sort order
    n = len(order)
    cols = _Columns(srel, order)

    # 2. partition ids from key-change boundaries
    if node.partition_by and n:
        change = np.zeros(n, dtype=bool)
        for sym in node.partition_by:
            vals, valid = cols.get(sym)
            change[1:] |= (vals[1:] != vals[:-1]) | (valid[1:] != valid[:-1])
        pid = np.cumsum(change)
    else:
        pid = np.zeros(n, dtype=np.int64)

    subsets = {name: members for name, members in node.subsets}

    # 3. vectorized DEFINE conditions (variables without DEFINE are TRUE)
    defined = dict(node.defines)
    conds: Dict[str, np.ndarray] = {}

    def pattern_var_names(p) -> set:
        if isinstance(p, t.PatternVariable):
            return {p.name}
        if isinstance(p, t.PatternConcatenation):
            return set().union(*(pattern_var_names(x) for x in p.elements))
        if isinstance(p, t.PatternAlternation):
            return set().union(*(pattern_var_names(x) for x in p.alternatives))
        if isinstance(p, t.PatternQuantified):
            return pattern_var_names(p.element)
        raise MatchError(f"unsupported pattern element: {p}")

    for var in pattern_var_names(node.pattern):
        if var in defined:
            vals, valid = _eval_static(defined[var], cols, pid, var, subsets)
            conds[var] = np.asarray(vals, dtype=bool) & valid
        else:
            conds[var] = np.ones(n, dtype=bool)

    # 4. per-partition match loop
    sys.setrecursionlimit(max(sys.getrecursionlimit(), 4 * n + 10000))
    matches = []  # (start, end_exclusive, assign, match_no, part_lo, part_hi)
    bounds = np.nonzero(np.diff(pid))[0] + 1 if n else np.array([], dtype=int)
    starts = np.concatenate([[0], bounds]).astype(int) if n else []
    ends = np.concatenate([bounds, [n]]).astype(int) if n else []
    for lo, hi in zip(starts, ends):
        matcher = _Matcher(node.pattern, conds, lo, hi)
        match_no = 0
        pos = lo
        while pos < hi:
            m = matcher.match_at(pos)
            if m is None:
                pos += 1
                continue
            end, assign = m
            match_no += 1
            matches.append((pos, end, assign, match_no, lo, hi))
            if end == pos:  # empty match: always advance
                pos += 1
            elif node.skip_mode == "TO_NEXT_ROW":
                pos += 1
            elif node.skip_mode in ("TO_FIRST", "TO_LAST"):
                members = set(subsets.get(node.skip_target, (node.skip_target,)))
                var_rows = [i for i in range(pos, end) if assign.get(i) in members]
                if not var_rows:
                    raise MatchError(
                        f"AFTER MATCH SKIP TO {node.skip_target}: variable "
                        "matched no rows"
                    )
                target = var_rows[0] if node.skip_mode == "TO_FIRST" else var_rows[-1]
                if target == pos:
                    # skipping to the first row of the current match would
                    # re-match the same position forever — the reference
                    # raises for both TO FIRST and TO LAST (ref:
                    # operator/window/matcher semantics, "cannot skip to
                    # first row of match")
                    raise MatchError(
                        f"AFTER MATCH SKIP TO "
                        f"{'FIRST' if node.skip_mode == 'TO_FIRST' else 'LAST'} "
                        "would not advance (spec error)"
                    )
                pos = target
            else:  # PAST_LAST
                pos = end
    # 5. measures + output rows
    ev = _MeasureEval(cols, subsets, 0, n)
    out_rows: List[int] = []  # sorted-input row index each output row shows
    measure_vals: List[List] = [[] for _ in node.measures]
    for start, end, assign, match_no, lo, hi in matches:
        ev.part_lo, ev.part_hi = lo, hi
        if node.rows_per_match == "ONE":
            ev.setup(start, end, assign, match_no, end - 1 if end > start else start - 1)
            out_rows.append(start)
            for i, (_, ir, _) in enumerate(node.measures):
                measure_vals[i].append(
                    ev.evaluate(ir) if end > start else _empty_measure(ev, ir, match_no)
                )
        else:
            for r in range(start, end):
                ev.setup(start, end, assign, match_no, r)
                out_rows.append(r)
                for i, (_, ir, _) in enumerate(node.measures):
                    measure_vals[i].append(ev.evaluate(ir))

    # 6. build the output page
    out_cols: List[Column] = []
    src_idx = order[out_rows] if out_rows else np.array([], dtype=int)
    m = len(out_rows)
    if node.rows_per_match == "ONE":
        carried = node.partition_by
    else:
        carried = node.source.output_symbols
    for sym in carried:
        c = rel.column_for(sym)
        # gather the carried rows on host (materialization boundary)
        data = np.asarray(c.data)[src_idx] if m else np.zeros(0, c.data.dtype)
        valid = np.asarray(c.valid)[src_idx] if m else np.zeros(0, bool)
        out_cols.append(Column(c.type, jnp.asarray(data), jnp.asarray(valid), c.dictionary))
    for i, (sym, ir, typ) in enumerate(node.measures):
        out_cols.append(_measure_column(typ, measure_vals[i]))
    active_out = jnp.ones((max(m, 1),), dtype=jnp.bool_) if m else jnp.zeros((1,), dtype=jnp.bool_)
    if m == 0:
        out_cols = [
            Column(c.type, jnp.zeros((1,), c.data.dtype), jnp.zeros((1,), jnp.bool_), c.dictionary)
            for c in out_cols
        ]
    page = Page(tuple(out_cols), active_out)
    from .executor import Relation as R

    return R(page, node.output_symbols)


def _empty_measure(ev: _MeasureEval, ir: IrExpr, match_no: int):
    """Empty match: navigation/aggregates see zero rows; MATCH_NUMBER still
    numbers the match (SQL empty-match semantics)."""
    if isinstance(ir, Call) and ir.name == "$match_number":
        return match_no
    if isinstance(ir, Call) and ir.name.startswith("$agg_count"):
        return 0
    try:
        return ev.evaluate(ir)
    except Exception:
        return None


def _measure_column(typ: Type, values: List) -> Column:
    if not values:
        return Column(typ, jnp.zeros((1,), typ.storage_dtype), jnp.zeros((1,), jnp.bool_))
    if typ.name in ("varchar", "char"):
        return Column.from_strings([None if v is None else str(v) for v in values], typ)
    # decimals are already scaled ints from the evaluator — build storage directly
    valid = np.array([v is not None for v in values], dtype=bool)
    conv = np.zeros(len(values), dtype=typ.storage_dtype)
    for i, v in enumerate(values):
        if v is not None:
            conv[i] = v
    return Column(typ, jnp.asarray(conv), jnp.asarray(valid))
