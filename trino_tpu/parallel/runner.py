"""DistributedQueryRunner: multi-worker stage-by-stage fragment execution.

Reference blueprint: the coordinator scheduling loop of SURVEY.md §3.1 —
PlanFragmenter output scheduled stage by stage (PipelinedQueryScheduler.java:163,
SqlStage/StageScheduler), splits assigned to workers (SOURCE_DISTRIBUTION,
SourcePartitionedScheduler), stage outputs repartitioned/gathered/broadcast
between stages (§3.3 exchange data plane).

Round-1 execution model: N logical workers; each fragment runs once per
partition with that partition's inputs; page movement between stages is
host-mediated (the DCN tier). The single-program ICI all_to_all path for
partial-agg pipelines lives in parallel/distributed.py; fusing fragment chains
into shard_map programs is the round-2 unification.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..metadata import CatalogManager, Metadata, Session
from ..planner import LogicalPlanner, optimize
from ..planner.fragmenter import (
    ExchangeType,
    Partitioning,
    PlanFragment,
    RemoteSourceNode,
    SubPlan,
    add_exchanges,
    create_fragments,
)
from ..planner.plan import LogicalPlan, OutputNode, PlanNode, TableScanNode, visit_plan
from ..runtime.executor import PlanExecutor, Relation, _concat_pages
from ..runtime.local import QueryResult
from ..spi.page import Column, Page
from ..sql import parse_statement
from ..sql import tree as t


_INT64_MIN = np.int64(np.iinfo(np.int64).min)
_INT64_MAX = np.int64(np.iinfo(np.int64).max)


def _host_order_key(d: np.ndarray) -> np.ndarray:
    """Host mirror of kernels.order_key (floats: sign-magnitude bit unfold)."""
    if d.dtype.kind == "f":
        bits = np.ascontiguousarray(d, dtype=np.float64).view(np.int64)
        return np.where(bits < 0, np.bitwise_xor(~bits, _INT64_MIN), bits)
    return d.astype(np.int64)


def _hash_partition_host(cols: List, n: int) -> np.ndarray:
    """Host mirror of parallel.exchange.partition_ids (same 64-bit mix, same
    NULL-sentinel and float order-key normalization). ``cols``: (data, valid)."""
    acc = np.full(cols[0][0].shape, 0x9E3779B97F4A7C15, dtype=np.uint64)
    for d, v in cols:
        k = np.where(v, _host_order_key(d), _INT64_MAX)
        x = k.astype(np.uint64)
        x = (x ^ (x >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
        x = (x ^ (x >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
        x = x ^ (x >> np.uint64(33))
        acc = (acc ^ x) * np.uint64(0x100000001B3)
    return (acc % np.uint64(n)).astype(np.int64)


def _page_to_host(page: Page):
    active = np.asarray(page.active)
    cols = [
        (c.type, np.asarray(c.data)[active], np.asarray(c.valid)[active], c.dictionary)
        for c in page.columns
    ]
    return cols


def _page_from_host_chunks(chunks: List[List]) -> Page:
    """Merge host column-spec chunks [(type, data, valid, dict), ...] from
    multiple producers into one Page. Columns whose chunks carry DIFFERENT
    dictionaries are re-encoded into a merged sorted dictionary — codes are
    only comparable within one dictionary (host mirror of
    runtime.executor._concat_pages)."""
    from ..spi.page import Dictionary

    merged = []
    for i in range(len(chunks[0])):
        type_ = chunks[0][i][0]
        dicts = [c[i][3] for c in chunks]
        real = [d for d in dicts if d is not None]
        if real and len({d.fingerprint() for d in real}) > 1:
            merged_values = sorted(set().union(*[list(d.values) for d in real]))
            dictionary = Dictionary(np.asarray(merged_values, dtype=object))
            code_of = {s: c for c, s in enumerate(merged_values)}
            datas = []
            for c in chunks:
                col = c[i]
                if col[3] is None:
                    datas.append(np.zeros_like(col[1]))
                    continue
                lut = np.array([code_of[s] for s in col[3].values], dtype=col[1].dtype)
                datas.append(lut[np.clip(col[1], 0, len(lut) - 1)])
            data = np.concatenate(datas)
        else:
            data = np.concatenate([c[i][1] for c in chunks])
            dictionary = real[0] if real else None
        valid = np.concatenate([c[i][2] for c in chunks])
        merged.append((type_, data, valid, dictionary))
    n = len(merged[0][1]) if merged else 0
    cols = tuple(
        Column.from_numpy(tp, d, v, capacity=max(n, 1), dictionary=dc)
        for tp, d, v, dc in merged
    )
    active = np.zeros(max(n, 1), dtype=np.bool_)
    active[:n] = True
    return Page(cols, jnp.asarray(active))


def _pages_from_host_rows(col_specs, row_sel: np.ndarray) -> Page:
    cols = []
    n = int(row_sel.sum()) if row_sel.dtype == bool else len(row_sel)
    for type_, data, valid, dictionary in col_specs:
        d = data[row_sel]
        v = valid[row_sel]
        cols.append(Column.from_numpy(type_, d, v, capacity=max(len(d), 1), dictionary=dictionary))
    if not cols:
        return Page((), jnp.zeros((1,), dtype=jnp.bool_))
    cap = cols[0].capacity
    active = np.zeros(cap, dtype=np.bool_)
    active[: len(col_specs[0][1][row_sel])] = True
    return Page(tuple(cols), jnp.asarray(active))


def run_fragment_partition(executor: "_FragmentExecutor", root: PlanNode) -> Page:
    """One fragment x one partition -> output Page (shared by the in-process
    scheduler and the worker task API)."""
    if isinstance(root, OutputNode):
        _, page = executor.execute()
        return page
    rel = executor.eval(root)
    return Page(tuple(rel.column_for(s) for s in root.output_symbols), rel.page.active)


class _FragmentExecutor(PlanExecutor):
    """Executes one fragment for one partition: RemoteSources read staged pages;
    table scans take only this partition's splits (SOURCE distribution)."""

    def __init__(
        self,
        plan: LogicalPlan,
        metadata: Metadata,
        session: Session,
        staged: Dict[int, List[Page]],
        partition: int,
        n_workers: int,
    ):
        super().__init__(plan, metadata, session)
        self.staged = staged
        self.partition = partition
        self.n_workers = n_workers

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Relation:
        pages = self.staged[node.fragment_id]
        page = pages[self.partition] if self.partition < len(pages) else pages[0]
        return Relation(page, node.symbols)

    def _exec_TableScanNode(self, node: TableScanNode) -> Relation:
        connector = self.metadata.connector_for(node.table)
        handle = node.table
        if node.constraint.domains:
            absorbed = self.metadata.apply_filter(handle, node.constraint)
            if absorbed is not None:
                handle = absorbed
        splits = connector.split_manager().get_splits(handle)
        # SOURCE distribution: round-robin split assignment
        # (ref: UniformNodeSelector / SourcePartitionedScheduler)
        splits = [s for i, s in enumerate(splits) if i % self.n_workers == self.partition]
        symbols = tuple(s for s, _ in node.assignments)
        meta = self.metadata.get_table_metadata(node.table)
        col_indexes = [meta.column_index(c) for _, c in node.assignments]
        if not splits:
            cols = tuple(
                Column(
                    self.types[s],
                    jnp.zeros((1,), dtype=self.types[s].storage_dtype),
                    jnp.zeros((1,), dtype=jnp.bool_),
                )
                for s in symbols
            )
            return Relation(Page(cols, jnp.zeros((1,), dtype=jnp.bool_)), symbols)
        provider = connector.page_source_provider()
        pages = [provider.create_page_source(sp, col_indexes) for sp in splits]
        return Relation(_concat_pages(pages), symbols)


class DistributedQueryRunner:
    """Multi-worker engine (the DistributedQueryRunner.java:108 analogue —
    a full multi-stage cluster in one process)."""

    def __init__(
        self,
        session: Optional[Session] = None,
        n_workers: int = 4,
        worker_urls: Optional[List[str]] = None,
    ):
        """``worker_urls``: if set, tasks dispatch to remote WorkerServers over
        the /v1/task HTTP API (HttpRemoteTask analogue) instead of executing
        in-process; workers must mount identically-configured catalogs."""
        self.catalogs = CatalogManager()
        self.metadata = Metadata(self.catalogs)
        self.session = session or Session()
        self.n_workers = n_workers
        self.worker_urls = worker_urls

    @staticmethod
    def tpch(scale: float = 0.01, n_workers: int = 4, split_target_rows: int = 4096):
        from ..connectors.tpch import TpchConnector

        runner = DistributedQueryRunner(
            Session(catalog="tpch", schema="sf" + f"{scale:g}".replace(".", "_")), n_workers
        )
        runner.catalogs.register(
            "tpch", TpchConnector(scale=scale, split_target_rows=split_target_rows)
        )
        return runner

    def plan_distributed(self, sql: str) -> SubPlan:
        stmt = parse_statement(sql)
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, self.metadata, self.session)
        return create_fragments(plan)

    def execute(self, sql: str) -> QueryResult:
        from ..runtime.failure import execute_with_retry

        return execute_with_retry(
            self._execute_once, sql, retry_policy=str(self.session.get("retry_policy"))
        )

    def _execute_once(self, sql: str) -> QueryResult:
        subplan = self.plan_distributed(sql)
        # tier 1 (SURVEY.md §5.8): lower the whole fragment tree into one
        # shard_map program — exchanges ride ICI collectives, no host hops.
        # Falls back to the staged (DCN-tier) path for plans that need host
        # syncs, remote workers, or when the mesh is unavailable.
        if (
            self.worker_urls is None
            and self.session.get("use_ici_exchange")
            and len(jax.devices()) >= self.n_workers
        ):
            from .mesh_runner import MeshLoweringError, MeshQueryRunner

            try:
                if getattr(self, "_mesh_runner", None) is None:
                    self._mesh_runner = MeshQueryRunner(
                        session=self.session,
                        n_devices=self.n_workers,
                        catalogs=self.catalogs,
                        metadata=self.metadata,
                    )
                names, page = self._mesh_runner.execute_subplan(subplan)
                return QueryResult(names, page.to_pylist())
            except MeshLoweringError:
                pass
        from ..runtime.spiller import Spiller

        spiller = Spiller(int(self.session.get("exchange_spill_trigger_bytes") or 0))
        self.last_spiller = spiller
        staged: Dict[int, List[object]] = {}
        # fragments are listed children-first, so inputs are always staged;
        # parked stage outputs spill to host beyond the device budget (the root
        # fragment's output is consumed immediately — never parked/spilled)
        root_id = subplan.root_fragment.fragment_id
        for frag in subplan.fragments:
            pages = self._execute_fragment(subplan, frag, staged)
            staged[frag.fragment_id] = (
                pages if frag.fragment_id == root_id else spiller.maybe_spill(pages)
            )
        final_pages = staged[root_id]
        assert len(final_pages) == 1
        root = subplan.root_fragment.root
        assert isinstance(root, OutputNode)
        return QueryResult(list(root.column_names), final_pages[0].to_pylist())

    # ------------------------------------------------------------------ internals

    def _execute_fragment(
        self, subplan: SubPlan, frag: PlanFragment, staged
    ) -> List[Page]:
        n_parts = 1 if frag.partitioning == Partitioning.SINGLE else self.n_workers

        # locate this fragment's remote sources to pre-stage their exchanges
        remotes: List[RemoteSourceNode] = []

        def collect(n: PlanNode):
            if isinstance(n, RemoteSourceNode):
                remotes.append(n)

        visit_plan(frag.root, collect)
        exchanged: Dict[int, List[Page]] = {}
        from ..runtime.spiller import Spiller

        for rs in remotes:
            producer = [Spiller.load(e) for e in staged[rs.fragment_id]]
            pages = self._run_exchange(rs, producer, n_parts, subplan)
            if self.session.get("exchange_compression"):
                # cross the wire: serialize -> LZ4 (C++) -> deserialize, exactly
                # what the DCN page stream does (runtime/serde.py)
                from ..runtime.serde import deserialize_page, serialize_page

                pages = [deserialize_page(serialize_page(p)) for p in pages]
            exchanged[rs.fragment_id] = pages

        plan = LogicalPlan(frag.root, subplan.types)
        if self.worker_urls:
            return self._dispatch_remote(frag, subplan, exchanged, n_parts)
        out_pages: List[Page] = []
        for p in range(n_parts):
            executor = _FragmentExecutor(
                plan, self.metadata, self.session, exchanged, p, n_parts
            )
            out_pages.append(run_fragment_partition(executor, frag.root))
        return out_pages

    def _dispatch_remote(self, frag, subplan, exchanged, n_parts) -> List[Page]:
        """Ship each partition's task to a worker over POST /v1/task
        (HttpRemoteTask.sendUpdate analogue); pages travel on the serde wire."""
        import urllib.request
        from concurrent.futures import ThreadPoolExecutor

        from ..runtime.serde import deserialize_page, serialize_page
        from ..server.worker import TaskDescriptor, encode_task

        def run_partition(p: int) -> Page:
            inputs = {
                fid: [serialize_page(pages[p] if p < len(pages) else pages[0])]
                for fid, pages in exchanged.items()
            }
            # partition index drives scan split assignment; staged inputs ship
            # as single-page lists, which _exec_RemoteSourceNode resolves via
            # its pages[0] fallback for any partition index
            desc = TaskDescriptor(
                root=frag.root,
                types=subplan.types,
                session_props=dict(self.session.properties),
                partition=p,
                n_workers=n_parts,
                inputs=inputs,
            )
            url = self.worker_urls[p % len(self.worker_urls)]
            req = urllib.request.Request(
                f"{url.rstrip('/')}/v1/task/{frag.fragment_id}_{p}",
                data=encode_task(desc),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                return deserialize_page(resp.read())

        with ThreadPoolExecutor(max_workers=max(n_parts, 1)) as pool:
            return list(pool.map(run_partition, range(n_parts)))

    def _run_exchange(
        self,
        rs: RemoteSourceNode,
        producer_pages: List[Page],
        n_consumer_parts: int,
        subplan: SubPlan,
    ) -> List[Page]:
        """The DCN-tier exchange: repartition/gather/broadcast producer outputs.
        (ref: §3.3 — pull-based page streams; host-mediated in round 1.)"""
        if rs.exchange_type == ExchangeType.GATHER:
            merged = self._merge_host(producer_pages)
            return [merged]
        if rs.exchange_type == ExchangeType.BROADCAST:
            merged = self._merge_host(producer_pages)
            return [merged for _ in range(n_consumer_parts)]
        # REPARTITION by hash of partition keys
        key_idx = [rs.symbols.index(k) for k in rs.partition_keys]
        host_parts: List[List] = [[] for _ in range(n_consumer_parts)]
        specs = None
        buckets_per_producer = []
        for page in producer_pages:
            cols = _page_to_host(page)
            specs = [(c[0], c[3]) for c in cols]
            if len(cols[0][1]) == 0:
                continue
            # dictionary-coded keys hash by VALUE (content-stable key), not by
            # code — producers may carry different dictionaries for the same
            # column, and the same string must land on one consumer partition
            keys = []
            for i in key_idx:
                _, data, valid, dictionary = cols[i]
                if dictionary is not None:
                    lut = dictionary.value_keys()
                    data = lut[np.clip(data, 0, len(lut) - 1)]
                keys.append((data, valid))
            keys = keys or [
                (
                    np.zeros(len(cols[0][1]), dtype=np.int64),
                    np.ones(len(cols[0][1]), dtype=np.bool_),
                )
            ]
            target = _hash_partition_host(keys, n_consumer_parts)
            for part in range(n_consumer_parts):
                sel = target == part
                if sel.any():
                    host_parts[part].append([(c[0], c[1][sel], c[2][sel], c[3]) for c in cols])
        out = []
        for part in range(n_consumer_parts):
            out.append(self._build_page(host_parts[part], rs, subplan))
        return out

    def _merge_host(self, pages: List[Page]) -> Page:
        chunks = [_page_to_host(p) for p in pages]
        chunks = [c for c in chunks if len(c) == 0 or len(c[0][1]) > 0] or chunks[:1]
        return _page_from_host_chunks(chunks)

    def _build_page(self, chunk_list, rs: RemoteSourceNode, subplan: SubPlan) -> Page:
        if not chunk_list:
            cols = tuple(
                Column(
                    subplan.types[s],
                    jnp.zeros((1,), dtype=subplan.types[s].storage_dtype),
                    jnp.zeros((1,), dtype=jnp.bool_),
                )
                for s in rs.symbols
            )
            return Page(cols, jnp.zeros((1,), dtype=jnp.bool_))
        return _page_from_host_chunks(chunk_list)
