"""FTE data plane: durable-exchange reads/writes shared by workers and the
coordinator's in-process task execution.

Round-4 verdict: every FTE task's inputs shipped inline in the task
descriptor and outputs were pulled back through the coordinator — all
exchange bytes transited one host twice. The reference's FTE exists
precisely to avoid that: tasks read/write shuffle storage directly
(plugin/trino-exchange-filesystem/.../FileSystemExchangeSink.java,
FileSystemExchangeManager.java); the coordinator moves only descriptors
and statistics. These helpers are that direct path: a task descriptor
carries {"durable": {...}} input specs and a {"kind": "durable", ...}
output spec naming locations in the shared exchange store; whoever runs
the task (a WorkerServer or the coordinator's local fallback) resolves
them against the store itself.

Input spec   {"dir", "producer_parts", "mode": "part"|"all", "part",
              "n_parts", "symbols"}
Output spec  {"kind": "durable", "dir", "partition", "attempt", "n",
              "keys", "symbols"}
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def stage_durable_input(spec: Dict, types) -> object:
    """Assemble one input edge's Page from the durable exchange store.

    mode "part": this task's hash part from every producer partition
    (co-partitioned join/aggregation input). mode "all": every part of
    every producer partition (gather, broadcast, and the adaptive
    partitioned->broadcast flip).

    Frames STREAM off disk (Exchange.iter_part) and deserialize on the
    shared I/O pool, so decode of frame i overlaps the read of frame i+1."""
    from ..spi.host_pages import (
        empty_page_for,
        page_from_host_chunks as _page_from_host_chunks,
        page_to_host as _page_to_host,
    )
    from .exchange_spi import decode_guard, exchange_for
    from .serde import deserialize_page
    from .spiller import io_pool

    ex = exchange_for(spec["dir"])
    pool = io_pool()
    # (producer_partition, attempt-at-READ-time, future) — corruption must
    # name its source, tagged with the attempt the blobs actually came from
    futs = []
    n_pp = int(spec.get("producer_parts", 1))
    for pp in range(n_pp):
        if spec.get("mode") == "all":
            ks = range(int(spec.get("n_parts", 1)))
        else:
            ks = [int(spec.get("part", 0))]
        # ONE attempt selection per producer partition, threaded into every
        # part read AND the decode-failure tag — re-selecting per part could
        # read (or tag) a different attempt after a concurrent quarantine
        attempt = ex.committed_parts_attempt(pp)
        for k in ks:
            for blob in ex.iter_part(pp, k, attempt=attempt):
                futs.append((pp, attempt, pool.submit(deserialize_page, blob)))
    pages = []
    for pp, attempt, f in futs:
        # frame read fine but failed to DECODE (checksum/magic/dtype):
        # same recovery contract as a truncated read
        with decode_guard(ex.root, pp, attempt):
            pages.append(f.result())
    if not pages:
        return empty_page_for(list(spec.get("symbols", [])), types)
    return _page_from_host_chunks([_page_to_host(p) for p in pages])


def emit_durable_output(spec: Dict, page) -> None:
    """Partition one task's output by the consumer stage's keys and COMMIT
    it to the durable exchange atomically (meta carries the row count the
    coordinator's adaptive replanning reads — no payload).

    The repartition runs as the compiled device epilogue (ops/repartition.py)
    when the layout allows: one D2H of a partition-contiguous page, v2 frames
    sliced from it (LZ4 on the shared I/O pool), empty parts skipped — the
    reader treats a missing part file as []. Nested layouts and the A/B
    kill-switch fall back to the host path."""
    from ..ops.repartition import (
        device_repartition_enabled,
        repartition_frames,
        supports_device_repartition,
    )
    from ..spi.host_pages import (
        host_partition_targets,
        page_to_host as _page_to_host,
        pages_from_host_rows as _pages_from_host_rows,
    )
    from .exchange_spi import exchange_for
    from .failure import InjectedFailure, chaos_category, chaos_fire
    from .serde import serialize_page
    from .spiller import io_pool

    def _after_commit() -> None:
        # chaos site "task_crash_after_commit": the attempt's output IS
        # durable but the task reports FAILED — the retry commits a second
        # attempt and first-committed-wins dedup must keep results exact
        act = chaos_fire(
            "task_crash_after_commit",
            text=f"p{spec.get('partition')}_a{spec.get('attempt', 0)}",
        )
        if act is not None:
            raise InjectedFailure(
                "injected crash after durable commit", category=chaos_category(act)
            )

    ex = exchange_for(spec["dir"])
    sink = ex.part_sink(int(spec["partition"]), int(spec.get("attempt", 0)))
    try:
        n = int(spec.get("n", 1))
        keys = list(spec.get("keys", []))
        out_syms = list(spec.get("symbols", []))
        key_idx = [out_syms.index(k) for k in keys]
        if (
            n > 1
            and keys
            and page.columns
            and device_repartition_enabled()
            and supports_device_repartition(page)
        ):
            blobs, counts = repartition_frames(page, key_idx, n, pool=io_pool())
            for k in range(n):
                cnt = int(counts[k])
                if cnt:
                    sink.add_part(k, blobs[k], rows=cnt)
            sink.commit()
            _after_commit()
            return
        cols = _page_to_host(page)
        rows = len(cols[0][1]) if cols else 0
        if n == 1 or not keys or rows == 0:
            sink.add_part(0, serialize_page(page), rows=rows)
        else:
            target = host_partition_targets(cols, key_idx, n)
            for k in range(n):
                sel = target == k
                cnt = int(np.count_nonzero(sel))
                if cnt:
                    sink.add_part(
                        k, serialize_page(_pages_from_host_rows(cols, sel)), rows=cnt
                    )
        sink.commit()
        _after_commit()
    except Exception:
        sink.abort()
        raise
