"""Out-of-core streaming aggregation (runtime/streaming.py).

ref: operator/Driver.java:372 (page-at-a-time streaming),
SpillableHashAggregationBuilder (bounded aggregation state) — redesigned as
split-at-a-time dispatches of one compiled partial/combine program with a
fixed-capacity device carry.
"""

import numpy as np
import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.streaming import (
    StreamingAggQuery,
    StreamingUnsupported,
    execute_streaming,
)

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       avg(l_quantity) AS avg_qty, avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


@pytest.fixture(scope="module")
def runner():
    # tiny splits force a real multi-split stream at test scale
    r = LocalQueryRunner()
    r.register_catalog("tpch", TpchConnector(scale=0.02, split_target_rows=1 << 13))
    r.session.catalog, r.session.schema = "tpch", "sf0_02"
    return r


def _rows(page):
    act = np.asarray(page.active)
    return [tuple(r) for r, a in zip(page.to_pylist(), act) if a]


def _close(got, ref):
    assert len(got) == len(ref), (len(got), len(ref))
    for rg, rr in zip(got, ref):
        for a, b in zip(rg, rr):
            if isinstance(a, float):
                assert abs(a - b) < max(1e-6, 1e-8 * abs(b)), (a, b)
            else:
                assert a == b, (a, b)


class TestStreamingCorrectness:
    def test_q6_global_aggregate(self, runner):
        plan = runner.plan_sql(Q6)
        q = StreamingAggQuery(plan, runner.metadata, runner.session)
        names, page = q.execute()
        assert q.splits_processed > 4  # genuinely streamed
        _close(_rows(page), [tuple(r) for r in runner.execute(Q6).rows])

    def test_q1_grouped_with_avg_decomposition(self, runner):
        plan = runner.plan_sql(Q1)
        q = StreamingAggQuery(plan, runner.metadata, runner.session)
        names, page = q.execute()
        assert q.splits_processed > 4
        _close(_rows(page), [tuple(r) for r in runner.execute(Q1).rows])

    def test_carry_capacity_bounded(self, runner):
        # the carry page (partial state) must stay at the key-domain size,
        # independent of how many splits streamed through
        plan = runner.plan_sql(Q1)
        q = StreamingAggQuery(plan, runner.metadata, runner.session)
        page = None
        for p in q._split_pages():
            page = jax.jit(lambda pg: q._partial_rel(pg).page)(p)
            break
        assert page.capacity <= 64


import jax  # noqa: E402  (used in the fixture-level lambda above)


class TestStreamingRejections:
    def test_join_rejected(self, runner):
        plan = runner.plan_sql(
            "SELECT count(*) FROM lineitem, orders WHERE l_orderkey = o_orderkey"
        )
        with pytest.raises(StreamingUnsupported):
            execute_streaming(plan, runner.metadata, runner.session)

    def test_unbounded_group_keys_rejected(self, runner):
        # group by a raw bigint key: no bounded domain, carry would be
        # unbounded -> reject (that workload belongs to partitioned spill)
        plan = runner.plan_sql(
            "SELECT l_orderkey, sum(l_quantity) FROM lineitem GROUP BY l_orderkey"
        )
        q = StreamingAggQuery(plan, runner.metadata, runner.session)
        with pytest.raises(StreamingUnsupported):
            q.execute()

    def test_distinct_rejected(self, runner):
        plan = runner.plan_sql(
            "SELECT count(DISTINCT l_suppkey) FROM lineitem"
        )
        with pytest.raises(StreamingUnsupported):
            execute_streaming(plan, runner.metadata, runner.session)
