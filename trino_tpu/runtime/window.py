"""Window function execution (ref: operator/window/WindowOperator.java, §2.5).

Sort-based: rows are sorted by (partition keys, order keys); ranking and
unbounded-frame aggregates are computed with segment operations over partition
boundaries; results scatter back to original row positions via the inverse
permutation. All static shapes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List

import jax
import jax.numpy as jnp

from ..ops import kernels as K
from ..spi.page import Column, Page
from ..spi.types import BIGINT, DOUBLE, DecimalType, is_floating
from ..planner.plan import WindowNode

if TYPE_CHECKING:
    from .executor import PlanExecutor, Relation


def execute_window(executor: "PlanExecutor", rel: "Relation", node: WindowNode):
    from .executor import Relation

    cap = rel.capacity
    active = rel.page.active

    part_cols = [
        (rel.column_for(s).data, rel.column_for(s).valid) for s in node.partition_by
    ]
    # sort: partitions grouped, then order-by within partition
    sort_keys: List[jnp.ndarray] = []
    for data, valid in part_cols:
        sort_keys.append(K.encode_sort_column(data, valid, True, False))
    for o in node.order_by:
        c = rel.column_for(o.symbol)
        sort_keys.append(K.encode_sort_column(c.data, c.valid, o.ascending, o.nulls_first))
    perm = K.lexsort_perm(sort_keys, active) if sort_keys else jnp.arange(cap)
    inv = jnp.zeros(cap, dtype=jnp.int32).at[perm].set(jnp.arange(cap, dtype=jnp.int32))

    active_s = active[perm]
    # partition boundaries
    if part_cols:
        pkeys_s = [K.encode_sort_column(d, v, True, False)[perm] for d, v in part_cols]
        diff = jnp.zeros(cap, dtype=bool)
        for k in pkeys_s:
            diff = diff | (k != jnp.roll(k, 1))
    else:
        diff = jnp.zeros(cap, dtype=bool)
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    prev_active = jnp.roll(active_s, 1).at[0].set(False)
    new_part = active_s & (first | diff | ~prev_active)
    pid = (jnp.cumsum(new_part.astype(jnp.int32)) - 1).astype(jnp.int32)

    # order-key change points (for rank/dense_rank peer groups)
    if node.order_by:
        okeys_s = []
        for o in node.order_by:
            c = rel.column_for(o.symbol)
            okeys_s.append(
                K.encode_sort_column(c.data, c.valid, o.ascending, o.nulls_first)[perm]
            )
        odiff = jnp.zeros(cap, dtype=bool)
        for k in okeys_s:
            odiff = odiff | (k != jnp.roll(k, 1))
        peer_start = new_part | (active_s & odiff)
    else:
        peer_start = new_part

    idx = jnp.arange(cap)
    part_anchor = jax.lax.cummax(jnp.where(new_part, idx, 0))
    peer_anchor = jax.lax.cummax(jnp.where(peer_start, idx, 0))

    out_cols = list(rel.page.columns)
    out_symbols = list(rel.symbols)
    for sym, wf in node.functions:
        name = wf.function
        if name == "row_number":
            vals_s = (idx - part_anchor + 1).astype(jnp.int64)
            col = Column(BIGINT, vals_s[inv], active)
        elif name == "rank":
            vals_s = (peer_anchor - part_anchor + 1).astype(jnp.int64)
            col = Column(BIGINT, vals_s[inv], active)
        elif name == "dense_rank":
            c = jnp.cumsum(peer_start.astype(jnp.int64))
            vals_s = c - c[part_anchor] + 1
            col = Column(BIGINT, vals_s[inv], active)
        elif name in ("lead", "lag"):
            arg = rel.column_for(wf.args[0])
            offset = 1
            shift = -offset if name == "lead" else offset
            data_s = arg.data[perm]
            valid_s = arg.valid[perm]
            rolled = jnp.roll(data_s, shift)
            rolled_valid = jnp.roll(valid_s, shift)
            rolled_pid = jnp.roll(pid, shift)
            same = (rolled_pid == pid) & active_s
            if name == "lead":
                same = same & (jnp.roll(active_s, shift))
            col_data = rolled
            col_valid = same & rolled_valid
            col = Column(arg.type, col_data[inv], col_valid[inv], arg.dictionary)
        elif name in ("sum", "count", "avg", "min", "max"):
            # unbounded frame: aggregate over whole partition, broadcast back
            if wf.args:
                arg = rel.column_for(wf.args[0])
                vals_s = arg.data[perm]
                valid_s = arg.valid[perm]
            else:
                arg = None
                vals_s = jnp.ones(cap, dtype=jnp.int64)
                valid_s = jnp.ones(cap, dtype=jnp.bool_)
            w = active_s & valid_s
            if name == "count":
                agg = K.segment_reduce(w.astype(jnp.int64), w, pid, cap, "count")
                out_type = BIGINT
            elif name in ("min", "max"):
                if jnp.issubdtype(vals_s.dtype, jnp.floating):
                    sent = jnp.inf if name == "min" else -jnp.inf
                else:
                    info = jnp.iinfo(jnp.int64)
                    sent = info.max if name == "min" else info.min
                masked = jnp.where(w, vals_s.astype(jnp.float64 if jnp.issubdtype(vals_s.dtype, jnp.floating) else jnp.int64), sent)
                agg = K.segment_reduce(masked, jnp.ones_like(w), pid, cap, name)
                out_type = wf.output_type
            else:
                acc = jnp.float64 if is_floating(arg.type) else jnp.int64
                agg = K.segment_reduce(vals_s.astype(acc), w, pid, cap, "sum")
                out_type = wf.output_type
                if name == "avg":
                    cnt = K.segment_reduce(w.astype(jnp.int64), w, pid, cap, "count")
                    agg = agg.astype(jnp.float64) / jnp.maximum(cnt, 1)
                    if isinstance(arg.type, DecimalType):
                        agg = agg / float(10**arg.type.scale)
                    out_type = wf.output_type
            vals_back = agg[pid]  # broadcast partition aggregate to rows
            dt = out_type.storage_dtype
            col = Column(
                out_type,
                vals_back.astype(dt)[inv],
                active,
                arg.dictionary if (arg is not None and name in ("min", "max")) else None,
            )
        elif name in ("first_value", "last_value"):
            arg = rel.column_for(wf.args[0])
            data_s = arg.data[perm]
            valid_s = arg.valid[perm]
            if name == "first_value":
                anchor = part_anchor
            else:
                # last active row of partition: reverse cummax trick
                last = jnp.flip(jax.lax.cummax(jnp.flip(jnp.where(new_part, idx, 0))))
                # compute partition end: anchor of next partition minus 1; simpler:
                part_count = K.segment_reduce(active_s.astype(jnp.int64), active_s, pid, cap, "count")
                anchor = part_anchor + jnp.maximum(part_count[pid] - 1, 0).astype(idx.dtype)
            col = Column(
                arg.type, data_s[anchor][inv], valid_s[anchor][inv] & active, arg.dictionary
            )
        else:
            raise NotImplementedError(f"window function {name}")
        out_cols.append(col)
        out_symbols.append(sym)

    return Relation(Page(tuple(out_cols), active), tuple(out_symbols))
