"""Plan optimizer: ordered passes over the logical plan.

Reference blueprint: io.trino.sql.planner.PlanOptimizers (PlanOptimizers.java:275,
~80 passes over 232 iterative rules; SURVEY.md §2.3). Round 1 implements the
highest-leverage subset as whole-plan passes:

- merge_projections     (rule/InlineProjections + removeRedundantIdentityProjections)
- merge_filters         (rule/MergeFilters)
- simplify_predicates   (IR constant simplification)
- pushdown_predicates   (optimizations/PredicatePushDown.java — through Project,
                         Filter into TableScan constraint via TupleDomain extraction)
- prune_columns         (rule/Prune*Columns — restrict every node to needed symbols)
- determine_join_distribution (rule/DetermineJoinDistributionType — broadcast vs
                         partitioned by build-side size estimate)

AddExchanges/fragmentation live in fragmenter.py (separate phase, as in Trino).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..metadata import Metadata, Session
from ..spi.predicate import Domain, Range, TupleDomain
from ..spi.types import BOOLEAN, Type, VarcharType, is_string
from ..sql.ir import Call, Case, CastExpr, Constant, InLut, IrExpr, Reference, references, substitute
from .logical_planner import split_conjuncts, combine_conjuncts
from .plan import (
    AggregationNode,
    EnforceSingleRowNode,
    ExchangeNode,
    FilterNode,
    JoinDistribution,
    JoinKind,
    JoinNode,
    LimitNode,
    LogicalPlan,
    Ordering,
    OutputNode,
    PlanNode,
    ProjectNode,
    SemiJoinNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    VectorTopNNode,
    WindowNode,
    rewrite_plan,
)

TRUE = Constant(BOOLEAN, True)


def optimizer_passes(metadata: Metadata, types: Dict[str, Type], session: Session):
    """The ordered pass pipeline as (rule_name, fn) pairs (ref:
    PlanOptimizers.java:275's sequencing — simplify first so later passes see
    folded constants, push predicates before pruning, cost-based decisions
    last). Named so the sanity plane can report WHICH rule corrupted a plan."""
    from . import rules
    from .stats import make_estimator

    # one estimator shared by the cost-based tail (join reordering inside
    # eliminate_cross_joins builds its own; see stats.make_estimator)
    memo = {}

    def estimator():
        if "e" not in memo:
            memo["e"] = make_estimator(metadata, types, session)
        return memo["e"]

    return [
        ("simplify_expressions", rules.simplify_expressions),
        ("remove_trivial_filters", rules.remove_trivial_filters),
        ("merge_projections", merge_projections),
        ("merge_filters", merge_filters),
        ("extract_common_predicates", extract_common_predicates),
        ("eliminate_cross_joins",
         lambda r: eliminate_cross_joins(r, metadata, types, session)),
        ("pushdown_predicates", lambda r: pushdown_predicates(r, types)),
        ("infer_join_predicates",
         lambda r: rules.infer_join_predicates(r, types)),
        ("pushdown_predicates#2", lambda r: pushdown_predicates(r, types)),
        ("push_filter_through_window", rules.push_filter_through_window),
        ("push_filter_through_sort", rules.push_filter_through_sort),
        ("push_filter_through_aggregation",
         rules.push_filter_through_aggregation),
        ("push_filter_through_union", rules.push_filter_through_union),
        ("push_filter_through_unnest", rules.push_filter_through_unnest),
        ("pushdown_predicates#3", lambda r: pushdown_predicates(r, types)),
        ("merge_adjacent_windows", rules.merge_adjacent_windows),
        ("merge_projections#2", merge_projections),
        ("pushdown_into_scans", lambda r: pushdown_into_scans(r, metadata)),
        ("prune_agg_ordering", rules.prune_agg_ordering),
        ("remove_redundant_sort", rules.remove_redundant_sort),
        ("remove_redundant_enforce_single_row",
         rules.remove_redundant_enforce_single_row),
        ("remove_limit_over_single_row", rules.remove_limit_over_single_row),
        ("merge_limits", rules.merge_limits),
        ("push_limit_through_project", rules.push_limit_through_project),
        ("push_limit_through_union", rules.push_limit_through_union),
        ("push_limit_through_outer_join", rules.push_limit_through_outer_join),
        ("push_topn_through_union", rules.push_topn_through_union),
        ("push_limit_into_scan", rules.push_limit_into_scan),
        ("prune_empty_subplans", rules.prune_empty_subplans),
        ("remove_trivial_filters#2", rules.remove_trivial_filters),
        ("prune_columns", lambda r: prune_columns(r, types)),
        ("push_join_residuals", push_join_residuals),
        ("decompose_long_decimal_aggregates",
         lambda r: rules.decompose_long_decimal_aggregates(r, types)),
        ("merge_projections#3", merge_projections),
        ("flip_join_sides", lambda r: flip_join_sides(r, metadata, estimator())),
        ("determine_join_distribution",
         lambda r: determine_join_distribution(r, metadata, session, estimator())),
        ("sort_limit_to_topn", sort_limit_to_topn),
        ("push_topn_through_project", rules.push_topn_through_project),
        ("merge_limits#2", rules.merge_limits),
        # tensor workload plane: ORDER BY <similarity> LIMIT k -> one fused
        # scores->top-k device program (gated off by default)
        ("fuse_vector_topn", lambda r: fuse_vector_topn(r, session, metadata)),
    ]


def optimize(plan: LogicalPlan, metadata: Metadata, session: Session) -> LogicalPlan:
    """Run the pass pipeline. With the ``validate_plan`` session knob on, the
    plan-sanity checkers (planner/sanity.py) run after EVERY rule — the
    validateIntermediatePlan analogue; the overhead when off is this one flag
    check. Final validation always runs (validateFinalPlan: a corrupt plan
    must never reach a fragmenter or executor, even in production)."""
    from .sanity import validate_final, validate_intermediate

    validate = False
    try:
        validate = bool(session.get("validate_plan"))
    except KeyError:
        pass

    root = plan.root
    for rule_name, fn in optimizer_passes(metadata, plan.types, session):
        root = fn(root)
        if validate:
            validate_intermediate(root, plan.types, rule_name, session=session)
    out = LogicalPlan(root, plan.types)
    validate_final(out, metadata, session, stage="optimize")
    return out


def flip_join_sides(root: PlanNode, metadata: Metadata, estimator=None) -> PlanNode:
    """Put the smaller input on the build (right) side of inner joins
    (ref: the DetermineJoinDistributionType cost comparison that may flip
    sides). Output symbols are looked up by name, so the swap is free."""
    if estimator is None:
        from .stats import StatsEstimator

        estimator = StatsEstimator(metadata, {})

    def fn(node: PlanNode) -> PlanNode:
        if (
            isinstance(node, JoinNode)
            and node.kind == JoinKind.INNER
            and node.criteria
        ):
            l = estimator.rows(node.left)
            r = estimator.rows(node.right)
            if l is not None and r is not None and l < r:
                return replace(
                    node,
                    left=node.right,
                    right=node.left,
                    criteria=tuple((b, a) for a, b in node.criteria),
                )
        return node

    return rewrite_plan(root, fn)


def push_join_residuals(root: PlanNode) -> PlanNode:
    """Push single-sided ON-clause residual conjuncts into the join inputs.

    Valid for INNER (both sides) and for the non-preserved side of outer joins
    (e.g. TPC-H Q13's LEFT JOIN ... AND o_comment NOT LIKE ... filters the build
    input). ref: PredicatePushDown's join handling."""

    def fn(node: PlanNode) -> PlanNode:
        if not (isinstance(node, JoinNode) and node.filter is not None):
            return node
        left_syms = set(node.left.output_symbols)
        right_syms = set(node.right.output_symbols)
        to_left: List[IrExpr] = []
        to_right: List[IrExpr] = []
        remaining: List[IrExpr] = []
        for c in split_conjuncts(node.filter):
            refs = references(c)
            if refs and refs <= left_syms and node.kind in (JoinKind.INNER, JoinKind.CROSS, JoinKind.RIGHT):
                to_left.append(c)
            elif refs and refs <= right_syms and node.kind in (JoinKind.INNER, JoinKind.CROSS, JoinKind.LEFT):
                to_right.append(c)
            else:
                remaining.append(c)
        if not to_left and not to_right:
            return node
        left = node.left
        right = node.right
        if to_left:
            left = FilterNode(source=left, predicate=combine_conjuncts(to_left))
        if to_right:
            right = FilterNode(source=right, predicate=combine_conjuncts(to_right))
        return replace(
            node,
            left=left,
            right=right,
            filter=combine_conjuncts(remaining) if remaining else None,
        )

    return rewrite_plan(root, fn)


# --------------------------------------------------------------------------- #
# projection / filter merging
# --------------------------------------------------------------------------- #


def merge_projections(root: PlanNode) -> PlanNode:
    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, ProjectNode):
            src = node.source
            if isinstance(src, ProjectNode):
                mapping = {s: e for s, e in src.assignments}
                merged = tuple((s, substitute(e, mapping)) for s, e in node.assignments)
                return ProjectNode(source=src.source, assignments=merged)
            if node.is_identity() and node.output_symbols == src.output_symbols:
                return src
        return node

    # iterate to fixpoint (cheap: plans are small)
    prev = None
    while prev is not root:
        prev = root
        root = rewrite_plan(root, fn)
    return root


def merge_filters(root: PlanNode) -> PlanNode:
    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, FilterNode) and isinstance(node.source, FilterNode):
            inner = node.source
            return FilterNode(
                source=inner.source,
                predicate=Call("$and", (inner.predicate, node.predicate), BOOLEAN),
            )
        if isinstance(node, FilterNode) and node.predicate == TRUE:
            return node.source
        return node

    return rewrite_plan(root, fn)


# --------------------------------------------------------------------------- #
# common-predicate extraction (ref: io.trino.sql.ir.optimizer
# ExtractCommonPredicatesExpressionRewriter): or(and(A,B), and(A,C)) ->
# and(A, or(B,C)) — without it TPC-H Q19's join condition stays trapped
# inside the OR and the join planner sees only a cross product.
# --------------------------------------------------------------------------- #


def _factor_or(expr: IrExpr) -> IrExpr:
    if isinstance(expr, Call) and expr.name == "$and":
        return combine_conjuncts([_factor_or(c) for c in split_conjuncts(expr)])
    if not (isinstance(expr, Call) and expr.name == "$or"):
        return expr

    def or_terms(e: IrExpr) -> List[IrExpr]:
        if isinstance(e, Call) and e.name == "$or":
            return or_terms(e.args[0]) + or_terms(e.args[1])
        return [e]

    branches = [split_conjuncts(_factor_or(b)) for b in or_terms(expr)]
    common = [c for c in branches[0] if all(c in b for b in branches[1:])]
    if not common:
        return expr
    residuals = [[c for c in b if c not in common] for b in branches]
    if any(not r for r in residuals):
        # a branch reduced to the common part alone: OR collapses to it
        return combine_conjuncts(common)
    rest: IrExpr = combine_conjuncts(residuals[0])
    for r in residuals[1:]:
        rest = Call("$or", (rest, combine_conjuncts(r)), BOOLEAN)
    return combine_conjuncts(common + [rest])


def extract_common_predicates(root: PlanNode) -> PlanNode:
    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, FilterNode):
            return replace(node, predicate=_factor_or(node.predicate))
        return node

    return rewrite_plan(root, fn)


# --------------------------------------------------------------------------- #
# cross-join elimination (ref: rule/EliminateCrossJoins.java + ReorderJoins'
# join-graph model, optimizations/joins/JoinGraph.java)
# --------------------------------------------------------------------------- #


def eliminate_cross_joins(
    root: PlanNode,
    metadata: Metadata,
    types: Dict[str, Type],
    session: Optional[Session] = None,
) -> PlanNode:
    """Cost-based reordering of flat cross/inner join trees along the
    equi-join graph (ref: rule/EliminateCrossJoins.java + ReorderJoins.java +
    optimizations/joins/JoinGraph.java). Greedy over estimated intermediate
    cardinalities: start from the smallest FILTERED relation, repeatedly add
    the connected relation minimizing the estimated join output — so
    comma-join queries like TPC-H Q5/Q8/Q9 both avoid cross products AND join
    in selectivity order.

    join_reordering_strategy: NONE (keep syntactic order),
    ELIMINATE_CROSS_JOINS (reorder only when a cross product is present),
    AUTOMATIC (reorder any flat inner-join tree of >= 3 relations)."""
    from .stats import join_graph_order, make_estimator

    strategy = str(session.get("join_reordering_strategy")) if session else "AUTOMATIC"
    if strategy == "NONE":
        return root
    estimator = make_estimator(metadata, types, session)

    def fn(node: PlanNode) -> PlanNode:
        if not (isinstance(node, FilterNode) and isinstance(node.source, JoinNode)):
            return node

        # flatten the maximal CROSS/INNER join tree under the filter
        leaves: List[PlanNode] = []
        conjuncts: List[IrExpr] = list(split_conjuncts(node.predicate))
        saw_cross = [False]

        def flatten(n: PlanNode):
            if isinstance(n, JoinNode) and n.kind in (JoinKind.CROSS, JoinKind.INNER):
                if n.kind == JoinKind.CROSS:
                    saw_cross[0] = True
                for l, r in n.criteria:
                    conjuncts.append(
                        Call(
                            "$eq",
                            (Reference(l, types.get(l)), Reference(r, types.get(r))),
                            BOOLEAN,
                        )
                    )
                if n.filter is not None:
                    conjuncts.extend(split_conjuncts(n.filter))
                flatten(n.left)
                flatten(n.right)
            else:
                leaves.append(n)

        flatten(node.source)
        if len(leaves) < 3 or (strategy == "ELIMINATE_CROSS_JOINS" and not saw_cross[0]):
            return node

        # relation index per output symbol
        sym_to_rel: Dict[str, int] = {}
        for i, leaf in enumerate(leaves):
            for s in leaf.output_symbols:
                sym_to_rel[s] = i

        # equi edges + per-leaf local filter conjuncts
        equi_edges: List[Tuple[int, str, int, str]] = []
        leaf_conjuncts: Dict[int, List[IrExpr]] = {}
        for c in conjuncts:
            if isinstance(c, Call) and c.name == "$eq":
                a, b = c.args
                if isinstance(a, Reference) and isinstance(b, Reference):
                    ra, rb = sym_to_rel.get(a.symbol), sym_to_rel.get(b.symbol)
                    if ra is not None and rb is not None and ra != rb:
                        equi_edges.append((ra, a.symbol, rb, b.symbol))
                        continue
            refs = references(c)
            rels = {sym_to_rel.get(s) for s in refs}
            if len(rels) == 1 and None not in rels:
                leaf_conjuncts.setdefault(next(iter(rels)), []).append(c)

        order = join_graph_order(leaves, leaf_conjuncts, equi_edges, estimator)
        if order == list(range(len(leaves))):
            return node  # already optimal under the estimate

        tree: PlanNode = leaves[order[0]]
        for i in order[1:]:
            tree = JoinNode(left=tree, right=leaves[i], kind=JoinKind.CROSS)
        return FilterNode(source=tree, predicate=combine_conjuncts(conjuncts))

    return rewrite_plan(root, fn)


# --------------------------------------------------------------------------- #
# predicate pushdown (ref: optimizations/PredicatePushDown.java)
# --------------------------------------------------------------------------- #


def pushdown_predicates(root: PlanNode, types: Dict[str, Type]) -> PlanNode:
    def fn(node: PlanNode) -> PlanNode:
        if not isinstance(node, FilterNode):
            return node
        src = node.source
        conjuncts = split_conjuncts(node.predicate)

        if isinstance(src, ProjectNode):
            mapping = {s: e for s, e in src.assignments}
            pushable: List[IrExpr] = []
            stuck: List[IrExpr] = []
            for c in conjuncts:
                rewritten = substitute(c, mapping)
                # only push deterministic references (all our IR is deterministic)
                pushable.append(rewritten)
            new_filter = FilterNode(source=src.source, predicate=combine_conjuncts(pushable))
            out: PlanNode = ProjectNode(source=fn(new_filter), assignments=src.assignments)
            return out

        if isinstance(src, JoinNode):
            left_syms = set(src.left.output_symbols)
            right_syms = set(src.right.output_symbols)
            to_left: List[IrExpr] = []
            to_right: List[IrExpr] = []
            remaining: List[IrExpr] = []
            new_criteria: List[Tuple[str, str]] = []
            for c in conjuncts:
                refs = references(c)
                if refs and refs <= left_syms and src.kind in (JoinKind.INNER, JoinKind.CROSS, JoinKind.LEFT):
                    to_left.append(c)
                elif refs and refs <= right_syms and src.kind in (JoinKind.INNER, JoinKind.CROSS, JoinKind.RIGHT):
                    to_right.append(c)
                elif src.kind in (JoinKind.CROSS, JoinKind.INNER):
                    # promote a.x = b.y into join criteria (the EliminateCrossJoins
                    # / PredicatePushDown-into-criteria rule — without this a
                    # comma-join materializes the full cross product)
                    from .logical_planner import as_equi_clause

                    pair = as_equi_clause(c, left_syms, right_syms)
                    if pair is not None:
                        new_criteria.append(pair)
                    else:
                        remaining.append(c)
                else:
                    remaining.append(c)
            left = src.left
            right = src.right
            if to_left:
                left = fn(FilterNode(source=left, predicate=combine_conjuncts(to_left)))
            if to_right:
                right = fn(FilterNode(source=right, predicate=combine_conjuncts(to_right)))
            new_join = replace(src, left=left, right=right)
            if new_criteria:
                new_join = replace(
                    new_join,
                    kind=JoinKind.INNER,
                    criteria=tuple(src.criteria) + tuple(new_criteria),
                )
            if remaining:
                return FilterNode(source=new_join, predicate=combine_conjuncts(remaining))
            return new_join

        if isinstance(src, SemiJoinNode):
            # push conjuncts not referencing the semi-join output below it
            # (so equi conjuncts can reach and re-type the cross join beneath)
            pushable = [c for c in conjuncts if src.output not in references(c)]
            kept = [c for c in conjuncts if src.output in references(c)]
            if pushable:
                new_source = fn(
                    FilterNode(source=src.source, predicate=combine_conjuncts(pushable))
                )
                src = replace(src, source=new_source)
            if kept:
                return FilterNode(source=src, predicate=combine_conjuncts(kept))
            return src

        if isinstance(src, UnionNode):
            new_inputs = []
            for inp, in_syms in zip(src.inputs, src.symbol_mapping):
                mapping = {
                    out_sym: Reference(in_sym, types.get(in_sym))
                    for out_sym, in_sym in zip(src.symbols, in_syms)
                }
                pred = substitute(node.predicate, mapping)
                new_inputs.append(fn(FilterNode(source=inp, predicate=pred)))
            return replace(src, inputs=tuple(new_inputs))

        return node

    return rewrite_plan(root, fn)


def extract_tuple_domain(
    conjuncts: Sequence[IrExpr], symbol_to_column: Dict[str, str]
) -> Tuple[TupleDomain, List[IrExpr]]:
    """Split conjuncts into (TupleDomain over column names, residual conjuncts).
    ref: planner/DomainTranslator.java — the residual keeps full fidelity; the
    domain is only used for pruning (connector may not enforce it)."""
    domains: Dict[str, Domain] = {}
    residual: List[IrExpr] = []

    def const_value(c: Constant):
        # dictionary-code comparisons can't prune generically yet; strings pass
        # through (the tpch generator orders dictionaries so ranges still work
        # when the connector chooses to use them).
        return c.value

    for c in conjuncts:
        handled = False
        if isinstance(c, Call) and c.name in ("$eq", "$lt", "$lte", "$gt", "$gte"):
            a, b = c.args
            ref, const, flipped = None, None, False
            if isinstance(a, Reference) and isinstance(b, Constant):
                ref, const = a, b
            elif isinstance(b, Reference) and isinstance(a, Constant):
                ref, const, flipped = b, a, True
            if ref is not None and ref.symbol in symbol_to_column and const.value is not None:
                col = symbol_to_column[ref.symbol]
                v = const_value(const)
                op = c.name
                if flipped:
                    op = {"$lt": "$gt", "$lte": "$gte", "$gt": "$lt", "$gte": "$lte"}.get(op, op)
                if op == "$eq":
                    dom = Domain(range=Range(v, v))
                elif op == "$lt":
                    dom = Domain(range=Range(None, v, True, False))
                elif op == "$lte":
                    dom = Domain(range=Range(None, v, True, True))
                elif op == "$gt":
                    dom = Domain(range=Range(v, None, False, True))
                else:
                    dom = Domain(range=Range(v, None, True, True))
                domains[col] = domains.get(col, Domain.all()).intersect(dom)
                handled = True
        residual.append(c)
        if handled:
            pass
    return TupleDomain.from_dict(domains), residual


def pushdown_into_scans(root: PlanNode, metadata: Metadata) -> PlanNode:
    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, FilterNode) and isinstance(node.source, TableScanNode):
            scan = node.source
            sym_to_col = {s: c for s, c in scan.assignments}
            conjuncts = split_conjuncts(node.predicate)
            domain, _ = extract_tuple_domain(conjuncts, sym_to_col)
            if domain.domains:
                new_scan = replace(scan, constraint=scan.constraint.intersect(domain))
                return FilterNode(source=new_scan, predicate=node.predicate)
        return node

    return rewrite_plan(root, fn)


# --------------------------------------------------------------------------- #
# column pruning (ref: rule/Prune*Columns)
# --------------------------------------------------------------------------- #


def prune_columns(root: PlanNode, types: Dict[str, Type]) -> PlanNode:
    def prune(node: PlanNode, needed: Set[str]) -> PlanNode:
        if isinstance(node, OutputNode):
            src = prune(node.source, set(node.symbols))
            return replace(node, source=src)
        if isinstance(node, ProjectNode):
            kept = tuple((s, e) for s, e in node.assignments if s in needed)
            child_needed: Set[str] = set()
            for _, e in kept:
                child_needed |= references(e)
            src = prune(node.source, child_needed)
            return ProjectNode(source=src, assignments=kept)
        if isinstance(node, FilterNode):
            child_needed = set(needed) | references(node.predicate)
            return replace(node, source=prune(node.source, child_needed))
        if isinstance(node, TableScanNode):
            kept = tuple((s, c) for s, c in node.assignments if s in needed)
            return replace(node, assignments=kept)
        if isinstance(node, AggregationNode):
            kept_aggs = tuple((s, a) for s, a in node.aggregations if s in needed)
            child_needed = set(node.group_keys)
            for _, a in kept_aggs:
                child_needed |= set(a.args)
                if a.filter:
                    child_needed.add(a.filter)
                child_needed |= {o.symbol for o in a.ordering}
            return replace(
                node,
                source=prune(node.source, child_needed),
                aggregations=kept_aggs,
            )
        if isinstance(node, JoinNode):
            child_needed = set(needed)
            for l, r in node.criteria:
                child_needed.add(l)
                child_needed.add(r)
            if node.filter is not None:
                child_needed |= references(node.filter)
            left = prune(node.left, child_needed & set(node.left.output_symbols) | {l for l, _ in node.criteria})
            right = prune(node.right, child_needed & set(node.right.output_symbols) | {r for _, r in node.criteria})
            return replace(node, left=left, right=right)
        if isinstance(node, SemiJoinNode):
            child_needed = (set(needed) | {node.source_key}) & set(node.source.output_symbols) | {node.source_key}
            src = prune(node.source, child_needed)
            filt = prune(node.filtering_source, {node.filtering_key})
            return replace(node, source=src, filtering_source=filt)
        if isinstance(node, (SortNode, TopNNode)):
            child_needed = set(needed) | {o.symbol for o in node.orderings}
            return replace(node, source=prune(node.source, child_needed))
        if isinstance(node, WindowNode):
            kept_fns = tuple((s, f) for s, f in node.functions if s in needed)
            child_needed = set(needed) & set(node.source.output_symbols)
            child_needed |= set(node.partition_by) | {o.symbol for o in node.order_by}
            for _, f in kept_fns:
                child_needed |= set(f.args)
            return replace(node, source=prune(node.source, child_needed), functions=kept_fns)
        if isinstance(node, LimitNode):
            return replace(node, source=prune(node.source, needed))
        if isinstance(node, EnforceSingleRowNode):
            return replace(node, source=prune(node.source, needed))
        if isinstance(node, UnionNode):
            keep_idx = [i for i, s in enumerate(node.symbols) if s in needed]
            if not keep_idx:
                keep_idx = [0] if node.symbols else []
            new_symbols = tuple(node.symbols[i] for i in keep_idx)
            new_mapping = []
            new_inputs = []
            for inp, in_syms in zip(node.inputs, node.symbol_mapping):
                kept_in = tuple(in_syms[i] for i in keep_idx)
                new_inputs.append(prune(inp, set(kept_in)))
                new_mapping.append(kept_in)
            return UnionNode(
                inputs=tuple(new_inputs),
                symbols=new_symbols,
                symbol_mapping=tuple(new_mapping),
            )
        if isinstance(node, ValuesNode):
            return node
        if isinstance(node, ExchangeNode):
            return replace(node, source=prune(node.source, needed | set(node.partition_keys)))
        # default: conservative — require everything
        new_sources = tuple(prune(s, set(s.output_symbols)) for s in node.sources)
        return node.with_sources(new_sources)

    return prune(root, set(root.output_symbols))


# --------------------------------------------------------------------------- #
# join distribution + TopN
# --------------------------------------------------------------------------- #


def estimate_rows(node: PlanNode, metadata: Metadata) -> Optional[float]:
    """Back-compat shim over the full estimator (planner/stats.py)."""
    from .stats import StatsEstimator

    return StatsEstimator(metadata, {}).rows(node)


def determine_join_distribution(
    root: PlanNode, metadata: Metadata, session: Session, estimator=None
) -> PlanNode:
    """ref: rule/DetermineJoinDistributionType.java — broadcast small build
    sides (estimated with filter selectivity, not just base-table size)."""
    threshold = session.get("broadcast_join_threshold_rows")
    mode = session.get("join_distribution_type")
    if estimator is None:
        from .stats import StatsEstimator

        estimator = StatsEstimator(metadata, {})

    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, JoinNode) and node.distribution == JoinDistribution.AUTO:
            if mode == "BROADCAST":
                return replace(node, distribution=JoinDistribution.BROADCAST)
            if mode == "PARTITIONED":
                return replace(node, distribution=JoinDistribution.PARTITIONED)
            build_rows = estimator.rows(node.right)
            if build_rows is not None and build_rows <= threshold:
                return replace(node, distribution=JoinDistribution.BROADCAST)
            return replace(node, distribution=JoinDistribution.PARTITIONED)
        return node

    return rewrite_plan(root, fn)


def sort_limit_to_topn(root: PlanNode) -> PlanNode:
    """ref: rule/CreatePartialTopN precursor — Limit(Sort) -> TopN."""

    def fn(node: PlanNode) -> PlanNode:
        if isinstance(node, LimitNode) and node.count >= 0 and node.offset == 0:
            if isinstance(node.source, SortNode):
                return TopNNode(
                    source=node.source.source,
                    count=node.count,
                    orderings=node.source.orderings,
                )
        return node

    return rewrite_plan(root, fn)


def fuse_vector_topn(
    root: PlanNode, session: Session, metadata: Optional[Metadata] = None
) -> PlanNode:
    """Tensor workload plane: ``ORDER BY <similarity> LIMIT k`` as ONE
    scores -> top-k device program (ref arXiv:2306.08367). Recognizes
    ``TopN(Project)`` where the LEADING ordering symbol is a projection
    assignment computing a vector-similarity (or model-scoring) expression;
    the pair fuses into a VectorTopNNode the executor runs as a single jit
    program, reusing the serial path's compiled expression closures and the
    stable TopN sort kernels — the unfused Project + TopN pair is the
    bit-identity oracle. Gated on ``tensor_plane`` AND ``vector_topk_fusion``
    (both default off; off = byte-identical plans)."""
    try:
        enabled = bool(session.get("tensor_plane")) and bool(
            session.get("vector_topk_fusion")
        )
    except KeyError:
        enabled = False
    if not enabled:
        return root
    from ..ops.tensor import on_topk_fallback, walk_vector_calls

    def fn(node: PlanNode) -> PlanNode:
        if not (
            isinstance(node, TopNNode)
            and not node.partial
            and node.count >= 0
            and isinstance(node.source, ProjectNode)
            and node.orderings
        ):
            return node
        project = node.source
        assigned = {s: e for s, e in project.assignments}
        lead = assigned.get(node.orderings[0].symbol)
        if lead is None or not any(True for _ in walk_vector_calls(lead)):
            return node  # not a similarity ordering — not this plane's shape
        missing = [
            o.symbol for o in node.orderings if o.symbol not in assigned
        ]
        if missing:
            # a similarity ordering whose secondary keys bypass the scoring
            # projection: the fused node cannot produce them — labeled
            # fallback (the serial pair still answers the query)
            on_topk_fallback("unprojected_order_key")
            return node
        fused = VectorTopNNode(
            source=project.source,
            assignments=project.assignments,
            count=node.count,
            orderings=node.orderings,
        )
        return _maybe_ann_rewrite(fused, session, metadata)

    return rewrite_plan(root, fn)


def _maybe_ann_rewrite(
    node: VectorTopNNode, session: Session, metadata: Optional[Metadata]
) -> VectorTopNNode:
    """ANN serving tier: under ``ann_mode=approx``, a fused vector top-k
    whose source is a direct scan of an IVF-indexed table gets a centroid
    probe spec pushed into the scan handle — ``get_splits`` then returns only
    the ``nprobe`` nearest clusters, pruning splits the way partition pruning
    does. Declined (exact scan kept) whenever any precondition fails: the
    probe must target the indexed vector column with a constant query, and
    the lead ordering direction must actually want the NEAREST rows (DESC for
    similarities, ASC for l2 distance) — the pruned clusters hold far rows,
    so a FARTHEST-first ordering would lose exactly the rows it wants."""
    from ..knobs import resolve_ann_mode
    from ..ops.tensor import constant_vector_value, split_query_constant

    if metadata is None:
        return node
    try:
        mode, nprobe = resolve_ann_mode(session.get("ann_mode"))
    except KeyError:
        return node
    if mode != "approx":
        return node
    if nprobe is None:
        try:
            nprobe = int(session.get("ann_nprobe") or 1)
        except KeyError:
            nprobe = 1
    scan = node.source
    if not isinstance(scan, TableScanNode):
        return node
    assigned = {s: e for s, e in node.assignments}
    lead = assigned.get(node.orderings[0].symbol)
    parts = split_query_constant(lead) if lead is not None else None
    if parts is None:
        return node
    sim, col_expr, const = parts
    asc = node.orderings[0].ascending
    if (sim == "l2_distance") != asc:
        return node  # ordering wants the farthest rows — pruning is unsound
    if not isinstance(col_expr, Reference):
        return node
    column = {s: c for s, c in scan.assignments}.get(col_expr.symbol)
    if column is None:
        return node
    q = constant_vector_value(const)
    if q is None:
        return node
    try:
        connector = metadata.connector_for(scan.table)
    except Exception:  # noqa: BLE001 — planner knobs degrade, never fail
        return node
    probe = getattr(connector, "ann_probe_handle", None)
    if probe is None:
        return node  # connector has no index tier
    new_handle = probe(scan.table, column, q, max(1, int(nprobe)), sim)
    if new_handle is None:
        return node
    return replace(node, source=replace(scan, table=new_handle))
