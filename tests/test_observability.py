"""Metrics, tracing spans, and the spool SPI.

Model: the reference's spi/metrics + JMX exposure, its OpenTelemetry span
instrumentation (TracingMetadata planning spans), and spi/spool
SpoolingManager + the spooled client protocol (protocol/spooling).
"""

import json
import urllib.request

import pytest


@pytest.fixture(scope="module")
def server():
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.server.coordinator import CoordinatorServer

    r = LocalQueryRunner.tpch(scale=0.001)
    srv = CoordinatorServer(r)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    from trino_tpu.client.client import StatementClient

    return StatementClient(f"http://{server.address}")


class TestMetrics:
    def test_prometheus_rendering(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("test_total", help="a test counter").inc(3)
        reg.gauge("test_gauge", {"pool": "a"}).set(7)
        text = reg.render()
        assert "# TYPE test_total counter" in text
        assert "test_total 3" in text
        assert 'test_gauge{pool="a"} 7' in text

    def test_endpoint_counts_queries(self, server, client):
        client.execute("SELECT 1")
        text = (
            urllib.request.urlopen(f"http://{server.address}/v1/metrics")
            .read()
            .decode()
        )
        assert "trino_tpu_queries_submitted_total" in text
        assert "trino_tpu_queries_finished_total" in text


class TestTracing:
    def test_span_tree(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with tr.span("root") as root:
            with tr.span("child"):
                pass
        spans = tr.trace(root.trace_id)
        assert [s["name"] for s in spans] == ["root", "child"]
        child = spans[1]
        assert child["parentSpanId"] == spans[0]["spanId"]
        assert child["durationMs"] is not None

    def test_error_recorded(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom") as s:
                raise ValueError("nope")
        assert "ValueError" in s.attributes["error"]

    def test_query_trace_endpoint(self, server, client):
        res = client.execute("SELECT count(*) FROM nation")
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/query/{res.query_id}/trace"
            ).read()
        )
        names = [s["name"] for s in info["spans"]]
        assert names == ["query", "planner", "optimizer", "execution"]


class TestSpool:
    def test_manager_roundtrip(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path))
        h = m.create_segment(b"payload", rows=3)
        assert m.get_segment(h.segment_id) == b"payload"
        m.delete_segment(h.segment_id)
        assert m.get_segment(h.segment_id) is None

    def test_ttl_eviction(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path), ttl_secs=0.0)
        h1 = m.create_segment(b"a", rows=1)
        m.create_segment(b"b", rows=1)  # triggers eviction of h1
        assert h1.segment_id not in m.list_segments()

    def test_spooled_protocol_matches_inline(self, client):
        inline = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey"
        )
        spooled = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey",
            data_encoding="json",
        )
        assert spooled.rows == inline.rows

    def test_spooled_lz4(self, client):
        from trino_tpu.native import native_available

        if not native_available():
            pytest.skip("native lz4 unavailable")
        spooled = client.execute(
            "SELECT n_nationkey FROM nation ORDER BY n_nationkey",
            data_encoding="json+lz4",
        )
        assert len(spooled.rows) == 25

    def test_segments_acked_and_freed(self, server, client):
        client.execute("SELECT n_name FROM nation", data_encoding="json")
        # the client acks (DELETEs) every segment it fetched
        assert server.spooling.list_segments() == []


class TestMetricsPrecision:
    def test_large_counter_full_precision(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("big_total").inc(12_345_678)
        assert "big_total 12345678" in reg.render()


class TestSchemaFilterRules:
    def test_table_scoped_deny_does_not_hide_schema(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"schema": "sales", "table": "secret", "privileges": []},
                    {"schema": "sales", "privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["sales"]) == ["sales"]

    def test_whole_schema_deny_hides(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"user": "bob", "schema": "secret", "privileges": []},
                    {"privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["secret", "open"]) == ["open"]
        assert ac.filter_schemas("alice", "c", ["secret"]) == ["secret"]
