"""Native (C++) runtime kernels, loaded via ctypes.

Built on demand with g++ (baked toolchain) and cached next to the source; falls
back to a pure-Python store codec when no compiler is available, so the engine
never hard-depends on the native build.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    # The binary is keyed by a content hash of the source so a stale (or
    # tampered/committed) .so is never dlopen'd as-is: binaries are always
    # rebuilt from the reviewed source on content change, never shipped in git
    # (*.so is gitignored).
    src = os.path.join(os.path.dirname(__file__), "pageserde.cpp")
    try:
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        out = os.path.join(os.path.dirname(__file__), f"_pageserde-{digest}.so")
        if not os.path.exists(out):
            tmp = out + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", tmp, src],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, out)
        lib = ctypes.CDLL(out)
    except (OSError, subprocess.CalledProcessError):
        return None
    lib.lz4_compress.restype = ctypes.c_int64
    lib.lz4_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.lz4_decompress.restype = ctypes.c_int64
    lib.lz4_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.lz4_max_compressed.restype = ctypes.c_int64
    lib.lz4_max_compressed.argtypes = [ctypes.c_int64]
    lib.hash64.restype = ctypes.c_uint64
    lib.hash64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
        return _LIB


def native_available() -> bool:
    return get_lib() is not None


def lz4_compress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native serde not available")
    n = len(data)
    cap = lib.lz4_max_compressed(n)
    dst = ctypes.create_string_buffer(cap)
    written = lib.lz4_compress(data, n, dst, cap)
    if written < 0:
        raise RuntimeError("lz4_compress failed")
    return dst.raw[:written]


def lz4_decompress(data: bytes, raw_len: int) -> bytes:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native serde not available")
    dst = ctypes.create_string_buffer(raw_len)
    written = lib.lz4_decompress(data, len(data), dst, raw_len)
    if written != raw_len:
        raise ValueError(f"lz4_decompress: corrupt frame ({written} != {raw_len})")
    return dst.raw


def hash64(data: bytes) -> int:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native serde not available")
    return int(lib.hash64(data, len(data)))
