"""Operator-state spill (memory revoke) tests.

Coverage model: the reference's spill suites — TestHashAggregationOperator
spill cases (SpillableHashAggregationBuilder), spilling HashBuilderOperator
tests, and BaseFailureRecoveryTest's result-parity discipline: every spilled
execution must produce EXACTLY the unspilled plan's answer.
"""

import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.executor import PlanExecutor


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def run_spilled(runner, sql, threshold=2000):
    """Execute with a tiny revoke threshold; returns (rows, executor)."""
    runner.session.set("spill_operator_threshold_bytes", threshold)
    try:
        plan = runner.plan_sql(sql)
        ex = PlanExecutor(plan, runner.metadata, runner.session)
        names, page = ex.execute()
        return page.to_pylist(), ex
    finally:
        runner.session.set("spill_operator_threshold_bytes", 0)


def check_parity(runner, sql, order=True):
    want = runner.execute(sql).rows
    got, ex = run_spilled(runner, sql)
    assert ex.spill_count > 0, "spill threshold was not triggered"
    if not order:
        got, want = sorted(got, key=repr), sorted(want, key=repr)
    assert got == want
    return ex


class TestSpilledAggregation:
    def test_high_cardinality_group_by(self, runner):
        ex = check_parity(
            runner,
            "SELECT l_orderkey, sum(l_quantity), count(*) FROM lineitem "
            "GROUP BY l_orderkey",
            order=False,
        )
        assert ex.spilled_bytes > 0

    def test_group_by_string_key(self, runner):
        check_parity(
            runner,
            "SELECT l_shipmode, sum(l_extendedprice), avg(l_discount) "
            "FROM lineitem GROUP BY l_shipmode",
            order=False,
        )

    def test_group_by_with_having_and_order(self, runner):
        check_parity(
            runner,
            "SELECT l_suppkey, count(*) c FROM lineitem GROUP BY l_suppkey "
            "HAVING count(*) > 5 ORDER BY c DESC, l_suppkey LIMIT 20",
        )


class TestSpilledJoin:
    def test_inner_join(self, runner):
        check_parity(
            runner,
            "SELECT count(*), sum(l_extendedprice) FROM lineitem "
            "JOIN orders ON l_orderkey = o_orderkey",
        )

    def test_left_join_unmatched_preserved(self, runner):
        check_parity(
            runner,
            "SELECT count(*), count(l_orderkey) FROM orders "
            "LEFT JOIN lineitem ON o_orderkey = l_orderkey "
            "AND l_quantity > 49",
        )

    def test_full_join(self, runner):
        check_parity(
            runner,
            "SELECT count(*) FROM "
            "(SELECT o_orderkey k FROM orders WHERE o_orderkey < 1000) a "
            "FULL JOIN "
            "(SELECT l_orderkey k FROM lineitem WHERE l_orderkey > 500) b "
            "ON a.k = b.k",
            order=False,
        )

    def test_string_key_join(self, runner):
        check_parity(
            runner,
            "SELECT n_name, count(*) FROM nation JOIN customer "
            "ON n_nationkey = c_nationkey GROUP BY n_name",
            order=False,
        )

    def test_join_then_aggregation_both_spill(self, runner):
        ex = check_parity(
            runner,
            "SELECT o_custkey, sum(l_extendedprice) FROM lineitem "
            "JOIN orders ON l_orderkey = o_orderkey GROUP BY o_custkey",
            order=False,
        )
        # both the join and the aggregation revoked (>= 2 partition sets)
        assert ex.spill_count >= 4


class TestSourceConcurrency:
    """Intra-node source parallelism (LocalExchange.java:66 analogue): the
    task_concurrency session property loads splits on concurrent host
    threads; results must be bit-identical to the serial path."""

    def test_concurrent_scan_parity(self, runner):
        sql = ("SELECT l_returnflag, count(*), sum(l_quantity) FROM lineitem "
               "GROUP BY l_returnflag ORDER BY l_returnflag")
        want = runner.execute(sql).rows
        runner.session.set("task_concurrency", 4)
        try:
            got = runner.execute(sql).rows
        finally:
            runner.session.set("task_concurrency", 1)
        assert got == want

    def test_concurrent_scan_preserves_split_order(self, runner):
        # split order carries connector-declared sort order; verify rows
        # arrive in orderkey order without an ORDER BY re-sort
        runner.session.set("task_concurrency", 4)
        try:
            rows = runner.execute(
                "SELECT o_orderkey FROM orders WHERE o_orderkey < 50"
            ).rows
        finally:
            runner.session.set("task_concurrency", 1)
        keys = [r[0] for r in rows]
        assert keys == sorted(keys)
