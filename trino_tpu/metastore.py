"""Metastore-lite: table + partition catalog over the filesystem API.

Reference blueprint: lib/trino-metastore (Table/Partition/Column model,
HiveMetastore interface) + plugin/trino-hive's FileHiveMetastore (the
metastore that stores its own state as JSON files under the warehouse —
exactly this design, minus thrift). State layout:

    <warehouse>/_metastore/<schema>/<table>.json

Each table document records columns, partition columns, data format, the
table's storage location, and the partition list (values -> location).
Everything goes through :mod:`trino_tpu.fs`, so pointing the warehouse at
an object-store scheme needs no code changes here.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .fs import FileSystemManager, Location


@dataclass(frozen=True)
class MetaColumn:
    name: str
    type_name: str


@dataclass(frozen=True)
class MetaPartition:
    """One partition: its key values (aligned with partition_columns) and
    storage location relative to the table location."""

    values: Tuple[str, ...]
    location: str


@dataclass
class MetaTable:
    schema: str
    table: str
    columns: List[MetaColumn]
    partition_columns: List[str] = field(default_factory=list)
    format: str = "parquet"
    location: str = ""
    partitions: List[MetaPartition] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "schema": self.schema,
            "table": self.table,
            "columns": [{"name": c.name, "type": c.type_name} for c in self.columns],
            "partitionColumns": list(self.partition_columns),
            "format": self.format,
            "location": self.location,
            "partitions": [
                {"values": list(p.values), "location": p.location}
                for p in self.partitions
            ],
        }

    @staticmethod
    def from_json(doc: dict) -> "MetaTable":
        return MetaTable(
            schema=doc["schema"],
            table=doc["table"],
            columns=[MetaColumn(c["name"], c["type"]) for c in doc["columns"]],
            partition_columns=list(doc.get("partitionColumns", [])),
            format=doc.get("format", "parquet"),
            location=doc.get("location", ""),
            partitions=[
                MetaPartition(tuple(p["values"]), p["location"])
                for p in doc.get("partitions", [])
            ],
        )


class FileMetastore:
    """ref: plugin/trino-hive FileHiveMetastore — JSON documents under the
    warehouse, one per table; add_partition is read-modify-write behind the
    filesystem's atomic put."""

    def __init__(self, fs_manager: FileSystemManager, warehouse: str):
        self.fs_manager = fs_manager
        self.warehouse = Location.parse(warehouse)

    def _fs(self):
        return self.fs_manager.for_location(self.warehouse)

    def _doc_location(self, schema: str, table: str) -> Location:
        return self.warehouse.child("_metastore", schema, f"{table}.json")

    # ------------------------------------------------------------------- api

    def create_table(self, t: MetaTable) -> None:
        loc = self._doc_location(t.schema, t.table)
        if self._fs().exists(loc):
            raise ValueError(f"table already exists: {t.schema}.{t.table}")
        if not t.location:
            t.location = self.warehouse.child(t.schema, t.table).uri()
        self._fs().write(loc, json.dumps(t.to_json(), indent=1).encode())

    def drop_table(self, schema: str, table: str) -> None:
        self._fs().delete(self._doc_location(schema, table))

    def get_table(self, schema: str, table: str) -> Optional[MetaTable]:
        loc = self._doc_location(schema, table)
        if not self._fs().exists(loc):
            return None
        return MetaTable.from_json(json.loads(self._fs().read(loc)))

    def list_tables(self, schema: Optional[str] = None) -> List[Tuple[str, str]]:
        prefix = (
            self.warehouse.child("_metastore", schema)
            if schema
            else self.warehouse.child("_metastore")
        )
        out = []
        for entry in self._fs().list_files(prefix):
            if not entry.location.path.endswith(".json"):
                continue
            parts = entry.location.path.rsplit("/", 2)
            out.append((parts[-2], parts[-1][: -len(".json")]))
        return sorted(out)

    def add_partition(self, schema: str, table: str, part: MetaPartition) -> None:
        t = self.get_table(schema, table)
        if t is None:
            raise ValueError(f"table not found: {schema}.{table}")
        if len(part.values) != len(t.partition_columns):
            raise ValueError("partition values do not match partition columns")
        if all(p.values != part.values for p in t.partitions):
            t.partitions.append(part)
            self._fs().write(
                self._doc_location(schema, table),
                json.dumps(t.to_json(), indent=1).encode(),
            )

    def get_partitions(
        self, schema: str, table: str, filters: Optional[Dict[str, str]] = None
    ) -> List[MetaPartition]:
        """Partitions, optionally pruned by exact key=value filters (the
        HiveMetastore getPartitionsByFilter slice the connector needs)."""
        t = self.get_table(schema, table)
        if t is None:
            return []
        out = []
        for p in t.partitions:
            if filters:
                vals = dict(zip(t.partition_columns, p.values))
                if any(vals.get(k) != v for k, v in filters.items()):
                    continue
            out.append(p)
        return out
