"""Planner-connected single-program ICI execution (parallel/mesh_runner.py).

The round-2 unification: real SQL plans from the fragmenter execute as ONE
shard_map program over the 8-device mesh — REPARTITION as all_to_all,
GATHER/BROADCAST as all_gather — parity-checked against single-device
execution (the DistributedQueryRunner-vs-local model of SURVEY.md §4).
"""

import numpy as np
import pytest

import jax

from trino_tpu.runtime import LocalQueryRunner


N_DEV = 8
SCALE = 0.001


@pytest.fixture(scope="module")
def mesh_runner():
    from trino_tpu.parallel.mesh_runner import MeshQueryRunner

    if len(jax.devices()) < N_DEV:
        pytest.skip(f"need {N_DEV} devices")
    return MeshQueryRunner.tpch(scale=SCALE, n_devices=N_DEV)


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=SCALE)


def check(mesh_runner, local, sql, sort=False):
    got = mesh_runner.execute(sql).rows
    want = local.execute(sql).rows
    if sort:
        got, want = sorted(got), sorted(want)
    assert got == want


class TestMeshParity:
    def test_global_agg(self, mesh_runner, local):
        check(mesh_runner, local, "SELECT count(*), sum(l_quantity) FROM lineitem")

    def test_q6_filter_agg(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            """SELECT sum(l_extendedprice * l_discount) FROM lineitem
               WHERE l_shipdate >= DATE '1994-01-01'
                 AND l_shipdate < DATE '1995-01-01'
                 AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",
        )

    def test_q1_groupby_repartition(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            """SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*),
                      avg(l_extendedprice)
               FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
               GROUP BY l_returnflag, l_linestatus
               ORDER BY l_returnflag, l_linestatus""",
        )

    def test_high_cardinality_groupby(self, mesh_runner, local):
        # forces the sort-based path per shard + all_to_all of partials
        check(
            mesh_runner,
            local,
            """SELECT l_orderkey, count(*) FROM lineitem
               GROUP BY l_orderkey ORDER BY l_orderkey LIMIT 50""",
        )

    def test_join_repartitioned(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey",
        )

    def test_q3_two_joins_topn(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            """SELECT o_orderkey, sum(l_extendedprice * (1 - l_discount)) AS rev
               FROM customer JOIN orders ON c_custkey = o_custkey
               JOIN lineitem ON l_orderkey = o_orderkey
               WHERE c_mktsegment = 'BUILDING'
                 AND o_orderdate < DATE '1995-03-15'
               GROUP BY o_orderkey ORDER BY rev DESC LIMIT 10""",
        )

    def test_left_join(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            """SELECT count(*), count(l_orderkey) FROM orders
               LEFT JOIN lineitem ON o_orderkey = l_orderkey
                 AND l_quantity > 45""",
        )

    def test_semi_join(self, mesh_runner, local):
        check(
            mesh_runner,
            local,
            """SELECT count(*) FROM orders WHERE o_orderkey IN
               (SELECT l_orderkey FROM lineitem WHERE l_quantity > 45)""",
        )

    def test_distributed_runner_uses_mesh(self):
        """DistributedQueryRunner's tier-1 path gives the same results."""
        from trino_tpu.parallel.runner import DistributedQueryRunner

        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
        assert bool(r.session.get("use_ici_exchange"))
        got = r.execute(
            "SELECT l_returnflag, count(*) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        local = LocalQueryRunner.tpch(scale=SCALE)
        want = local.execute(
            "SELECT l_returnflag, count(*) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        assert got == want


class TestMeshLoweringGuards:
    def test_cross_join_falls_back_correctly(self):
        # cross joins get no exchange: SPMD execution would pair only same-
        # shard blocks — the runner must detect this and use the staged path
        from trino_tpu.parallel.runner import DistributedQueryRunner

        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
        assert r.execute("SELECT count(*) FROM nation CROSS JOIN region").rows == [
            (25 * 5,)
        ]

    def test_scan_union_values_falls_back_correctly(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner

        if len(jax.devices()) < 4:
            pytest.skip("need 4 devices")
        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
        got = r.execute(
            "SELECT count(*) FROM "
            "(SELECT n_name, x FROM nation CROSS JOIN (VALUES (1)) t(x)) u"
        ).rows
        assert got == [(25,)]

    def test_mesh_rejects_cross_join(self, mesh_runner):
        from trino_tpu.parallel.mesh_runner import MeshLoweringError

        with pytest.raises(MeshLoweringError):
            mesh_runner.execute("SELECT count(*) FROM nation CROSS JOIN region")

    def test_program_cache_reused(self, mesh_runner, local):
        sql = "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"
        mesh_runner.execute(sql)
        before = len(mesh_runner._program_cache)
        got = mesh_runner.execute(sql).rows
        assert len(mesh_runner._program_cache) == before
        assert got == local.execute(sql).rows


class TestMeshStringKeys:
    def test_string_key_join_across_dictionaries(self):
        """Repartition must route the same string to the same shard even when
        the two join sides carry different dictionaries (codes are local)."""
        from trino_tpu.parallel.runner import DistributedQueryRunner

        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=8)
        r.session.set("join_distribution_type", "PARTITIONED")
        try:
            got = r.execute(
                "SELECT t.k, s.v FROM (VALUES ('apple'), ('banana'), ('cherry'), "
                "('fig')) t(k) JOIN (VALUES ('banana', 1), ('cherry', 2), "
                "('grape', 3)) s(k, v) ON t.k = s.k ORDER BY t.k"
            ).rows
        finally:
            r.session.properties.pop("join_distribution_type", None)
        assert got == [("banana", 1), ("cherry", 2)]


class TestMeshCapacityRetry:
    def test_join_overflow_retries(self, mesh_runner, local):
        # 1:N expansion beyond probe capacity: initial static capacity
        # overflows, the runner must retry with a doubled factor — same result
        mesh_runner.session.properties["mesh_join_capacity_factor"] = 0.01
        try:
            check(
                mesh_runner,
                local,
                "SELECT count(*) FROM orders JOIN lineitem ON o_orderkey = l_orderkey",
            )
        finally:
            mesh_runner.session.properties.pop("mesh_join_capacity_factor")


class TestDistributedSort:
    """Range-shuffle + per-shard sort + merge gather (the dist-sort path;
    ref docs admin/dist-sort.md, operator/MergeOperator.java)."""

    def test_order_by_full_table(self, mesh_runner, local):
        check(
            mesh_runner, local,
            "SELECT l_orderkey, l_linenumber, l_quantity FROM lineitem "
            "ORDER BY l_quantity, l_orderkey, l_linenumber",
        )

    def test_order_by_desc_with_nulls(self, mesh_runner, local):
        check(
            mesh_runner, local,
            "SELECT o_orderkey, o_totalprice FROM orders "
            "ORDER BY o_totalprice DESC, o_orderkey",
        )

    def test_order_by_string_key(self, mesh_runner, local):
        check(
            mesh_runner, local,
            "SELECT c_name, c_custkey FROM customer ORDER BY c_name",
        )

    def test_order_by_after_join(self, mesh_runner, local):
        check(
            mesh_runner, local,
            "SELECT o_orderkey, o_totalprice, c_name FROM orders "
            "JOIN customer ON o_custkey = c_custkey "
            "ORDER BY o_totalprice DESC, o_orderkey LIMIT 1000",
        )

    def test_plan_uses_range_partitioning(self, mesh_runner):
        from trino_tpu.planner.fragmenter import Partitioning

        subplan = mesh_runner.plan_distributed(
            "SELECT l_orderkey FROM lineitem ORDER BY l_orderkey"
        )
        parts = [f.partitioning for f in subplan.fragments]
        assert Partitioning.FIXED_RANGE in parts


class TestTierObservability:
    """Which queries lower to the single-program ICI tier vs fall back, and
    why — the round-2 review asked for exactly this tracking."""

    def test_tpch_ladder_tiers(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner

        r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=8)
        lowered = {}
        for name, sql in {
            "q6": "SELECT sum(l_extendedprice * l_discount) FROM lineitem "
                  "WHERE l_discount BETWEEN 0.05 AND 0.07",
            "q1": "SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1",
            "join": "SELECT count(*) FROM lineitem JOIN orders "
                    "ON l_orderkey = o_orderkey",
            "cross": "SELECT count(*) FROM nation, region",
        }.items():
            r.execute(sql)
            lowered[name] = (r.last_tier, r.last_tier_reason)
        assert lowered["q6"][0] == "ici"
        assert lowered["q1"][0] == "ici"
        assert lowered["join"][0] == "ici"
        # cross joins are a documented mesh rejection — staged, with a reason
        assert lowered["cross"][0] == "staged"
        assert "cross" in (lowered["cross"][1] or "")
