"""Host-side page utilities shared by every layer that moves rows through
host memory: the DCN exchange tiers, the out-of-core bucket store, the FTE
data plane, worker output partitioning, and bucketed connector writes.

Living in the SPI keeps the layering upright — connectors (e.g. the memory
connector's bucketed writes) must not import the distribution scheduler to
split a page. ref: the reference's analogous split is spi/Page utilities vs
engine-side PagePartitioner (operator/output/PagePartitioner.java), which
share the spi block model.

A "host chunk" is ``[(type, data, valid, dictionary), ...]`` — one numpy
triple per column, compacted to active rows.
"""

from __future__ import annotations

import struct
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .page import Column, Dictionary, Page

_INT64_MIN = np.int64(np.iinfo(np.int64).min)
_INT64_MAX = np.int64(np.iinfo(np.int64).max)


def host_order_key(d: np.ndarray) -> np.ndarray:
    """Host mirror of kernels.order_key (floats: sign-magnitude bit unfold)."""
    if d.dtype.kind == "f":
        bits = np.ascontiguousarray(d, dtype=np.float64).view(np.int64)
        return np.where(bits < 0, np.bitwise_xor(~bits, _INT64_MIN), bits)
    return d.astype(np.int64)


def hash_partition_host(cols: List, n: int) -> np.ndarray:
    """Host mirror of parallel.exchange.partition_ids (same 64-bit mix, same
    NULL-sentinel and float order-key normalization). ``cols``: (data, valid)."""
    acc = np.full(cols[0][0].shape, 0x9E3779B97F4A7C15, dtype=np.uint64)
    for d, v in cols:
        k = np.where(v, host_order_key(d), _INT64_MAX)
        x = k.astype(np.uint64)
        x = (x ^ (x >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
        x = (x ^ (x >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
        x = x ^ (x >> np.uint64(33))
        acc = (acc ^ x) * np.uint64(0x100000001B3)
    return (acc % np.uint64(n)).astype(np.int64)


def host_partition_targets(cols: List, key_idx: List[int], n: int) -> np.ndarray:
    """Row -> consumer partition for host column specs [(type, data, valid,
    dict), ...]. THE single host-side repartition rule: dictionary-coded keys
    hash by content-stable VALUE keys (codes are dictionary-local — producers
    of one exchange can carry different vocabularies, and the same string must
    land on one consumer partition); no keys = everything to partition of
    hash(0)."""
    nrows = len(cols[0][1]) if cols else 0
    keys = []
    for i in key_idx:
        _, data, valid, dictionary = cols[i]
        if dictionary is not None:
            lut = dictionary.value_keys()
            data = lut[np.clip(data, 0, len(lut) - 1)]
        keys.append((data, valid))
    keys = keys or [
        (np.zeros(nrows, dtype=np.int64), np.ones(nrows, dtype=np.bool_))
    ]
    return hash_partition_host(keys, n)


def page_to_host(page: Page):
    """Device Page -> host chunk, compacted to active rows."""
    active = np.asarray(page.active)
    return [
        (c.type, np.asarray(c.data)[active], np.asarray(c.valid)[active], c.dictionary)
        for c in page.columns
    ]


def page_from_host_chunks(chunks: List[List], capacity: Optional[int] = None) -> Page:
    """Merge host chunks from multiple producers into one Page. Columns whose
    chunks carry DIFFERENT dictionaries are re-encoded into a merged sorted
    dictionary — codes are only comparable within one dictionary. ``capacity``
    pads the page (static-shape discipline: callers bucket to powers of two
    so varying row counts share compiled programs)."""
    merged = []
    for i in range(len(chunks[0])):
        type_ = chunks[0][i][0]
        dicts = [c[i][3] for c in chunks]
        real = [d for d in dicts if d is not None]
        if real and len({d.fingerprint() for d in real}) > 1:
            merged_values = sorted(set().union(*[list(d.values) for d in real]))
            dictionary = Dictionary(np.asarray(merged_values, dtype=object))
            code_of = {s: c for c, s in enumerate(merged_values)}
            datas = []
            for c in chunks:
                col = c[i]
                if col[3] is None:
                    datas.append(np.zeros_like(col[1]))
                    continue
                lut = np.array([code_of[s] for s in col[3].values], dtype=col[1].dtype)
                datas.append(lut[np.clip(col[1], 0, len(lut) - 1)])
            data = np.concatenate(datas)
        else:
            data = np.concatenate([c[i][1] for c in chunks])
            dictionary = real[0] if real else None
        valid = np.concatenate([c[i][2] for c in chunks])
        merged.append((type_, data, valid, dictionary))
    n = len(merged[0][1]) if merged else 0
    cap = max(capacity or 0, n, 1)
    cols = tuple(
        Column.from_numpy(tp, d, v, capacity=cap, dictionary=dc)
        for tp, d, v, dc in merged
    )
    active = np.zeros(cap, dtype=np.bool_)
    active[:n] = True
    return Page(cols, jnp.asarray(active))


def pages_from_host_rows(col_specs, row_sel: np.ndarray) -> Page:
    cols = []
    n = int(row_sel.sum()) if row_sel.dtype == bool else len(row_sel)
    for type_, data, valid, dictionary in col_specs:
        d = data[row_sel]
        v = valid[row_sel]
        cols.append(
            Column.from_numpy(type_, d, v, capacity=max(len(d), 1), dictionary=dictionary)
        )
    if not cols:
        return Page((), jnp.zeros((1,), dtype=jnp.bool_))
    cap = cols[0].capacity
    active = np.zeros(cap, dtype=np.bool_)
    active[:n] = True
    return Page(tuple(cols), jnp.asarray(active))


# --------------------------------------------------------------------------- #
# LZ4 spill files: numpy arrays -> one compressed file (the out-of-core bucket
# store's disk format). Each array compresses independently, so a thread pool
# can (de)compress all of a chunk's columns in parallel — the reference's
# parallel LZ4 spill (io.trino.spiller.FileSingleStreamSpiller, one spill
# executor thread per stream). Format, little-endian:
#   magic 'TPS1' | narrays u32
#   per array: dtype_len u8 | dtype_str | ndim u8 | dim u64 * ndim |
#              codec u8 (0=raw, 1=lz4) | raw_len u64 | comp_len u64 | payload
# --------------------------------------------------------------------------- #

_SPILL_MAGIC = b"TPS1"
_SPILL_MIN_COMPRESS = 64  # tiny buffers aren't worth an LZ4 round-trip


def _pack_array(a: np.ndarray) -> bytes:
    from .. import native

    raw = np.ascontiguousarray(a).tobytes()
    codec, payload = 0, raw
    if native.native_available() and len(raw) >= _SPILL_MIN_COMPRESS:
        comp = native.lz4_compress(raw)
        if len(comp) < len(raw):
            codec, payload = 1, comp
    ds = a.dtype.str.encode()
    head = struct.pack("<B", len(ds)) + ds + struct.pack("<B", a.ndim)
    head += struct.pack(f"<{a.ndim}Q", *a.shape) if a.ndim else b""
    head += struct.pack("<BQQ", codec, len(raw), len(payload))
    return head + payload


def _unpack_array(blob: bytes) -> np.ndarray:
    from .. import native

    (ds_len,) = struct.unpack_from("<B", blob, 0)
    off = 1
    dtype = np.dtype(blob[off : off + ds_len].decode())
    off += ds_len
    (ndim,) = struct.unpack_from("<B", blob, off)
    off += 1
    shape = struct.unpack_from(f"<{ndim}Q", blob, off) if ndim else ()
    off += 8 * ndim
    codec, raw_len, comp_len = struct.unpack_from("<BQQ", blob, off)
    off += struct.calcsize("<BQQ")
    payload = blob[off : off + comp_len]
    if codec == 1:
        payload = native.lz4_decompress(payload, raw_len)
    return np.frombuffer(payload, dtype=dtype).reshape(shape)


def write_arrays_lz4(path: str, arrays: List[np.ndarray], pool=None) -> None:
    """Compress ``arrays`` (in parallel on ``pool`` when given) and write one
    spill file. Callers already running ON the pool pass ``pool=None`` —
    fanning out from inside a pool job deadlocks a saturated executor."""
    packs = list(pool.map(_pack_array, arrays)) if pool is not None else [
        _pack_array(a) for a in arrays
    ]
    with open(path, "wb") as f:
        f.write(_SPILL_MAGIC + struct.pack("<I", len(packs)))
        for p in packs:
            f.write(struct.pack("<Q", len(p)))
            f.write(p)


def read_arrays_lz4(path: str, pool=None) -> List[np.ndarray]:
    """Read a spill file back; decompression parallelizes on ``pool``."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != _SPILL_MAGIC:
        raise ValueError(f"bad spill file magic in {path}")
    (n,) = struct.unpack_from("<I", data, 4)
    off = 4 + 4
    blobs = []
    for _ in range(n):
        (blen,) = struct.unpack_from("<Q", data, off)
        off += 8
        blobs.append(data[off : off + blen])
        off += blen
    if pool is not None:
        return list(pool.map(_unpack_array, blobs))
    return [_unpack_array(b) for b in blobs]


def empty_page_for(symbols, types) -> Page:
    """A 1-row all-inactive Page with the symbols' storage layouts (what an
    empty exchange input or empty table scan materializes as). String columns
    carry the sentinel empty dictionary so downstream string predicates still
    compile against the layout."""
    from .types import is_string

    cols = []
    for s in symbols:
        t = types[s]
        lanes = t.storage_lanes
        shape = (1,) if lanes is None else (1, lanes)
        cols.append(
            Column(
                t,
                jnp.zeros(shape, dtype=t.storage_dtype),
                jnp.zeros((1,), dtype=jnp.bool_),
                Dictionary.empty() if is_string(t) else None,
            )
        )
    return Page(tuple(cols), jnp.zeros((1,), dtype=jnp.bool_))
