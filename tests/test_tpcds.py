"""TPC-DS connector + star-join queries vs pandas oracle
(ref: plugin/trino-tpcds + BASELINE.json config #4 query family)."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu.connectors import tpcds as ds
from trino_tpu.metadata import Session
from trino_tpu.runtime import LocalQueryRunner

SCALE = 0.001


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpcds", schema="sf0_001"))
    r.register_catalog("tpcds", ds.TpcdsConnector(scale=SCALE))
    return r


def df(table):
    conn = ds.TpcdsConnector(scale=SCALE)
    total = conn.split_count(table, SCALE)
    frames = []
    for s in range(total):
        data, count = ds.generate_split(table, SCALE, s, total)
        cols = {}
        for cname, tname, _ in ds._TABLES[table]:
            arr = data[cname]
            d = conn.dictionary(table, cname, SCALE)
            if d is not None:
                cols[cname] = d.decode(arr.astype(np.int64))
            elif tname.startswith("decimal"):
                cols[cname] = arr / 100.0
            else:
                cols[cname] = arr
        frames.append(pd.DataFrame(cols))
    return pd.concat(frames, ignore_index=True)


class TestTpcdsData:
    def test_date_dim_calendar(self, runner):
        res = runner.execute(
            "SELECT d_year, count(*) FROM date_dim GROUP BY 1 ORDER BY 1"
        )
        years = {y: c for y, c in res.rows}
        assert years[1992] == 366  # leap year
        assert years[1995] == 365

    def test_split_invariance(self):
        a, _ = ds.generate_split("store_sales", SCALE, 0, 1)
        parts = [ds.generate_split("store_sales", SCALE, s, 3)[0] for s in range(3)]
        b = np.concatenate([p["ss_item_sk"] for p in parts])
        assert np.array_equal(a["ss_item_sk"], b)


class TestTpcdsQueries:
    def test_q3_shape(self, runner):
        res = runner.execute(
            """
            SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) sum_agg
            FROM date_dim, store_sales, item
            WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
              AND i_manufact_id <= 50 AND d_moy = 11
            GROUP BY d_year, i_brand_id, i_brand
            ORDER BY d_year, sum_agg DESC, i_brand_id
            LIMIT 10
            """
        )
        dd, ss, it = df("date_dim"), df("store_sales"), df("item")
        m = (
            ss.merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(it[it.i_manufact_id <= 50], left_on="ss_item_sk", right_on="i_item_sk")
        )
        g = (
            m.groupby(["d_year", "i_brand_id", "i_brand"])["ss_ext_sales_price"].sum()
            .reset_index()
            .sort_values(["d_year", "ss_ext_sales_price", "i_brand_id"],
                         ascending=[True, False, True])
            .head(10)
        )
        assert len(res.rows) == len(g)
        for got, r in zip(res.rows, g.itertuples()):
            assert got[0] == r.d_year and got[1] == int(r.i_brand_id)
            assert abs(got[3] - r.ss_ext_sales_price) <= 1e-6 * max(1, abs(r.ss_ext_sales_price))

    def test_q42_shape(self, runner):
        res = runner.execute(
            """
            SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
            FROM date_dim, store_sales, item
            WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
              AND d_moy = 12 AND d_year = 2000
            GROUP BY d_year, i_category_id, i_category
            ORDER BY s DESC, d_year, i_category_id, i_category
            """
        )
        dd, ss, it = df("date_dim"), df("store_sales"), df("item")
        m = (
            ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 2000)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        )
        g = (
            m.groupby(["d_year", "i_category_id", "i_category"])["ss_ext_sales_price"]
            .sum().reset_index()
            .sort_values(["ss_ext_sales_price", "i_category_id"], ascending=[False, True])
        )
        assert [r[1] for r in res.rows] == [int(x) for x in g.i_category_id]

    def test_q52_shape(self, runner):
        res = runner.execute(
            """
            SELECT d_year, i_brand_id, sum(ss_ext_sales_price) AS ext_price
            FROM date_dim, store_sales, item
            WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
              AND i_manufact_id <= 100 AND d_moy = 11 AND d_year = 1999
            GROUP BY d_year, i_brand_id
            ORDER BY d_year, ext_price DESC, i_brand_id LIMIT 5
            """
        )
        dd, ss, it = df("date_dim"), df("store_sales"), df("item")
        m = (
            ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
            .merge(it[it.i_manufact_id <= 100], left_on="ss_item_sk", right_on="i_item_sk")
        )
        g = (
            m.groupby(["d_year", "i_brand_id"])["ss_ext_sales_price"].sum().reset_index()
            .sort_values(["ss_ext_sales_price", "i_brand_id"], ascending=[False, True])
            .head(5)
        )
        assert [r[1] for r in res.rows] == [int(x) for x in g.i_brand_id]

    def test_store_join_with_dimension_filter(self, runner):
        res = runner.execute(
            "SELECT s_state, count(*) FROM store_sales, store "
            "WHERE ss_store_sk = s_store_sk GROUP BY 1 ORDER BY 1"
        )
        ss, st = df("store_sales"), df("store")
        g = (
            ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
            .groupby("s_state").size().reset_index(name="c").sort_values("s_state")
        )
        assert res.rows == [tuple(r) for r in g.itertuples(index=False)]
