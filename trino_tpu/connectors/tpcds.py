"""TPC-DS connector — the full 24-table schema.

Reference blueprint: plugin/trino-tpcds (SURVEY.md §2.9; TpcdsConnectorFactory,
TpcdsMetadata table list). Same architecture as the tpch connector:
deterministic canonical-chunk generation (split-layout invariant,
process-stable seeding), sorted vocabularies so strings are int32 codes,
range-partitioned surrogate keys, julian-day date_sk values like dsdgen.

Data distributions follow dsdgen's *shapes* (calendar-correct date_dim/
time_dim, brand/class/category hierarchies, consistent fact price chains:
list -> sales -> ext_* -> net_paid -> net_profit) without being bit-identical;
correctness tests compare against a pandas oracle over the same generated
data (tests/test_tpcds.py), mirroring how the reference verifies tpch queries
against H2 (H2QueryRunner).

Deviations from dsdgen, declared: returns rows are generated independently of
sales rows (same FK ranges, not the same order/ticket numbers), and slowly-
changing-dimension rec_start/rec_end versioning collapses to one current row.
Nullable foreign keys carry ~4%% NULLs like dsdgen's fact FKs.
"""

from __future__ import annotations

import datetime
import hashlib
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Column, Dictionary, Page
from ..spi.predicate import TupleDomain
from ..spi.types import parse_type

EPOCH = datetime.date(1970, 1, 1)

# dsdgen: d_date_sk is the julian day number; 2415022 == 1900-01-02, the first
# date_dim row. 73049 rows span 1900-01-02 .. 2100-01-01.
JULIAN_BASE = 2415022
DATE_START = datetime.date(1900, 1, 2)
N_DATES = 73049
# sales activity lives in 1998-01-02 .. 2002-12-31 (5 years, like dsdgen)
SALES_LO = JULIAN_BASE + (datetime.date(1998, 1, 2) - DATE_START).days
SALES_HI = JULIAN_BASE + (datetime.date(2002, 12, 31) - DATE_START).days

# ---------------------------------------------------------------------------
# vocabularies (sorted, so dictionary code order == lexicographic order)
# ---------------------------------------------------------------------------
CATEGORIES = sorted(
    ["Books", "Children", "Electronics", "Home", "Jewelry",
     "Men", "Music", "Shoes", "Sports", "Women"]
)
CLASSES = sorted(f"class{i:02d}" for i in range(1, 17))
DAY_NAMES = sorted(
    ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday", "Sunday"]
)
QUARTER_NAMES = sorted(
    f"{y}Q{q}" for y in range(1900, 2101) for q in range(1, 5)
)
N_BRANDS = 250
BRANDS = sorted(f"Brand #{i}" for i in range(1, N_BRANDS + 1))
MANUFACTS = sorted(f"manufact{i:04d}" for i in range(1, 1001))
STORE_NAMES = sorted(["able", "ation", "bar", "cally", "eing", "ese", "ought", "anti"])
STATES = sorted(["AL", "CA", "GA", "IL", "KS", "MI", "MN", "NY", "OH", "TN", "TX", "WA"])
COUNTIES = sorted(f"{w} County" for w in
                  ["Ziebach", "Walker", "Daviess", "Barrow", "Fairfield",
                   "Bronx", "Maverick", "Mesa", "Raleigh", "Luce"])
CITIES = sorted(["Fairview", "Midway", "Oakland", "Centerville", "Liberty",
                 "Glenwood", "Springdale", "Riverside", "Union", "Salem"])
STREET_NAMES = sorted(["Main", "Oak", "Park", "Elm", "Lake", "Hill", "Pine",
                       "Maple", "Cedar", "River"])
STREET_TYPES = sorted(["ST", "AVE", "BLVD", "RD", "CT", "DR", "LN", "PKWY", "WAY", "CIR"])
ZIPS = sorted(f"{z:05d}" for z in range(10000, 10100))
# sorted(): the Dictionary invariant is code-order == lexicographic order
STREET_NUMBERS = tuple(sorted(str(i) for i in range(1, 1001)))
SUITE_NUMBERS = tuple(sorted(f"Suite {i}" for i in range(100)))
COUNTRY = ("United States",)
GENDERS = ("F", "M")
MARITAL = sorted(["D", "M", "S", "U", "W"])
EDUCATION = sorted(["2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
                    "Primary", "Secondary", "Unknown"])
CREDIT_RATING = sorted(["Good", "High Risk", "Low Risk", "Unknown"])
BUY_POTENTIAL = sorted(["0-500", "1001-5000", "501-1000", ">10000", "5001-10000", "Unknown"])
SALUTATIONS = sorted(["Dr.", "Miss", "Mr.", "Mrs.", "Ms.", "Sir"])
FIRST_NAMES = sorted(["James", "John", "Robert", "Michael", "William", "David",
                      "Mary", "Patricia", "Linda", "Barbara", "Elizabeth", "Jennifer"])
LAST_NAMES = sorted(["Smith", "Johnson", "Williams", "Brown", "Jones", "Miller",
                     "Davis", "Garcia", "Rodriguez", "Wilson", "Martinez", "Anderson"])
COUNTRIES = sorted(["United States", "Canada", "Mexico", "Germany", "France",
                    "Japan", "Brazil", "India", "China", "Australia"])
YN = ("N", "Y")
AMPM = ("AM", "PM")
SHIFTS = sorted(["first", "second", "third"])
SUB_SHIFTS = sorted(["afternoon", "evening", "morning", "night"])
MEALS = sorted(["breakfast", "dinner", "lunch", ""])
SM_TYPES = sorted(["EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"])
SM_CODES = sorted(["AIR", "GROUND", "SEA", "SHIP"])
SM_CARRIERS = sorted(["AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "CARDINAL",
                      "DHL", "DIAMOND", "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF",
                      "LATVIAN", "MSC", "ORIENTAL", "PRIVATECARRIER", "RUPEKSA",
                      "TBS", "UPS", "USPS", "ZHOU"])
REASONS = sorted(["Did not fit", "Did not get it on time", "Did not like the color",
                  "Did not like the model", "Did not like the warranty",
                  "Found a better price", "Gift exchange", "Item was damaged",
                  "Lost my job", "Changed my mind", "Item is not the product I wanted",
                  "No reason given", "Package was damaged", "Parts missing",
                  "Wrong size", "Not working any more", "Duplicate purchase",
                  "Bought too many", "Ordered wrong item", "Unauthorized purchase",
                  "Did not believe the description", "Too expensive",
                  "Not the product that was ordered", "Product did not work",
                  "Stopped working", "Found a better extended warranty",
                  "Warranty too expensive", "Delivery took too long",
                  "Did not want it any more", "Poor quality", "Wrong color",
                  "Wrong model", "Defective item", "Missing accessories", "Other"])
ITEM_SIZES = sorted(["N/A", "economy", "extra large", "large", "medium", "petite", "small"])
ITEM_COLORS = sorted(["almond", "antique", "aquamarine", "azure", "beige", "bisque",
                      "black", "blue", "brown", "chartreuse", "coral", "cream",
                      "cyan", "dark", "gold", "green", "indigo", "ivory", "khaki",
                      "lavender", "magenta", "maroon", "navy", "olive", "orange",
                      "pink", "plum", "puff", "purple", "red", "rose", "saddle",
                      "salmon", "sienna", "silver", "sky", "slate", "smoke", "snow",
                      "spring", "steel", "tan", "thistle", "tomato", "turquoise",
                      "violet", "white", "yellow"])
ITEM_UNITS = sorted(["Box", "Bunch", "Bundle", "Carton", "Case", "Cup", "Dozen",
                     "Dram", "Each", "Gram", "Gross", "Lb", "N/A", "Ounce",
                     "Pallet", "Pound", "Tbl", "Ton", "Tsp", "Unknown"])
ITEM_CONTAINERS = ("Unknown",)
ITEM_FORMULATIONS = sorted(f"formulation {i:03d}" for i in range(1, 101))
ITEM_DESCS = sorted(f"Item description {i:04d} for testing." for i in range(1, 301))
PRODUCT_NAMES = sorted(f"product{i:05d}" for i in range(1, 501))
MANAGERS = sorted(f"Manager {i:03d}" for i in range(1, 101))
MKT_DESCS = sorted(f"Market segment description {i:03d}" for i in range(1, 51))
DIVISION_NAMES = sorted(["able", "ation", "bar", "ese", "anti", "cally"])
COMPANY_NAMES = sorted(["Unknown", "ableanti", "amalgamalg", "brandbrand",
                        "corpcorp", "edu pack", "exportiunivamalg", "importoamalg",
                        "maxicorp", "univmaxi"])
HOURS = sorted(["8AM-12AM", "8AM-4PM", "8AM-8AM"])
GEOGRAPHY = ("Unknown",)
CC_CLASSES = sorted(["large", "medium", "small"])
CP_DEPARTMENTS = ("DEPARTMENT",)
CP_TYPES = sorted(["bi-annual", "monthly", "quarterly"])
WEB_NAMES = sorted(["site_0", "site_1", "site_2", "site_3", "site_4", "site_5"])
WP_TYPES = sorted(["ad", "dynamic", "feedback", "general", "order", "protected", "welcome"])
WP_URLS = ("http://www.foo.com",)
PROMO_NAMES = sorted(["able", "anti", "bar", "cally", "eing", "ese", "ought"])
PROMO_PURPOSES = ("Unknown",)
CHANNEL_DETAILS = sorted(f"channel details {i:03d}" for i in range(1, 101))
W_NAMES = sorted(["Bad cards must make.", "Conventional childr", "Doors canno",
                  "Important issues liv", "Rooms cook "])

# ---------------------------------------------------------------------------
# per-column generator specs
#
# ("sk",)                surrogate key (row index + 1; date/time use offsets)
# ("id", prefix, base)   per-row unique id string over base-table row count
# ("v", vocab)           uniform random code over a sorted vocabulary
# ("vn", vocab, p)       same with NULL probability p
# ("vmod", vocab)        deterministic (sk-1) % len(vocab)
# ("i", lo, hi)          uniform integer [lo, hi)
# ("in", lo, hi, p)      same with NULLs
# ("d", lo, hi)          decimal cents in [lo, hi)
# ("fk", table, p)       foreign key into table's sk range, NULL prob p
# ("fkdate", p)          julian date_sk in the sales window
# ("fktime", p)          time_sk 0..86399
# ("seq", k)             (sk-1)//k + 1 (ticket/order grouping)
# ("cdate", iso)         constant DATE
# None                   computed in a per-table special section
# ---------------------------------------------------------------------------

F = 0.04  # dsdgen-like fact FK null rate

_TABLES: Dict[str, List[Tuple[str, str, object]]] = {
    "date_dim": [
        ("d_date_sk", "bigint", None),
        ("d_date_id", "varchar(16)", None),
        ("d_date", "date", None),
        ("d_month_seq", "integer", None),
        ("d_week_seq", "integer", None),
        ("d_quarter_seq", "integer", None),
        ("d_year", "integer", None),
        ("d_dow", "integer", None),
        ("d_moy", "integer", None),
        ("d_dom", "integer", None),
        ("d_qoy", "integer", None),
        ("d_fy_year", "integer", None),
        ("d_fy_quarter_seq", "integer", None),
        ("d_fy_week_seq", "integer", None),
        ("d_day_name", "varchar(9)", None),
        ("d_quarter_name", "varchar(6)", None),
        ("d_holiday", "varchar(1)", None),
        ("d_weekend", "varchar(1)", None),
        ("d_following_holiday", "varchar(1)", None),
        ("d_first_dom", "integer", None),
        ("d_last_dom", "integer", None),
        ("d_same_day_ly", "integer", None),
        ("d_same_day_lq", "integer", None),
        ("d_current_day", "varchar(1)", None),
        ("d_current_week", "varchar(1)", None),
        ("d_current_month", "varchar(1)", None),
        ("d_current_quarter", "varchar(1)", None),
        ("d_current_year", "varchar(1)", None),
    ],
    "time_dim": [
        ("t_time_sk", "bigint", None),
        ("t_time_id", "varchar(16)", None),
        ("t_time", "integer", None),
        ("t_hour", "integer", None),
        ("t_minute", "integer", None),
        ("t_second", "integer", None),
        ("t_am_pm", "varchar(2)", None),
        ("t_shift", "varchar(20)", None),
        ("t_sub_shift", "varchar(20)", None),
        ("t_meal_time", "varchar(20)", None),
    ],
    "item": [
        ("i_item_sk", "bigint", ("sk",)),
        ("i_item_id", "varchar(16)", ("id", "AAAAAAAA", "item")),
        ("i_rec_start_date", "date", ("cdate", "1997-10-27")),
        ("i_rec_end_date", "date", ("cdate", None)),
        ("i_item_desc", "varchar(200)", ("v", ITEM_DESCS)),
        ("i_current_price", "decimal(7,2)", ("d", 99, 10000)),
        ("i_wholesale_cost", "decimal(7,2)", ("d", 50, 7000)),
        ("i_brand_id", "integer", None),
        ("i_brand", "varchar(50)", None),
        ("i_class_id", "integer", None),
        ("i_class", "varchar(50)", None),
        ("i_category_id", "integer", None),
        ("i_category", "varchar(50)", None),
        ("i_manufact_id", "integer", None),
        ("i_manufact", "varchar(50)", None),
        ("i_size", "varchar(20)", ("v", ITEM_SIZES)),
        ("i_formulation", "varchar(20)", ("v", ITEM_FORMULATIONS)),
        ("i_color", "varchar(20)", ("v", ITEM_COLORS)),
        ("i_units", "varchar(10)", ("v", ITEM_UNITS)),
        ("i_container", "varchar(10)", ("v", ITEM_CONTAINERS)),
        ("i_manager_id", "integer", ("i", 1, 101)),
        ("i_product_name", "varchar(50)", ("v", PRODUCT_NAMES)),
    ],
    "customer": [
        ("c_customer_sk", "bigint", ("sk",)),
        ("c_customer_id", "varchar(16)", ("id", "AAAAAAAA", "customer")),
        ("c_current_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("c_current_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("c_current_addr_sk", "bigint", ("fk", "customer_address", 0.0)),
        ("c_first_shipto_date_sk", "bigint", ("fkdate", F)),
        ("c_first_sales_date_sk", "bigint", ("fkdate", F)),
        ("c_salutation", "varchar(10)", ("vn", SALUTATIONS, 0.03)),
        ("c_first_name", "varchar(20)", ("vn", FIRST_NAMES, 0.03)),
        ("c_last_name", "varchar(30)", ("vn", LAST_NAMES, 0.03)),
        ("c_preferred_cust_flag", "varchar(1)", ("vn", YN, 0.03)),
        ("c_birth_day", "integer", ("in", 1, 29, 0.03)),
        ("c_birth_month", "integer", ("in", 1, 13, 0.03)),
        ("c_birth_year", "integer", ("in", 1924, 1993, 0.03)),
        ("c_birth_country", "varchar(20)", ("vn", COUNTRIES, 0.03)),
        ("c_login", "varchar(13)", ("vn", ("",), 1.0)),
        ("c_email_address", "varchar(50)", ("id", "EMAIL", "customer")),
        ("c_last_review_date_sk", "bigint", ("fkdate", F)),
    ],
    "customer_address": [
        ("ca_address_sk", "bigint", ("sk",)),
        ("ca_address_id", "varchar(16)", ("id", "AAAAAAAA", "customer_address")),
        ("ca_street_number", "varchar(10)", ("vmod", STREET_NUMBERS)),
        ("ca_street_name", "varchar(60)", ("v", STREET_NAMES)),
        ("ca_street_type", "varchar(15)", ("v", STREET_TYPES)),
        ("ca_suite_number", "varchar(10)", ("vmod", SUITE_NUMBERS)),
        ("ca_city", "varchar(60)", ("v", CITIES)),
        ("ca_county", "varchar(30)", ("v", COUNTIES)),
        ("ca_state", "varchar(2)", ("v", STATES)),
        ("ca_zip", "varchar(10)", ("v", ZIPS)),
        ("ca_country", "varchar(20)", ("v", COUNTRY)),
        ("ca_gmt_offset", "decimal(5,2)", None),
        ("ca_location_type", "varchar(20)", ("v", ("apartment", "condo", "single family"))),
    ],
    "customer_demographics": [
        ("cd_demo_sk", "bigint", ("sk",)),
        ("cd_gender", "varchar(1)", None),
        ("cd_marital_status", "varchar(1)", None),
        ("cd_education_status", "varchar(20)", None),
        ("cd_purchase_estimate", "integer", None),
        ("cd_credit_rating", "varchar(10)", None),
        ("cd_dep_count", "integer", None),
        ("cd_dep_employed_count", "integer", None),
        ("cd_dep_college_count", "integer", None),
    ],
    "household_demographics": [
        ("hd_demo_sk", "bigint", ("sk",)),
        ("hd_income_band_sk", "bigint", None),
        ("hd_buy_potential", "varchar(15)", None),
        ("hd_dep_count", "integer", None),
        ("hd_vehicle_count", "integer", None),
    ],
    "income_band": [
        ("ib_income_band_sk", "bigint", ("sk",)),
        ("ib_lower_bound", "integer", None),
        ("ib_upper_bound", "integer", None),
    ],
    "store": [
        ("s_store_sk", "bigint", ("sk",)),
        ("s_store_id", "varchar(16)", ("id", "AAAAAAAA", "store")),
        ("s_rec_start_date", "date", ("cdate", "1997-03-13")),
        ("s_rec_end_date", "date", ("cdate", None)),
        ("s_closed_date_sk", "bigint", ("fkdate", 0.7)),
        ("s_store_name", "varchar(50)", ("vmod", STORE_NAMES)),
        ("s_number_employees", "integer", ("i", 200, 301)),
        ("s_floor_space", "integer", ("i", 5000000, 10000001)),
        ("s_hours", "varchar(20)", ("vmod", HOURS)),
        ("s_manager", "varchar(40)", ("v", MANAGERS)),
        ("s_market_id", "integer", ("i", 1, 11)),
        ("s_geography_class", "varchar(100)", ("v", GEOGRAPHY)),
        ("s_market_desc", "varchar(100)", ("v", MKT_DESCS)),
        ("s_market_manager", "varchar(40)", ("v", MANAGERS)),
        ("s_division_id", "integer", ("i", 1, 2)),
        ("s_division_name", "varchar(50)", ("v", DIVISION_NAMES)),
        ("s_company_id", "integer", ("i", 1, 2)),
        ("s_company_name", "varchar(50)", ("v", COMPANY_NAMES)),
        ("s_street_number", "varchar(10)", ("vmod", STREET_NUMBERS)),
        ("s_street_name", "varchar(60)", ("v", STREET_NAMES)),
        ("s_street_type", "varchar(15)", ("v", STREET_TYPES)),
        ("s_suite_number", "varchar(10)", ("vmod", SUITE_NUMBERS)),
        ("s_city", "varchar(60)", ("v", CITIES)),
        ("s_county", "varchar(30)", ("v", COUNTIES)),
        ("s_state", "varchar(2)", ("v", STATES)),
        ("s_zip", "varchar(10)", ("v", ZIPS)),
        ("s_country", "varchar(20)", ("v", COUNTRY)),
        ("s_gmt_offset", "decimal(5,2)", None),
        ("s_tax_precentage", "decimal(5,2)", ("d", 0, 12)),
    ],
    "warehouse": [
        ("w_warehouse_sk", "bigint", ("sk",)),
        ("w_warehouse_id", "varchar(16)", ("id", "AAAAAAAA", "warehouse")),
        ("w_warehouse_name", "varchar(20)", ("vmod", W_NAMES)),
        ("w_warehouse_sq_ft", "integer", ("i", 50000, 1000001)),
        ("w_street_number", "varchar(10)", ("vmod", STREET_NUMBERS)),
        ("w_street_name", "varchar(60)", ("v", STREET_NAMES)),
        ("w_street_type", "varchar(15)", ("v", STREET_TYPES)),
        ("w_suite_number", "varchar(10)", ("vmod", SUITE_NUMBERS)),
        ("w_city", "varchar(60)", ("v", CITIES)),
        ("w_county", "varchar(30)", ("v", COUNTIES)),
        ("w_state", "varchar(2)", ("v", STATES)),
        ("w_zip", "varchar(10)", ("v", ZIPS)),
        ("w_country", "varchar(20)", ("v", COUNTRY)),
        ("w_gmt_offset", "decimal(5,2)", None),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", "bigint", ("sk",)),
        ("sm_ship_mode_id", "varchar(16)", ("id", "AAAAAAAA", "ship_mode")),
        ("sm_type", "varchar(30)", ("vmod", SM_TYPES)),
        ("sm_code", "varchar(10)", ("vmod", SM_CODES)),
        ("sm_carrier", "varchar(20)", ("vmod", SM_CARRIERS)),
        ("sm_contract", "varchar(20)", ("id", "CONTRACT", "ship_mode")),
    ],
    "reason": [
        ("r_reason_sk", "bigint", ("sk",)),
        ("r_reason_id", "varchar(16)", ("id", "AAAAAAAA", "reason")),
        ("r_reason_desc", "varchar(100)", ("vmod", REASONS)),
    ],
    "promotion": [
        ("p_promo_sk", "bigint", ("sk",)),
        ("p_promo_id", "varchar(16)", ("id", "AAAAAAAA", "promotion")),
        ("p_start_date_sk", "bigint", ("fkdate", F)),
        ("p_end_date_sk", "bigint", ("fkdate", F)),
        ("p_item_sk", "bigint", ("fk", "item", F)),
        ("p_cost", "decimal(15,2)", ("d", 100000, 100001)),
        ("p_response_target", "integer", ("i", 1, 2)),
        ("p_promo_name", "varchar(50)", ("v", PROMO_NAMES)),
        ("p_channel_dmail", "varchar(1)", ("v", YN)),
        ("p_channel_email", "varchar(1)", ("v", YN)),
        ("p_channel_catalog", "varchar(1)", ("v", YN)),
        ("p_channel_tv", "varchar(1)", ("v", YN)),
        ("p_channel_radio", "varchar(1)", ("v", YN)),
        ("p_channel_press", "varchar(1)", ("v", YN)),
        ("p_channel_event", "varchar(1)", ("v", YN)),
        ("p_channel_demo", "varchar(1)", ("v", YN)),
        ("p_channel_details", "varchar(100)", ("v", CHANNEL_DETAILS)),
        ("p_purpose", "varchar(15)", ("v", PROMO_PURPOSES)),
        ("p_discount_active", "varchar(1)", ("v", YN)),
    ],
    "call_center": [
        ("cc_call_center_sk", "bigint", ("sk",)),
        ("cc_call_center_id", "varchar(16)", ("id", "AAAAAAAA", "call_center")),
        ("cc_rec_start_date", "date", ("cdate", "1998-01-01")),
        ("cc_rec_end_date", "date", ("cdate", None)),
        ("cc_closed_date_sk", "bigint", ("fkdate", 0.9)),
        ("cc_open_date_sk", "bigint", ("fkdate", 0.0)),
        ("cc_name", "varchar(50)", ("vmod", sorted(f"call center {i}" for i in range(1, 31)))),
        ("cc_class", "varchar(50)", ("vmod", CC_CLASSES)),
        ("cc_employees", "integer", ("i", 1, 7)),
        ("cc_sq_ft", "integer", ("i", 100, 700)),
        ("cc_hours", "varchar(20)", ("vmod", HOURS)),
        ("cc_manager", "varchar(40)", ("v", MANAGERS)),
        ("cc_mkt_id", "integer", ("i", 1, 7)),
        ("cc_mkt_class", "varchar(50)", ("v", MKT_DESCS)),
        ("cc_mkt_desc", "varchar(100)", ("v", MKT_DESCS)),
        ("cc_market_manager", "varchar(40)", ("v", MANAGERS)),
        ("cc_division", "integer", ("i", 1, 7)),
        ("cc_division_name", "varchar(50)", ("v", DIVISION_NAMES)),
        ("cc_company", "integer", ("i", 1, 7)),
        ("cc_company_name", "varchar(50)", ("v", COMPANY_NAMES)),
        ("cc_street_number", "varchar(10)", ("vmod", STREET_NUMBERS)),
        ("cc_street_name", "varchar(60)", ("v", STREET_NAMES)),
        ("cc_street_type", "varchar(15)", ("v", STREET_TYPES)),
        ("cc_suite_number", "varchar(10)", ("vmod", SUITE_NUMBERS)),
        ("cc_city", "varchar(60)", ("v", CITIES)),
        ("cc_county", "varchar(30)", ("v", COUNTIES)),
        ("cc_state", "varchar(2)", ("v", STATES)),
        ("cc_zip", "varchar(10)", ("v", ZIPS)),
        ("cc_country", "varchar(20)", ("v", COUNTRY)),
        ("cc_gmt_offset", "decimal(5,2)", None),
        ("cc_tax_percentage", "decimal(5,2)", ("d", 0, 12)),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", "bigint", ("sk",)),
        ("cp_catalog_page_id", "varchar(16)", ("id", "AAAAAAAA", "catalog_page")),
        ("cp_start_date_sk", "bigint", ("fkdate", F)),
        ("cp_end_date_sk", "bigint", ("fkdate", F)),
        ("cp_department", "varchar(50)", ("v", CP_DEPARTMENTS)),
        ("cp_catalog_number", "integer", ("i", 1, 110)),
        ("cp_catalog_page_number", "integer", ("i", 1, 189)),
        ("cp_description", "varchar(100)", ("v", ITEM_DESCS)),
        ("cp_type", "varchar(100)", ("vmod", CP_TYPES)),
    ],
    "web_site": [
        ("web_site_sk", "bigint", ("sk",)),
        ("web_site_id", "varchar(16)", ("id", "AAAAAAAA", "web_site")),
        ("web_rec_start_date", "date", ("cdate", "1997-08-16")),
        ("web_rec_end_date", "date", ("cdate", None)),
        ("web_name", "varchar(50)", ("vmod", WEB_NAMES)),
        ("web_open_date_sk", "bigint", ("fkdate", 0.0)),
        ("web_close_date_sk", "bigint", ("fkdate", 0.8)),
        ("web_class", "varchar(50)", ("v", GEOGRAPHY)),
        ("web_manager", "varchar(40)", ("v", MANAGERS)),
        ("web_mkt_id", "integer", ("i", 1, 7)),
        ("web_mkt_class", "varchar(50)", ("v", MKT_DESCS)),
        ("web_mkt_desc", "varchar(100)", ("v", MKT_DESCS)),
        ("web_market_manager", "varchar(40)", ("v", MANAGERS)),
        ("web_company_id", "integer", ("i", 1, 7)),
        ("web_company_name", "varchar(50)", ("vmod", COMPANY_NAMES)),
        ("web_street_number", "varchar(10)", ("vmod", STREET_NUMBERS)),
        ("web_street_name", "varchar(60)", ("v", STREET_NAMES)),
        ("web_street_type", "varchar(15)", ("v", STREET_TYPES)),
        ("web_suite_number", "varchar(10)", ("vmod", SUITE_NUMBERS)),
        ("web_city", "varchar(60)", ("v", CITIES)),
        ("web_county", "varchar(30)", ("v", COUNTIES)),
        ("web_state", "varchar(2)", ("v", STATES)),
        ("web_zip", "varchar(10)", ("v", ZIPS)),
        ("web_country", "varchar(20)", ("v", COUNTRY)),
        ("web_gmt_offset", "decimal(5,2)", None),
        ("web_tax_percentage", "decimal(5,2)", ("d", 0, 12)),
    ],
    "web_page": [
        ("wp_web_page_sk", "bigint", ("sk",)),
        ("wp_web_page_id", "varchar(16)", ("id", "AAAAAAAA", "web_page")),
        ("wp_rec_start_date", "date", ("cdate", "1997-09-03")),
        ("wp_rec_end_date", "date", ("cdate", None)),
        ("wp_creation_date_sk", "bigint", ("fkdate", F)),
        ("wp_access_date_sk", "bigint", ("fkdate", F)),
        ("wp_autogen_flag", "varchar(1)", ("v", YN)),
        ("wp_customer_sk", "bigint", ("fk", "customer", 0.7)),
        ("wp_url", "varchar(100)", ("v", WP_URLS)),
        ("wp_type", "varchar(50)", ("vmod", WP_TYPES)),
        ("wp_char_count", "integer", ("i", 100, 8001)),
        ("wp_link_count", "integer", ("i", 2, 26)),
        ("wp_image_count", "integer", ("i", 1, 8)),
        ("wp_max_ad_count", "integer", ("i", 0, 5)),
    ],
    "inventory": [
        ("inv_date_sk", "bigint", None),
        ("inv_item_sk", "bigint", None),
        ("inv_warehouse_sk", "bigint", None),
        ("inv_quantity_on_hand", "integer", ("in", 0, 1001, 0.05)),
    ],
    "store_sales": [
        ("ss_sold_date_sk", "bigint", ("fkdate", F)),
        ("ss_sold_time_sk", "bigint", ("fktime", F)),
        ("ss_item_sk", "bigint", ("fk", "item", 0.0)),
        ("ss_customer_sk", "bigint", ("fk", "customer", F)),
        ("ss_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("ss_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("ss_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("ss_store_sk", "bigint", ("fk", "store", F)),
        ("ss_promo_sk", "bigint", ("fk", "promotion", F)),
        ("ss_ticket_number", "bigint", ("seq", 12)),
        ("ss_quantity", "integer", None),
        ("ss_wholesale_cost", "decimal(7,2)", None),
        ("ss_list_price", "decimal(7,2)", None),
        ("ss_sales_price", "decimal(7,2)", None),
        ("ss_ext_discount_amt", "decimal(7,2)", None),
        ("ss_ext_sales_price", "decimal(7,2)", None),
        ("ss_ext_wholesale_cost", "decimal(7,2)", None),
        ("ss_ext_list_price", "decimal(7,2)", None),
        ("ss_ext_tax", "decimal(7,2)", None),
        ("ss_coupon_amt", "decimal(7,2)", None),
        ("ss_net_paid", "decimal(7,2)", None),
        ("ss_net_paid_inc_tax", "decimal(7,2)", None),
        ("ss_net_profit", "decimal(7,2)", None),
    ],
    "store_returns": [
        ("sr_returned_date_sk", "bigint", ("fkdate", F)),
        ("sr_return_time_sk", "bigint", ("fktime", F)),
        ("sr_item_sk", "bigint", ("fk", "item", 0.0)),
        ("sr_customer_sk", "bigint", ("fk", "customer", F)),
        ("sr_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("sr_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("sr_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("sr_store_sk", "bigint", ("fk", "store", F)),
        ("sr_reason_sk", "bigint", ("fk", "reason", F)),
        ("sr_ticket_number", "bigint", ("seq", 6)),
        ("sr_return_quantity", "integer", None),
        ("sr_return_amt", "decimal(7,2)", None),
        ("sr_return_tax", "decimal(7,2)", None),
        ("sr_return_amt_inc_tax", "decimal(7,2)", None),
        ("sr_fee", "decimal(7,2)", None),
        ("sr_return_ship_cost", "decimal(7,2)", None),
        ("sr_refunded_cash", "decimal(7,2)", None),
        ("sr_reversed_charge", "decimal(7,2)", None),
        ("sr_store_credit", "decimal(7,2)", None),
        ("sr_net_loss", "decimal(7,2)", None),
    ],
    "catalog_sales": [
        ("cs_sold_date_sk", "bigint", ("fkdate", F)),
        ("cs_sold_time_sk", "bigint", ("fktime", F)),
        ("cs_ship_date_sk", "bigint", None),
        ("cs_bill_customer_sk", "bigint", ("fk", "customer", F)),
        ("cs_bill_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("cs_bill_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("cs_bill_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("cs_ship_customer_sk", "bigint", ("fk", "customer", F)),
        ("cs_ship_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("cs_ship_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("cs_ship_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("cs_call_center_sk", "bigint", ("fk", "call_center", F)),
        ("cs_catalog_page_sk", "bigint", ("fk", "catalog_page", F)),
        ("cs_ship_mode_sk", "bigint", ("fk", "ship_mode", F)),
        ("cs_warehouse_sk", "bigint", ("fk", "warehouse", F)),
        ("cs_item_sk", "bigint", ("fk", "item", 0.0)),
        ("cs_promo_sk", "bigint", ("fk", "promotion", F)),
        ("cs_order_number", "bigint", ("seq", 10)),
        ("cs_quantity", "integer", None),
        ("cs_wholesale_cost", "decimal(7,2)", None),
        ("cs_list_price", "decimal(7,2)", None),
        ("cs_sales_price", "decimal(7,2)", None),
        ("cs_ext_discount_amt", "decimal(7,2)", None),
        ("cs_ext_sales_price", "decimal(7,2)", None),
        ("cs_ext_wholesale_cost", "decimal(7,2)", None),
        ("cs_ext_list_price", "decimal(7,2)", None),
        ("cs_ext_tax", "decimal(7,2)", None),
        ("cs_coupon_amt", "decimal(7,2)", None),
        ("cs_ext_ship_cost", "decimal(7,2)", None),
        ("cs_net_paid", "decimal(7,2)", None),
        ("cs_net_paid_inc_tax", "decimal(7,2)", None),
        ("cs_net_paid_inc_ship", "decimal(7,2)", None),
        ("cs_net_paid_inc_ship_tax", "decimal(7,2)", None),
        ("cs_net_profit", "decimal(7,2)", None),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", "bigint", ("fkdate", F)),
        ("cr_returned_time_sk", "bigint", ("fktime", F)),
        ("cr_item_sk", "bigint", ("fk", "item", 0.0)),
        ("cr_refunded_customer_sk", "bigint", ("fk", "customer", F)),
        ("cr_refunded_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("cr_refunded_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("cr_refunded_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("cr_returning_customer_sk", "bigint", ("fk", "customer", F)),
        ("cr_returning_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("cr_returning_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("cr_returning_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("cr_call_center_sk", "bigint", ("fk", "call_center", F)),
        ("cr_catalog_page_sk", "bigint", ("fk", "catalog_page", F)),
        ("cr_ship_mode_sk", "bigint", ("fk", "ship_mode", F)),
        ("cr_warehouse_sk", "bigint", ("fk", "warehouse", F)),
        ("cr_reason_sk", "bigint", ("fk", "reason", F)),
        ("cr_order_number", "bigint", ("seq", 5)),
        ("cr_return_quantity", "integer", None),
        ("cr_return_amount", "decimal(7,2)", None),
        ("cr_return_tax", "decimal(7,2)", None),
        ("cr_return_amt_inc_tax", "decimal(7,2)", None),
        ("cr_fee", "decimal(7,2)", None),
        ("cr_return_ship_cost", "decimal(7,2)", None),
        ("cr_refunded_cash", "decimal(7,2)", None),
        ("cr_reversed_charge", "decimal(7,2)", None),
        ("cr_store_credit", "decimal(7,2)", None),
        ("cr_net_loss", "decimal(7,2)", None),
    ],
    "web_sales": [
        ("ws_sold_date_sk", "bigint", ("fkdate", F)),
        ("ws_sold_time_sk", "bigint", ("fktime", F)),
        ("ws_ship_date_sk", "bigint", None),
        ("ws_item_sk", "bigint", ("fk", "item", 0.0)),
        ("ws_bill_customer_sk", "bigint", ("fk", "customer", F)),
        ("ws_bill_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("ws_bill_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("ws_bill_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("ws_ship_customer_sk", "bigint", ("fk", "customer", F)),
        ("ws_ship_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("ws_ship_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("ws_ship_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("ws_web_page_sk", "bigint", ("fk", "web_page", F)),
        ("ws_web_site_sk", "bigint", ("fk", "web_site", F)),
        ("ws_ship_mode_sk", "bigint", ("fk", "ship_mode", F)),
        ("ws_warehouse_sk", "bigint", ("fk", "warehouse", F)),
        ("ws_promo_sk", "bigint", ("fk", "promotion", F)),
        ("ws_order_number", "bigint", ("seq", 8)),
        ("ws_quantity", "integer", None),
        ("ws_wholesale_cost", "decimal(7,2)", None),
        ("ws_list_price", "decimal(7,2)", None),
        ("ws_sales_price", "decimal(7,2)", None),
        ("ws_ext_discount_amt", "decimal(7,2)", None),
        ("ws_ext_sales_price", "decimal(7,2)", None),
        ("ws_ext_wholesale_cost", "decimal(7,2)", None),
        ("ws_ext_list_price", "decimal(7,2)", None),
        ("ws_ext_tax", "decimal(7,2)", None),
        ("ws_coupon_amt", "decimal(7,2)", None),
        ("ws_ext_ship_cost", "decimal(7,2)", None),
        ("ws_net_paid", "decimal(7,2)", None),
        ("ws_net_paid_inc_tax", "decimal(7,2)", None),
        ("ws_net_paid_inc_ship", "decimal(7,2)", None),
        ("ws_net_paid_inc_ship_tax", "decimal(7,2)", None),
        ("ws_net_profit", "decimal(7,2)", None),
    ],
    "web_returns": [
        ("wr_returned_date_sk", "bigint", ("fkdate", F)),
        ("wr_returned_time_sk", "bigint", ("fktime", F)),
        ("wr_item_sk", "bigint", ("fk", "item", 0.0)),
        ("wr_refunded_customer_sk", "bigint", ("fk", "customer", F)),
        ("wr_refunded_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("wr_refunded_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("wr_refunded_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("wr_returning_customer_sk", "bigint", ("fk", "customer", F)),
        ("wr_returning_cdemo_sk", "bigint", ("fk", "customer_demographics", F)),
        ("wr_returning_hdemo_sk", "bigint", ("fk", "household_demographics", F)),
        ("wr_returning_addr_sk", "bigint", ("fk", "customer_address", F)),
        ("wr_web_page_sk", "bigint", ("fk", "web_page", F)),
        ("wr_reason_sk", "bigint", ("fk", "reason", F)),
        ("wr_order_number", "bigint", ("seq", 4)),
        ("wr_return_quantity", "integer", None),
        ("wr_return_amt", "decimal(7,2)", None),
        ("wr_return_tax", "decimal(7,2)", None),
        ("wr_return_amt_inc_tax", "decimal(7,2)", None),
        ("wr_fee", "decimal(7,2)", None),
        ("wr_return_ship_cost", "decimal(7,2)", None),
        ("wr_refunded_cash", "decimal(7,2)", None),
        ("wr_reversed_charge", "decimal(7,2)", None),
        ("wr_account_credit", "decimal(7,2)", None),
        ("wr_net_loss", "decimal(7,2)", None),
    ],
}

# SF1 row counts from the TPC-DS scaling table; FIXED tables never scale.
_SF1_ROWS = {
    "call_center": 6, "catalog_page": 11718, "catalog_returns": 144067,
    "catalog_sales": 1441548, "customer": 100000, "customer_address": 50000,
    "customer_demographics": 1920800, "date_dim": N_DATES,
    "household_demographics": 7200, "income_band": 20, "inventory": 11745000,
    "item": 18000, "promotion": 300, "reason": 35, "ship_mode": 20,
    "store": 12, "store_returns": 287514, "store_sales": 2880404,
    "time_dim": 86400, "warehouse": 5, "web_page": 60, "web_returns": 71763,
    "web_sales": 719384, "web_site": 30,
}
_FIXED = {"date_dim", "time_dim", "customer_demographics",
          "household_demographics", "income_band", "ship_mode", "reason"}
_FACTS = {"store_sales", "store_returns", "catalog_sales", "catalog_returns",
          "web_sales", "web_returns", "inventory"}


def _row_count(table: str, scale: float) -> int:
    base = _SF1_ROWS[table]
    if table in _FIXED:
        return base
    if table in _FACTS:
        return max(1000, int(base * scale))
    if table in ("customer", "customer_address", "catalog_page"):
        return max(100, int(base * scale))
    # small dimensions scale sublinearly like dsdgen
    scaled = base * (scale if scale <= 1 else scale**0.5)
    return max(2 if base < 100 else 100, int(scaled))


def _seed(table: str, scale: float, chunk: int) -> np.random.Generator:
    key = f"tpcds:{table}:{round(scale * 1e6)}:{chunk}".encode()
    return np.random.default_rng(
        int.from_bytes(hashlib.blake2s(key, digest_size=8).digest(), "little")
    )


def _chunk_rows(total: int) -> int:
    return int(min(max(total // 64, 64), 262_144))


def _nullable(rng, arr: np.ndarray, p: float):
    if p <= 0:
        return arr
    valid = rng.random(len(arr)) >= p
    return (np.where(valid, arr, arr.dtype.type(0)), valid)


def data_valid(v) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Uniform view of a generated column: (values, validity-or-None)."""
    return v if isinstance(v, tuple) else (v, None)


def _price_chain(rng, n: int, prefix: str) -> Dict[str, np.ndarray]:
    """Consistent fact price columns (cents): wholesale -> list -> sales ->
    ext_* -> tax/coupon -> net_paid -> net_profit, like dsdgen's mk_*_sales."""
    qty = rng.integers(1, 101, n, dtype=np.int64)
    wholesale = rng.integers(100, 10001, n, dtype=np.int64)
    markup = rng.integers(100, 301, n, dtype=np.int64)  # 1.0x..3.0x of cost
    list_price = wholesale * markup // 100
    discount = rng.integers(0, 101, n, dtype=np.int64)  # percent sold at
    sales_price = list_price * discount // 100
    ext_sales = sales_price * qty
    ext_list = list_price * qty
    ext_wholesale = wholesale * qty
    tax_pct = rng.integers(0, 10, n, dtype=np.int64)
    coupon = np.where(rng.random(n) < 0.1, ext_sales // 2, 0).astype(np.int64)
    net_paid = ext_sales - coupon
    ext_tax = net_paid * tax_pct // 100
    out = {
        f"{prefix}_quantity": qty.astype(np.int32),
        f"{prefix}_wholesale_cost": wholesale,
        f"{prefix}_list_price": list_price,
        f"{prefix}_sales_price": sales_price,
        f"{prefix}_ext_discount_amt": ext_list - ext_sales,
        f"{prefix}_ext_sales_price": ext_sales,
        f"{prefix}_ext_wholesale_cost": ext_wholesale,
        f"{prefix}_ext_list_price": ext_list,
        f"{prefix}_ext_tax": ext_tax,
        f"{prefix}_coupon_amt": coupon,
        f"{prefix}_net_paid": net_paid,
        f"{prefix}_net_paid_inc_tax": net_paid + ext_tax,
        f"{prefix}_net_profit": net_paid - ext_wholesale,
    }
    if prefix in ("cs", "ws"):
        ship = rng.integers(0, 5001, n, dtype=np.int64)
        out[f"{prefix}_ext_ship_cost"] = ship
        out[f"{prefix}_net_paid_inc_ship"] = net_paid + ship
        out[f"{prefix}_net_paid_inc_ship_tax"] = net_paid + ship + ext_tax
    return out


def _returns_chain(rng, n: int, prefix: str, amount_col: str) -> Dict[str, np.ndarray]:
    qty = rng.integers(1, 101, n, dtype=np.int64)
    price = rng.integers(100, 10001, n, dtype=np.int64)
    amt = qty * price
    tax = amt * rng.integers(0, 10, n, dtype=np.int64) // 100
    fee = rng.integers(50, 10001, n, dtype=np.int64)
    ship = rng.integers(0, 5001, n, dtype=np.int64)
    cash = amt * rng.integers(0, 101, n, dtype=np.int64) // 100
    reversed_charge = (amt - cash) // 2
    credit = amt - cash - reversed_charge
    credit_col = {"sr": "sr_store_credit", "cr": "cr_store_credit",
                  "wr": "wr_account_credit"}[prefix]
    return {
        f"{prefix}_return_quantity": qty.astype(np.int32),
        amount_col: amt,
        f"{prefix}_return_tax": tax,
        f"{prefix}_return_amt_inc_tax": amt + tax,
        f"{prefix}_fee": fee,
        f"{prefix}_return_ship_cost": ship,
        f"{prefix}_refunded_cash": cash,
        f"{prefix}_reversed_charge": reversed_charge,
        credit_col: credit,
        f"{prefix}_net_loss": amt + tax + fee + ship - cash,
    }


def _gen_chunk(table: str, scale: float, start: int, stop: int, rng):
    """One canonical chunk of rows [start, stop) as {col: array | (array, valid)}."""
    keys = np.arange(start + 1, stop + 1, dtype=np.int64)
    n = len(keys)
    out: Dict[str, object] = {}

    if table == "date_dim":
        day_idx = keys - 1  # days since DATE_START
        dates = np.array((DATE_START - EPOCH).days + day_idx, dtype=np.int32)
        base = np.datetime64(DATE_START, "D") + day_idx
        years = base.astype("datetime64[Y]").astype(int) + 1970
        months0 = base.astype("datetime64[M]").astype(int)
        moy = months0 % 12 + 1
        dom = (base - base.astype("datetime64[M]")).astype(int) + 1
        # DATE_START is a Tuesday; dsdgen d_dow: 0 = Monday
        dow = (day_idx + 1) % 7
        qoy = (moy - 1) // 3 + 1
        month_seq = (years - 1900) * 12 + moy - 1
        week_seq = (day_idx + 1) // 7 + 1
        quarter_seq = (years - 1900) * 4 + qoy - 1
        first_dom = JULIAN_BASE + (
            base.astype("datetime64[M]").astype("datetime64[D]")
            - np.datetime64(DATE_START, "D")
        ).astype(int)
        last_dom = JULIAN_BASE + (
            (base.astype("datetime64[M]") + 1).astype("datetime64[D]")
            - np.datetime64(DATE_START, "D")
        ).astype(int) - 1
        day_code = {d: i for i, d in enumerate(DAY_NAMES)}
        names = np.array(
            [day_code[d] for d in
             ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday", "Sunday"]],
            dtype=np.int32,
        )
        qname_code = {q: i for i, q in enumerate(QUARTER_NAMES)}
        qnames = np.array(
            [qname_code[f"{y}Q{q}"] for y, q in zip(years, qoy)], dtype=np.int32
        )
        holiday = np.isin(moy * 100 + dom, [101, 704, 1125, 1225, 1231])
        # previous calendar day's flag, computed from the date itself (an
        # np.roll within the chunk would wrap at chunk boundaries)
        prev = base - 1
        pmoy = prev.astype("datetime64[M]").astype(int) % 12 + 1
        pdom = (prev - prev.astype("datetime64[M]")).astype(int) + 1
        following = np.isin(pmoy * 100 + pdom, [101, 704, 1125, 1225, 1231])
        weekend = dow >= 5
        out = {
            "d_date_sk": JULIAN_BASE + day_idx,
            "d_date_id": (keys - 1).astype(np.int32),
            "d_date": dates,
            "d_month_seq": month_seq.astype(np.int32),
            "d_week_seq": week_seq.astype(np.int32),
            "d_quarter_seq": quarter_seq.astype(np.int32),
            "d_year": years.astype(np.int32),
            "d_dow": dow.astype(np.int32),
            "d_moy": moy.astype(np.int32),
            "d_dom": dom.astype(np.int32),
            "d_qoy": qoy.astype(np.int32),
            "d_fy_year": years.astype(np.int32),
            "d_fy_quarter_seq": quarter_seq.astype(np.int32),
            "d_fy_week_seq": week_seq.astype(np.int32),
            "d_day_name": names[dow],
            "d_quarter_name": qnames,
            "d_holiday": holiday.astype(np.int32),
            "d_weekend": weekend.astype(np.int32),
            "d_following_holiday": following.astype(np.int32),
            "d_first_dom": first_dom,
            "d_last_dom": last_dom,
            "d_same_day_ly": JULIAN_BASE + np.maximum(day_idx - 365, 0),
            "d_same_day_lq": JULIAN_BASE + np.maximum(day_idx - 91, 0),
            "d_current_day": np.zeros(n, dtype=np.int32),  # code of "N"
            "d_current_week": np.zeros(n, dtype=np.int32),
            "d_current_month": np.zeros(n, dtype=np.int32),
            "d_current_quarter": np.zeros(n, dtype=np.int32),
            "d_current_year": np.zeros(n, dtype=np.int32),
        }
        return out

    if table == "time_dim":
        secs = keys - 1
        hour = secs // 3600
        minute = (secs % 3600) // 60
        shift_code = {s: i for i, s in enumerate(SHIFTS)}
        sub_code = {s: i for i, s in enumerate(SUB_SHIFTS)}
        meal_code = {s: i for i, s in enumerate(MEALS)}
        shifts = np.where(hour < 8, shift_code["third"],
                          np.where(hour < 16, shift_code["first"], shift_code["second"]))
        subs = np.where(hour < 6, sub_code["night"],
                        np.where(hour < 12, sub_code["morning"],
                                 np.where(hour < 18, sub_code["afternoon"],
                                          sub_code["evening"])))
        meals = np.where((hour >= 6) & (hour < 9), meal_code["breakfast"],
                         np.where((hour >= 11) & (hour < 14), meal_code["lunch"],
                                  np.where((hour >= 17) & (hour < 20),
                                           meal_code["dinner"], meal_code[""])))
        return {
            "t_time_sk": secs,
            "t_time_id": (keys - 1).astype(np.int32),
            "t_time": secs.astype(np.int32),
            "t_hour": hour.astype(np.int32),
            "t_minute": minute.astype(np.int32),
            "t_second": (secs % 60).astype(np.int32),
            "t_am_pm": (hour >= 12).astype(np.int32),
            "t_shift": shifts.astype(np.int32),
            "t_sub_shift": subs.astype(np.int32),
            "t_meal_time": meals.astype(np.int32),
        }

    if table == "customer_demographics":
        # dsdgen: cd is the cross product of the demographic domains
        idx = keys - 1
        return {
            "cd_demo_sk": keys,
            "cd_gender": (idx % 2).astype(np.int32),
            "cd_marital_status": (idx // 2 % 5).astype(np.int32),
            "cd_education_status": (idx // 10 % 7).astype(np.int32),
            "cd_purchase_estimate": ((idx // 70 % 20 + 1) * 500).astype(np.int32),
            "cd_credit_rating": (idx // 1400 % 4).astype(np.int32),
            "cd_dep_count": (idx // 5600 % 7).astype(np.int32),
            "cd_dep_employed_count": (idx // 39200 % 7).astype(np.int32),
            "cd_dep_college_count": (idx // 274400 % 7).astype(np.int32),
        }

    if table == "household_demographics":
        idx = keys - 1
        return {
            "hd_demo_sk": keys,
            "hd_income_band_sk": (idx % 20 + 1).astype(np.int64),
            "hd_buy_potential": (idx // 20 % 6).astype(np.int32),
            "hd_dep_count": (idx // 120 % 10).astype(np.int32),
            "hd_vehicle_count": (idx // 1200 % 6).astype(np.int32),
        }

    if table == "income_band":
        return {
            "ib_income_band_sk": keys,
            "ib_lower_bound": ((keys - 1) * 10000).astype(np.int32),
            "ib_upper_bound": (keys * 10000).astype(np.int32),
        }

    if table == "inventory":
        # weekly snapshots: date x item x warehouse in row-major order
        n_items = _row_count("item", scale)
        n_wh = _row_count("warehouse", scale)
        idx = keys - 1
        week = idx // (n_items * n_wh)
        rest = idx % (n_items * n_wh)
        out["inv_date_sk"] = SALES_LO + (week * 7)
        out["inv_item_sk"] = rest // n_wh + 1
        out["inv_warehouse_sk"] = rest % n_wh + 1

    if table == "item":
        brand_id = rng.integers(1, N_BRANDS + 1, n, dtype=np.int64)
        class_id = rng.integers(1, len(CLASSES) + 1, n, dtype=np.int32)
        category_id = rng.integers(1, len(CATEGORIES) + 1, n, dtype=np.int32)
        manufact_id = rng.integers(1, 1001, n, dtype=np.int64)
        out["i_brand_id"] = brand_id.astype(np.int32)
        out["i_brand"] = _BRAND_CODE[brand_id]
        out["i_class_id"] = class_id
        out["i_class"] = (class_id - 1).astype(np.int32)  # CLASSES sorted
        out["i_category_id"] = category_id
        out["i_category"] = (category_id - 1).astype(np.int32)
        out["i_manufact_id"] = manufact_id.astype(np.int32)
        out["i_manufact"] = _MANUFACT_CODE[manufact_id]

    if table in ("customer_address", "store", "warehouse", "call_center", "web_site"):
        col = {"customer_address": "ca", "store": "s", "warehouse": "w",
               "call_center": "cc", "web_site": "web"}[table]
        out[f"{col}_gmt_offset"] = rng.choice(
            np.array([-1000, -900, -800, -700, -600, -500], dtype=np.int64), n
        )

    if table == "store_sales":
        out.update(_price_chain(rng, n, "ss"))
    if table == "catalog_sales":
        out.update(_price_chain(rng, n, "cs"))
        sold = rng.integers(SALES_LO, SALES_HI + 1, n, dtype=np.int64)
        out["cs_sold_date_sk"] = _nullable(rng, sold, F)
        out["cs_ship_date_sk"] = _nullable(rng, sold + rng.integers(1, 121, n), F)
    if table == "web_sales":
        out.update(_price_chain(rng, n, "ws"))
        sold = rng.integers(SALES_LO, SALES_HI + 1, n, dtype=np.int64)
        out["ws_sold_date_sk"] = _nullable(rng, sold, F)
        out["ws_ship_date_sk"] = _nullable(rng, sold + rng.integers(1, 121, n), F)
    if table == "store_returns":
        out.update(_returns_chain(rng, n, "sr", "sr_return_amt"))
    if table == "catalog_returns":
        out.update(_returns_chain(rng, n, "cr", "cr_return_amount"))
    if table == "web_returns":
        out.update(_returns_chain(rng, n, "wr", "wr_return_amt"))

    for cname, _tname, gen in _TABLES[table]:
        if cname in out or gen is None:
            continue
        kind = gen[0]
        if kind == "sk":
            out[cname] = keys
        elif kind == "id":
            out[cname] = (keys - 1).astype(np.int32)
        elif kind == "v":
            out[cname] = rng.integers(0, len(gen[1]), n, dtype=np.int32)
        elif kind == "vn":
            out[cname] = _nullable(
                rng, rng.integers(0, len(gen[1]), n, dtype=np.int32), gen[2]
            )
        elif kind == "vmod":
            out[cname] = ((keys - 1) % len(gen[1])).astype(np.int32)
        elif kind == "i":
            out[cname] = rng.integers(gen[1], gen[2], n, dtype=np.int32)
        elif kind == "in":
            out[cname] = _nullable(
                rng, rng.integers(gen[1], gen[2], n, dtype=np.int32), gen[3]
            )
        elif kind == "d":
            out[cname] = rng.integers(gen[1], gen[2], n, dtype=np.int64)
        elif kind == "fk":
            hi = _row_count(gen[1], scale) + 1
            out[cname] = _nullable(rng, rng.integers(1, hi, n, dtype=np.int64), gen[2])
        elif kind == "fkdate":
            out[cname] = _nullable(
                rng, rng.integers(SALES_LO, SALES_HI + 1, n, dtype=np.int64), gen[1]
            )
        elif kind == "fktime":
            out[cname] = _nullable(rng, rng.integers(0, 86400, n, dtype=np.int64), gen[1])
        elif kind == "seq":
            out[cname] = (keys - 1) // gen[1] + 1
        elif kind == "cdate":
            if gen[1] is None:
                out[cname] = _nullable(rng, np.zeros(n, dtype=np.int32), 1.0)
            else:
                d = (datetime.date.fromisoformat(gen[1]) - EPOCH).days
                out[cname] = np.full(n, d, dtype=np.int32)
        else:
            raise KeyError((table, cname, gen))
    return out


def generate_split(table: str, scale: float, split: int, total_splits: int):
    n = _row_count(table, scale)
    chunk = _chunk_rows(n)
    n_chunks = (n + chunk - 1) // chunk
    first = (n_chunks * split) // total_splits
    end = (n_chunks * (split + 1)) // total_splits
    pieces = []
    for c in range(first, end):
        start, stop = c * chunk, min((c + 1) * chunk, n)
        pieces.append(_gen_chunk(table, scale, start, stop, _seed(table, scale, c)))
    if not pieces:
        ref = _gen_chunk(table, scale, 0, 1, _seed(table, scale, 0))
        empty = {
            k: np.zeros(0, dtype=data_valid(v)[0].dtype) for k, v in ref.items()
        }
        return empty, 0

    def cat(col):
        vals = [data_valid(p[col]) for p in pieces]
        if vals[0][1] is not None:
            return (
                np.concatenate([a for a, _ in vals]),
                np.concatenate([v for _, v in vals]),
            )
        return np.concatenate([a for a, _ in vals])

    out = {k: cat(k) for k in pieces[0]}
    first_col = next(iter(pieces[0]))
    count = sum(len(data_valid(p[first_col])[0]) for p in pieces)
    return out, count


_BRAND_CODE = np.zeros(N_BRANDS + 1, dtype=np.int32)
for _i in range(1, N_BRANDS + 1):
    _BRAND_CODE[_i] = BRANDS.index(f"Brand #{_i}")
_MANUFACT_CODE = np.zeros(1001, dtype=np.int32)
for _i in range(1, 1001):
    _MANUFACT_CODE[_i] = MANUFACTS.index(f"manufact{_i:04d}")


class TpcdsConnector(Connector):
    """ref: plugin/trino-tpcds TpcdsConnectorFactory.java — full 24-table
    schema, on-the-fly deterministic generation."""

    name = "tpcds"

    def __init__(self, scale: Optional[float] = None, split_target_rows: int = 1 << 20):
        self.default_scale = scale
        self.split_target_rows = split_target_rows
        self._dictionaries: Dict[tuple, Optional[Dictionary]] = {}
        self._meta = _Meta(self)
        self._splits = _Splits(self)
        self._pages = _Pages(self)

    def metadata(self):
        return self._meta

    def cache_table_version(self, schema: str, table: str):
        """Warm-path cache plane hook (runtime/cachestore.py): generated
        data is deterministic per RESOLVED scale, carried in the token so
        non-scale-encoded schema names at different default scales never
        alias; unresolvable -> None (TTL-or-bypass)."""
        s = None
        if schema.startswith("sf"):
            try:
                s = float(schema[2:].replace("_", "."))
            except ValueError:
                s = None
        if s is None:
            s = self.default_scale
        if s is None:
            return None
        return f"static-{schema}-sf{s:g}"

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    def scale_of(self, handle: TableHandle) -> float:
        schema = handle.schema_table.schema
        if schema.startswith("sf"):
            try:
                return float(schema[2:].replace("_", "."))
            except ValueError:
                pass
        if self.default_scale is not None:
            return self.default_scale
        raise ValueError(f"unknown tpcds schema: {schema}")

    def dictionary(self, table: str, column: str, scale: float) -> Optional[Dictionary]:
        key = (table, column, round(scale * 1e6))
        if key not in self._dictionaries:
            spec = next(c for c in _TABLES[table] if c[0] == column)
            gen = spec[2]
            vocab = None
            if gen is not None and gen[0] in ("v", "vn", "vmod"):
                vocab = gen[1]
            elif gen is not None and gen[0] == "id":
                prefix, base = gen[1], gen[2]
                vocab = tuple(
                    f"{prefix}{i:012d}" for i in range(1, _row_count(base, scale) + 1)
                )
            elif column in _COMPUTED_VOCABS:
                vocab = _COMPUTED_VOCABS[column]
            # setdefault: concurrent page-source threads racing a cold key
            # must share ONE identity-hashed Dictionary (see tpch connector)
            self._dictionaries.setdefault(
                key,
                Dictionary(np.asarray(list(vocab), dtype=object)) if vocab else None,
            )
        return self._dictionaries[key]

    def split_count(self, table: str, scale: float) -> int:
        n = _row_count(table, scale)
        wanted = max(1, math.ceil(n / self.split_target_rows))
        n_chunks = (n + _chunk_rows(n) - 1) // _chunk_rows(n)
        return min(wanted, n_chunks)


# string columns whose vocabulary is implied by a computed generator
_COMPUTED_VOCABS: Dict[str, tuple] = {
    "d_date_id": None,  # filled below (per-row ids over fixed N_DATES)
    "d_day_name": tuple(DAY_NAMES),
    "d_quarter_name": tuple(QUARTER_NAMES),
    "d_holiday": YN, "d_weekend": YN, "d_following_holiday": YN,
    "d_current_day": YN, "d_current_week": YN, "d_current_month": YN,
    "d_current_quarter": YN, "d_current_year": YN,
    "t_time_id": None,
    "t_am_pm": AMPM, "t_shift": tuple(SHIFTS), "t_sub_shift": tuple(SUB_SHIFTS),
    "t_meal_time": tuple(MEALS),
    "i_brand": tuple(BRANDS), "i_class": tuple(CLASSES),
    "i_category": tuple(CATEGORIES), "i_manufact": tuple(MANUFACTS),
    "cd_gender": GENDERS, "cd_marital_status": tuple(MARITAL),
    "cd_education_status": tuple(EDUCATION), "cd_credit_rating": tuple(CREDIT_RATING),
    "hd_buy_potential": tuple(BUY_POTENTIAL),
}
_COMPUTED_VOCABS["d_date_id"] = tuple(f"DATE{i:012d}" for i in range(1, N_DATES + 1))
_COMPUTED_VOCABS["t_time_id"] = tuple(f"TIME{i:012d}" for i in range(1, 86401))


class _Meta(ConnectorMetadata):
    def __init__(self, connector):
        self.connector = connector

    def list_schemas(self):
        return ["sf0_001", "sf0_01", "sf1"]

    def list_tables(self, schema=None):
        schemas = [schema] if schema else self.list_schemas()
        return [SchemaTableName(s, t) for s in schemas for t in sorted(_TABLES)]

    def get_table_metadata(self, name: SchemaTableName):
        if name.table not in _TABLES:
            return None
        cols = tuple(
            ColumnMetadata(c[0], parse_type(c[1])) for c in _TABLES[name.table]
        )
        return TableMetadata(name, cols)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        scale = self.connector.scale_of(handle)
        return TableStatistics(row_count=float(_row_count(handle.schema_table.table, scale)))

    def apply_filter(self, handle, domain):
        return TableHandle(handle.catalog, handle.schema_table, connector_handle=domain)


class _Splits(ConnectorSplitManager):
    def __init__(self, connector):
        self.connector = connector

    def get_splits(self, handle, desired_splits: int = 1):
        scale = self.connector.scale_of(handle)
        total = self.connector.split_count(handle.schema_table.table, scale)
        return [Split(handle, i, total) for i in range(total)]


class _Pages(ConnectorPageSourceProvider):
    def __init__(self, connector):
        self.connector = connector

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        handle = split.table
        scale = self.connector.scale_of(handle)
        table = handle.schema_table.table
        data, count = generate_split(table, scale, split.split_id, split.total_splits)
        n = _row_count(table, scale)
        total = split.total_splits
        chunk = _chunk_rows(n)
        n_chunks = (n + chunk - 1) // chunk
        max_rows = 1
        for s in range(total):
            first = (n_chunks * s) // total
            end = (n_chunks * (s + 1)) // total
            max_rows = max(max_rows, min(end * chunk, n) - first * chunk)
        cap = 64
        while cap < max_rows and cap < (1 << 20):
            cap *= 2
        if cap < max_rows:
            cap = math.ceil(max_rows / (1 << 20)) << 20
        schema = _TABLES[table]
        cols = []
        for idx in column_indexes:
            cname, tname, _ = schema[idx]
            type_ = parse_type(tname)
            arr, valid = data_valid(data[cname])
            cols.append(
                Column.from_numpy(
                    type_, arr, valid, cap,
                    self.connector.dictionary(table, cname, scale),
                )
            )
        active = np.zeros(cap, dtype=np.bool_)
        active[:count] = True
        return Page(tuple(cols), jnp.asarray(active))
