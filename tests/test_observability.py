"""Metrics, tracing spans, and the spool SPI.

Model: the reference's spi/metrics + JMX exposure, its OpenTelemetry span
instrumentation (TracingMetadata planning spans), and spi/spool
SpoolingManager + the spooled client protocol (protocol/spooling).
"""

import json
import urllib.request

import pytest


@pytest.fixture(scope="module")
def server():
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.server.coordinator import CoordinatorServer

    r = LocalQueryRunner.tpch(scale=0.001)
    srv = CoordinatorServer(r)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    from trino_tpu.client.client import StatementClient

    return StatementClient(f"http://{server.address}")


class TestMetrics:
    def test_prometheus_rendering(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("test_total", help="a test counter").inc(3)
        reg.gauge("test_gauge", {"pool": "a"}).set(7)
        text = reg.render()
        assert "# TYPE test_total counter" in text
        assert "test_total 3" in text
        assert 'test_gauge{pool="a"} 7' in text

    def test_endpoint_counts_queries(self, server, client):
        client.execute("SELECT 1")
        text = (
            urllib.request.urlopen(f"http://{server.address}/v1/metrics")
            .read()
            .decode()
        )
        assert "trino_tpu_queries_submitted_total" in text
        assert "trino_tpu_queries_finished_total" in text


class TestTracing:
    def test_span_tree(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with tr.span("root") as root:
            with tr.span("child"):
                pass
        spans = tr.trace(root.trace_id)
        assert [s["name"] for s in spans] == ["root", "child"]
        child = spans[1]
        assert child["parentSpanId"] == spans[0]["spanId"]
        assert child["durationMs"] is not None

    def test_error_recorded(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom") as s:
                raise ValueError("nope")
        assert "ValueError" in s.attributes["error"]

    def test_query_trace_endpoint(self, server, client):
        res = client.execute("SELECT count(*) FROM nation")
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/query/{res.query_id}/trace"
            ).read()
        )
        names = [s["name"] for s in info["spans"]]
        assert names == ["query", "planner", "optimizer", "execution"]


class TestSpool:
    def test_manager_roundtrip(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path))
        h = m.create_segment(b"payload", rows=3)
        assert m.get_segment(h.segment_id) == b"payload"
        m.delete_segment(h.segment_id)
        assert m.get_segment(h.segment_id) is None

    def test_ttl_eviction(self, tmp_path):
        from trino_tpu.runtime.spool import FileSystemSpoolingManager

        m = FileSystemSpoolingManager(str(tmp_path), ttl_secs=0.0)
        h1 = m.create_segment(b"a", rows=1)
        m.create_segment(b"b", rows=1)  # triggers eviction of h1
        assert h1.segment_id not in m.list_segments()

    def test_spooled_protocol_matches_inline(self, client):
        inline = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey"
        )
        spooled = client.execute(
            "SELECT n_nationkey, n_name FROM nation ORDER BY n_nationkey",
            data_encoding="json",
        )
        assert spooled.rows == inline.rows

    def test_spooled_lz4(self, client):
        from trino_tpu.native import native_available

        if not native_available():
            pytest.skip("native lz4 unavailable")
        spooled = client.execute(
            "SELECT n_nationkey FROM nation ORDER BY n_nationkey",
            data_encoding="json+lz4",
        )
        assert len(spooled.rows) == 25

    def test_segments_acked_and_freed(self, server, client):
        client.execute("SELECT n_name FROM nation", data_encoding="json")
        # the client acks (DELETEs) every segment it fetched
        assert server.spooling.list_segments() == []


class TestMetricsPrecision:
    def test_large_counter_full_precision(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("big_total").inc(12_345_678)
        assert "big_total 12345678" in reg.render()


class TestPrometheusConformance:
    """Text exposition format conformance (the scrape contract)."""

    def test_help_and_type_lines_once_per_name(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.counter("multi_total", {"shard": "a"}, help="a multi counter").inc()
        reg.counter("multi_total", {"shard": "b"}).inc(2)
        text = reg.render()
        assert text.count("# HELP multi_total a multi counter") == 1
        assert text.count("# TYPE multi_total counter") == 1
        assert '# HELP' not in text.split("# TYPE multi_total counter")[1]

    def test_label_escaping(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge("esc_gauge", {"q": 'a"b\\c\nd'}).set(1)
        text = reg.render()
        assert 'q="a\\"b\\\\c\\nd"' in text

    def test_counter_monotonic_across_scrapes(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("mono_total")
        values = []
        for _ in range(5):
            c.inc(3)
            line = [
                l for l in reg.render().splitlines()
                if l.startswith("mono_total ")
            ][0]
            values.append(float(line.split()[1]))
        assert values == sorted(values)
        with pytest.raises(ValueError):
            c.inc(-1)  # counters never go down

    def test_metrics_endpoint_content_type(self, server):
        resp = urllib.request.urlopen(f"http://{server.address}/v1/metrics")
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in resp.headers["Content-Type"]

    def test_counter_and_gauge_thread_safety(self):
        import threading

        from trino_tpu.runtime.metrics import Counter, Gauge, Histogram

        c, g, h = Counter(), Gauge(), Histogram(buckets=[0.5, 1.0])
        n, k = 8, 5000

        def work():
            for _ in range(k):
                c.inc()
                g.inc(2)
                g.dec()
                h.observe(0.25)

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n * k
        assert g.value == n * k
        assert h.count == n * k
        assert h.bucket_counts[0] == n * k


class TestHistogram:
    def test_exposition_cumulative_buckets(self):
        from trino_tpu.runtime.metrics import MetricsRegistry

        reg = MetricsRegistry()
        h = reg.histogram(
            "lat_secs", {"stage": "x"}, help="latency", buckets=[0.1, 1.0, 10.0]
        )
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        text = reg.render()
        assert "# TYPE lat_secs histogram" in text
        assert 'lat_secs_bucket{stage="x",le="0.1"} 1' in text
        assert 'lat_secs_bucket{stage="x",le="1"} 3' in text
        assert 'lat_secs_bucket{stage="x",le="10"} 4' in text
        assert 'lat_secs_bucket{stage="x",le="+Inf"} 5' in text
        assert 'lat_secs_count{stage="x"} 5' in text
        assert 'lat_secs_sum{stage="x"} 56.05' in text

    def test_exponential_buckets(self):
        from trino_tpu.runtime.metrics import exponential_buckets

        assert exponential_buckets(0.001, 2.0, 4) == (0.001, 0.002, 0.004, 0.008)

    def test_boundary_lands_in_bucket(self):
        from trino_tpu.runtime.metrics import Histogram

        h = Histogram(buckets=[1.0, 2.0])
        h.observe(1.0)  # le="1" is inclusive
        assert h.bucket_counts[0] == 1

    def test_quantile_interpolation(self):
        import math

        from trino_tpu.runtime.metrics import histogram_quantile

        # 10 observations uniform in (0, 1], 10 in (1, 2]
        buckets = [(1.0, 10), (2.0, 20), (math.inf, 20)]
        assert histogram_quantile(buckets, 20, 0.5) == 1.0
        assert histogram_quantile(buckets, 20, 0.25) == 0.5
        assert abs(histogram_quantile(buckets, 20, 0.95) - 1.9) < 1e-9
        # empty series -> None; rank past the last finite bound clamps to it
        assert histogram_quantile(buckets, 0, 0.5) is None
        assert histogram_quantile([(1.0, 0), (math.inf, 5)], 5, 0.5) == 1.0


class TestTraceContextPropagation:
    def test_pool_thread_spans_join_parent_trace(self):
        """Spans opened on a pooled thread re-parent into the submitting
        thread's trace via capture()/attach() (the OOC prefetcher / FTE
        task-thread fix) instead of starting an orphan trace."""
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            with tr.span("query") as root:
                ctx = tr.capture()

                def job():
                    with tr.attach(ctx):
                        with tr.span("prefetch") as child:
                            return child

                child = pool.submit(job).result()
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            spans = tr.trace(root.trace_id)
            assert [s["name"] for s in spans] == ["query", "prefetch"]
        finally:
            pool.shutdown()

    def test_wrap_captures_at_wrap_time(self):
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            with tr.span("query") as root:
                def job():
                    with tr.span("inner") as s:
                        return s

                wrapped = tr.wrap(job)
            # runs AFTER the parent closed — parentage still holds
            child = pool.submit(wrapped).result()
            assert child.trace_id == root.trace_id
        finally:
            pool.shutdown()

    def test_remote_ids_cross_wire_boundary(self):
        """capture_ids()/attach_remote(): trace parentage shipped in a task
        descriptor over HTTP (the FTE task-thread path — a same-process
        capture can't carry it)."""
        from trino_tpu.runtime.tracing import Tracer
        from trino_tpu.server.worker import (
            TaskDescriptor,
            decode_task,
            encode_task,
        )

        tr = Tracer()
        with tr.span("query") as root:
            ids = tr.capture_ids()
        assert ids == {"trace_id": root.trace_id, "span_id": root.span_id}
        desc = decode_task(encode_task(TaskDescriptor(trace=ids)))
        assert desc.trace == ids
        with tr.attach_remote(desc.trace):
            with tr.span("task") as s:
                pass
        assert s.trace_id == root.trace_id
        assert s.parent_id == root.span_id
        assert tr.capture_ids() is None  # phantom popped cleanly

    def test_attach_none_is_noop(self):
        from trino_tpu.runtime.tracing import Tracer

        tr = Tracer()
        with tr.attach(tr.capture()):  # nothing current -> no parent
            with tr.span("solo") as s:
                pass
        assert s.parent_id is None

    def test_ooc_prefetch_spans_join_query_trace(self):
        """End-to-end: the OOC bucket prefetcher's pool-side spans land in
        the enclosing query trace."""
        from trino_tpu.runtime import LocalQueryRunner
        from trino_tpu.runtime.ooc import OutOfCoreRunner
        from trino_tpu.runtime.tracing import TRACER

        r = LocalQueryRunner.tpch(scale=0.001)
        plan = r.plan_sql(
            "SELECT o_custkey, count(*) FROM orders, lineitem "
            "WHERE o_orderkey = l_orderkey GROUP BY o_custkey"
        )
        with TRACER.span("query") as root:
            ooc = OutOfCoreRunner(
                plan, r.metadata, r.session, n_buckets=4, split_batch=2
            )
            ooc.execute()
        names = [s["name"] for s in TRACER.trace(root.trace_id)]
        assert "ooc.prefetch" in names


class TestFlightRecorder:
    def test_disabled_records_nothing(self):
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder()
        with rec.span("x", "test"):
            rec.instant("y", "test")
        assert rec.events() == []

    def test_bounded_ring(self):
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder(capacity=16)
        rec.enable()
        for i in range(100):
            rec.instant(f"e{i}", "test")
        events = rec.events()
        assert len(events) == 16
        assert events[-1]["name"] == "e99"

    def test_dropped_events_counted(self):
        """Ring truncation is visible: dropped_events counts overflow and
        rides the chrome_trace export (never silent loss)."""
        from trino_tpu.runtime.observability import FlightRecorder

        rec = FlightRecorder(capacity=16)
        rec.enable()
        for i in range(100):
            rec.instant(f"e{i}", "test")
        assert rec.dropped_events == 84
        assert rec.chrome_trace()["droppedEvents"] == 84
        rec.clear()
        assert rec.dropped_events == 0
        rec.instant("after", "test")
        assert rec.chrome_trace()["droppedEvents"] == 0

    def test_ring_capacity_from_env(self, monkeypatch):
        from trino_tpu.runtime.observability import FlightRecorder

        monkeypatch.setenv("TRINO_TPU_FLIGHT_RING", "32")
        rec = FlightRecorder()
        assert rec._buf.maxlen == 32
        monkeypatch.setenv("TRINO_TPU_FLIGHT_RING", "not-a-number")
        assert FlightRecorder()._buf.maxlen == 65536
        monkeypatch.delenv("TRINO_TPU_FLIGHT_RING")
        assert FlightRecorder()._buf.maxlen == 65536

    def test_chrome_trace_validates(self):
        from trino_tpu.runtime.observability import (
            FlightRecorder,
            validate_chrome_trace,
        )

        rec = FlightRecorder()
        rec.enable()
        with rec.span("outer", "test", tag=1):
            with rec.span("inner", "test"):
                rec.instant("point", "test", bytes=7)
        rec.complete("compile", "test", 0.001)
        trace = rec.chrome_trace()
        assert validate_chrome_trace(trace) == []
        names = [e["name"] for e in trace["traceEvents"]]
        assert "process_name" in names and "thread_name" in names

    def test_validator_catches_unpaired_and_nonmonotonic(self):
        from trino_tpu.runtime.observability import validate_chrome_trace

        meta = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "p"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "t"}},
        ]
        unpaired = meta + [
            {"name": "a", "cat": "c", "ph": "B", "ts": 10, "pid": 1, "tid": 1}
        ]
        assert any("unclosed" in p for p in validate_chrome_trace(
            {"traceEvents": unpaired}
        ))
        backwards = meta + [
            {"name": "a", "cat": "c", "ph": "i", "ts": 10, "pid": 1, "tid": 1},
            {"name": "b", "cat": "c", "ph": "i", "ts": 5, "pid": 1, "tid": 1},
        ]
        assert any("monotonic" in p for p in validate_chrome_trace(
            {"traceEvents": backwards}
        ))
        unknown_tid = meta + [
            {"name": "a", "cat": "c", "ph": "i", "ts": 1, "pid": 1, "tid": 9}
        ]
        assert any("undeclared tid" in p for p in validate_chrome_trace(
            {"traceEvents": unknown_tid}
        ))

    def test_flightrecorder_endpoint(self, server, client):
        from trino_tpu.runtime.observability import RECORDER, validate_chrome_trace

        RECORDER.clear()
        RECORDER.enable()
        try:
            client.execute("SELECT count(*) FROM region")
        finally:
            RECORDER.disable()
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/flightrecorder"
            ).read()
        )
        assert validate_chrome_trace(info) == []
        cats = {e.get("cat") for e in info["traceEvents"]}
        assert "query" in cats


class TestQueryStatsPlane:
    def test_explain_analyze_verbose_reports_attribution(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        res = r.execute(
            "EXPLAIN ANALYZE VERBOSE "
            "SELECT n_name, count(*) FROM supplier, nation "
            "WHERE s_nationkey = n_nationkey GROUP BY n_name"
        )
        text = "\n".join(line for (line,) in res.rows)
        assert "Join" in text
        assert "device=" in text and "host=" in text and "compile=" in text
        # plain ANALYZE keeps the compact annotation
        res2 = r.execute(
            "EXPLAIN ANALYZE SELECT count(*) FROM nation"
        )
        text2 = "\n".join(line for (line,) in res2.rows)
        assert "time=" in text2 and "device=" not in text2

    def test_query_stats_collected_async(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        res = r.execute("SELECT count(*) FROM lineitem")
        qs = res.query_stats
        assert qs is not None and not qs["syncMode"]
        assert qs["times"]["dispatch_secs"] > 0

    def test_query_stats_sync_mode_per_operator(self):
        from trino_tpu.metadata import Session
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        r.session.set("query_stats_sync", True)
        res = r.execute("SELECT l_returnflag, count(*) FROM lineitem GROUP BY l_returnflag")
        qs = res.query_stats
        assert qs["syncMode"]
        assert "AggregationNode" in qs["operators"]
        agg = qs["operators"]["AggregationNode"]
        assert agg["invocations"] >= 1 and agg["rows"] >= 1

    def test_v1_query_exposes_plane_fields(self, server, client):
        res = client.execute("SELECT count(*) FROM nation")
        info = json.loads(
            urllib.request.urlopen(
                f"http://{server.address}/v1/query/{res.query_id}"
            ).read()
        )
        qs = info["queryStats"]
        for field in (
            "deviceBusyTime", "hostWaitTime", "analysisTime",
            "spilledDataSize", "internalNetworkInputDataSize",
            "internalNetworkOutputDataSize", "compileCount",
        ):
            assert field in qs, field

    def test_spill_counters_reach_plane(self):
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner.tpch(scale=0.001)
        r.session.set("spill_operator_threshold_bytes", 1024)
        res = r.execute(
            "SELECT o_custkey, count(*) FROM orders GROUP BY o_custkey"
        )
        qs = res.query_stats
        assert qs["counts"]["spill_write_bytes"] > 0
        assert qs["counts"]["spill_read_bytes"] > 0


class TestSmokeCheck:
    """The tier-1 observability smoke check (satellite: CI/tooling)."""

    def test_smoke_check_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_smoke() == []

    def test_exchange_smoke_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_exchange_smoke() == []

    def test_memory_smoke_passes(self):
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_memory_smoke() == []

    def test_stats_smoke_passes(self):
        """The statistics-feedback-plane smoke: paired/monotonic
        cardinality_misestimate events + schema-checked operator_stats."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_stats_smoke() == []

    def test_cache_smoke_passes(self):
        """The warm-path cache-plane smoke: paired cache_lookup/cache_store/
        cache_invalidate spans with hit/miss outcomes, schema-checked
        system.runtime.caches, HELP-linted tier counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_cache_smoke() == []

    def test_batching_smoke_passes(self):
        """The device-batching-plane smoke: paired batch_admit/batch_launch/
        batch_demux spans with lane counts and packed rows on the E-args,
        bit-identical concurrent burst, shared-scan elimination, HELP-linted
        batching metrics."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_batching_smoke() == []

    def test_megakernel_smoke_passes(self):
        """The megakernel-plane smoke: paired pallas_compile/pallas_launch
        spans with shape class + fused-op list on the E-args, bit-identical
        fused vs serial run, strictly fewer device programs, HELP-linted
        launch/fallback counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_megakernel_smoke() == []

    def test_tensor_smoke_passes(self):
        """The tensor-plane smoke: paired vector_kernel/topk_fusion spans
        with rows/dim/k on the E-args, fused top-k bit-identical to the
        serial pair, strictly fewer device programs, HELP-linted
        launch/fallback counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_tensor_smoke() == []

    def test_ha_smoke_passes(self):
        """The serving-fabric-plane smoke: paired leader_lease/
        dispatch_replay/worker_drain spans, lease takeover under chaos
        expiry, a crash->resume round trip bit-identical to the oracle,
        torn-tail journal recovery, HELP-linted failover/renewal/torn
        counters."""
        import importlib.util
        import os

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = importlib.util.spec_from_file_location(
            "obs_smoke", os.path.join(tools, "obs_smoke.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.run_ha_smoke() == []


class TestSchemaFilterRules:
    def test_table_scoped_deny_does_not_hide_schema(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"schema": "sales", "table": "secret", "privileges": []},
                    {"schema": "sales", "privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["sales"]) == ["sales"]

    def test_whole_schema_deny_hides(self):
        from trino_tpu.spi.security import RuleBasedAccessControl

        ac = RuleBasedAccessControl.from_config(
            {
                "tables": [
                    {"user": "bob", "schema": "secret", "privileges": []},
                    {"privileges": ["SELECT"]},
                ]
            }
        )
        assert ac.filter_schemas("bob", "c", ["secret", "open"]) == ["open"]
        assert ac.filter_schemas("alice", "c", ["secret"]) == ["secret"]
