"""Device batching plane (ISSUE 11, runtime/device_scheduler.py): ragged
multi-query packing, shared-scan elimination, priority admission, and the
bit-identity + failure-isolation contracts that gate it."""

import threading
import time

import pytest

from trino_tpu.runtime.device_scheduler import (
    SCHEDULER,
    _LaunchGate,
    current_priority,
    priority_scope,
)
from trino_tpu.runtime.local import LocalQueryRunner

Q1 = """
    SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*)
    FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
    GROUP BY l_returnflag, l_linestatus
    ORDER BY l_returnflag, l_linestatus"""
Q3 = """
    SELECT o_orderkey, sum(l_extendedprice)
    FROM lineitem JOIN orders ON l_orderkey = o_orderkey
    WHERE o_orderdate < DATE '1995-03-15'
    GROUP BY o_orderkey ORDER BY 2 DESC, 1 LIMIT 10"""
Q6 = """
    SELECT sum(l_extendedprice * l_discount)
    FROM lineitem
    WHERE l_shipdate >= DATE '1994-01-01'
      AND l_shipdate < DATE '1995-01-01'
      AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24"""
Q13 = """
    SELECT c_custkey, count(o_orderkey)
    FROM customer LEFT JOIN orders ON c_custkey = o_custkey
    GROUP BY c_custkey ORDER BY 2 DESC, 1 LIMIT 10"""
MIX = [Q1, Q3, Q6, Q13]


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


@pytest.fixture(scope="module")
def baselines(runner):
    """Serial, batching-off reference rows for every mix query."""
    return {sql: runner.execute(sql).rows for sql in MIX}


@pytest.fixture
def batching(runner):
    """device_batching=on for the duration of a test, stats reset."""
    runner.session.set("device_batching", True)
    SCHEDULER.reset_stats()
    try:
        yield runner
    finally:
        runner.session.properties.pop("device_batching", None)
        SCHEDULER.reset_stats()


# --------------------------------------------------------------------------- #
# off-path byte-identity (the default must not change at all)
# --------------------------------------------------------------------------- #


class TestDisabledPath:
    def test_off_attaches_nothing_and_never_consults_scheduler(
        self, runner, baselines, monkeypatch
    ):
        def boom(*a, **k):
            raise AssertionError("scheduler consulted with batching off")

        monkeypatch.setattr(SCHEDULER, "execute", boom)
        monkeypatch.setattr(SCHEDULER, "shared_scan", boom)
        assert runner.execute(Q1).rows == baselines[Q1]
        assert runner.execute(Q6).rows == baselines[Q6]

    def test_default_is_off(self, runner):
        assert bool(runner.session.get("device_batching")) is False

    def test_on_off_identical_single_query(self, batching, baselines):
        for sql in MIX:
            assert batching.execute(sql).rows == baselines[sql]


# --------------------------------------------------------------------------- #
# 16-client mixed replay: bit-identity, incl. under chaos
# --------------------------------------------------------------------------- #


def _replay(runner, baselines, n_clients=16, per_client=3):
    """The BENCH_r09-shaped mixed replay on raw threads; asserts every
    result equals its serial baseline."""
    errors = []
    barrier = threading.Barrier(n_clients)

    def client(cid):
        try:
            barrier.wait(timeout=60)
            for j in range(per_client):
                sql = MIX[(cid + j) % len(MIX)]
                rows = runner.execute(sql).rows
                if rows != baselines[sql]:
                    errors.append(f"client {cid} query {j} diverged")
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append(f"client {cid}: {type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=client, args=(c,)) for c in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]


class TestMixedReplayBitIdentity:
    def test_16_clients_bit_identical(self, batching, baselines):
        _replay(batching, baselines)
        # the plane actually engaged: scans were shared and/or lanes packed
        assert SCHEDULER.scan_shares > 0 or SCHEDULER.batched_launches > 0

    def test_16_clients_under_task_stall_chaos(self, batching, baselines):
        from trino_tpu.runtime.failure import ChaosInjector

        with ChaosInjector() as chaos:
            chaos.arm("task_stall", times=4, delay=0.05)
            _replay(batching, baselines, n_clients=8, per_client=2)

    def test_mid_batch_kill_fails_only_victim_lanes(self, baselines):
        """A low-memory kill landing while batched lanes are in flight must
        fail ONLY the victim's queries: survivors stay bit-identical and no
        query fails for any reason other than the administrative kill."""
        from trino_tpu.runtime.failure import ChaosInjector
        from trino_tpu.runtime.memory import (
            ClusterMemoryManager,
            MemoryPool,
            TotalReservationOnBlockedNodesLowMemoryKiller,
            memory_scope,
        )
        from trino_tpu.runtime.query_manager import QueryManager, QueryState

        runner = LocalQueryRunner.tpch(scale=0.01)
        runner.session.set("device_batching", True)
        probe = MemoryPool(0, name="batch_probe")
        with memory_scope("probe", probe):
            for sql in MIX:
                runner.execute(sql)
        pool = MemoryPool(
            3 * probe.peak_bytes, name="batch_kill", reserve_timeout=120
        )
        cm = ClusterMemoryManager(
            pool, killer=TotalReservationOnBlockedNodesLowMemoryKiller(),
            spill_after=0.0, kill_after=0.001,
        )
        mgr = QueryManager(runner.execute, max_workers=16, cluster_memory=cm)
        SCHEDULER.reset_stats()
        with ChaosInjector() as chaos:
            # phantom pool pressure on top of real overload: the killer
            # fires while batched lanes from many queries are in flight
            chaos.arm(
                "memory_pressure", times=2,
                bytes=2 * probe.peak_bytes, hold=0.05,
            )
            qs = [mgr.submit(MIX[i % len(MIX)]) for i in range(24)]
            for q in qs:
                assert q.wait_done(300), f"query {q.query_id} WEDGED"
        finished = [q for q in qs if q.state is QueryState.FINISHED]
        unexpected = [
            q for q in qs
            if q.state is not QueryState.FINISHED
            and q.error_type != "AdministrativelyKilled"
        ]
        assert not unexpected, (
            f"non-kill failures: {[(q.error_type, q.error) for q in unexpected]}"
        )
        assert finished, "everything was killed"
        for q in finished:
            assert q.rows == baselines[q.sql], f"survivor {q.query_id} diverged"
        assert pool.reserved_bytes == 0 and pool.revocable_bytes == 0


# --------------------------------------------------------------------------- #
# shared-scan elimination
# --------------------------------------------------------------------------- #


class TestSharedScans:
    def test_16_concurrent_overlapping_queries_one_leaf_scan(
        self, batching, baselines
    ):
        """16 concurrent identical queries -> their lineitem leaf scan
        executes a small constant number of times (the flight winner plus
        at most stragglers that missed the linger window), NOT 16."""
        batching.execute(Q1)  # warm compile so the burst overlaps
        SCHEDULER.reset_stats()
        errors = []
        barrier = threading.Barrier(16)

        def go(i):
            try:
                barrier.wait(timeout=60)
                if batching.execute(Q1).rows != baselines[Q1]:
                    errors.append(f"{i} diverged")
            except Exception as e:  # noqa: BLE001
                errors.append(f"{i}: {e}")

        threads = [threading.Thread(target=go, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        total = SCHEDULER.scan_executions + SCHEDULER.scan_shares
        assert total >= 16
        assert SCHEDULER.scan_shares >= 12, (
            f"shared-scan elimination barely engaged: "
            f"executions={SCHEDULER.scan_executions} "
            f"shares={SCHEDULER.scan_shares}"
        )
        assert SCHEDULER.scan_executions <= 4

    def test_never_shares_across_dml(self, baselines):
        """A post-INSERT arrival must never see the pre-INSERT page: the
        scan key carries the connector version token."""
        from trino_tpu.connectors.memory import MemoryConnector

        runner = LocalQueryRunner.tpch(scale=0.01)
        runner.register_catalog("mem", MemoryConnector())
        runner.execute("CREATE TABLE mem.default.kv (x bigint)")
        runner.execute("INSERT INTO mem.default.kv VALUES (1), (2)")
        runner.session.set("device_batching", True)
        q = "SELECT count(*) FROM mem.default.kv"
        assert runner.execute(q).rows == [(2,)]
        runner.execute("INSERT INTO mem.default.kv VALUES (3)")
        assert runner.execute(q).rows == [(3,)]

    def test_time_travel_pin_never_shares_with_current(self, tmp_path):
        """Regression (review finding): a FOR VERSION scan must key
        separately from a current-version scan of the same table — the
        pinned snapshot rides the shared-scan key."""
        from trino_tpu.connectors.iceberg_lite import IcebergLiteConnector
        from trino_tpu.fs import FileSystemManager, LocalFileSystem

        fsm = FileSystemManager()
        fsm.register("local", lambda: LocalFileSystem(str(tmp_path)))
        r = LocalQueryRunner.tpch(scale=0.01)
        r.register_catalog("berg", IcebergLiteConnector(fsm, "local://wh"))
        r.execute("CREATE TABLE berg.default.kv AS SELECT 1 AS x")
        r.execute("INSERT INTO berg.default.kv VALUES (2)")
        r.session.set("device_batching", True)
        SCHEDULER.reset_stats()
        cur = "SELECT count(*) FROM berg.default.kv"
        pin = "SELECT count(*) FROM berg.default.kv FOR VERSION AS OF 1"
        assert r.execute(cur).rows == [(2,)]
        # within the shared-scan TTL: the pinned read must NOT be served
        # the current scan's pages
        assert r.execute(pin).rows == [(1,)]
        assert r.execute(cur).rows == [(2,)]

    def test_scan_winner_failure_falls_back(self, batching, monkeypatch):
        """A dying scan winner publishes its error; the next arrival
        executes the scan itself instead of inheriting the failure or
        wedging. Exercised directly on the scheduler API with a pinned
        scan key."""
        from trino_tpu.runtime import device_scheduler as ds

        calls = {"n": 0}
        entry_key = ("t", "s", "l:x", "v", ("a",))
        monkeypatch.setattr(
            ds.DeviceScheduler, "_scan_key", lambda self, b, n: entry_key
        )

        class _Node:
            assignments = (("sym_a", "a"),)

        class _Rel:
            page = object()
            symbols = ("sym_a",)
            sorted_by = ()

        class _B:
            metadata = None
            scope = ""
            registry = ""

        def failing_inner(node):
            calls["n"] += 1
            raise RuntimeError("scan died")

        with pytest.raises(RuntimeError):
            SCHEDULER.shared_scan(_B(), None, _Node(), failing_inner)
        # the failed flight is not served to the next caller: it executes
        ok_rel = _Rel()

        def ok_inner(node):
            calls["n"] += 1
            return ok_rel

        assert SCHEDULER.shared_scan(_B(), None, _Node(), ok_inner) is ok_rel
        assert calls["n"] == 2


# --------------------------------------------------------------------------- #
# ragged multi-lane packing
# --------------------------------------------------------------------------- #


class TestRaggedPacking:
    def test_fte_partitions_pack_into_one_ragged_launch(self):
        """Concurrent FTE task attempts of one fragment (same program,
        DIFFERENT split data per partition) are the genuine ragged case:
        they pack into a multi-lane vmapped launch, bit-identical to the
        batching-off run."""
        from trino_tpu.parallel.runner import DistributedQueryRunner

        dr = DistributedQueryRunner.tpch(
            scale=0.01, n_workers=4, split_target_rows=4096
        )
        dr.session.set("retry_policy", "TASK")
        off = dr.execute(Q1).rows
        dr.session.set("device_batching", True)
        # a wide admission window: concurrent attempts must land in one
        # group even when this box's scheduler staggers their dispatch
        dr.session.set("batch_admit_window_ms", 100.0)
        packed = False
        for _ in range(3):  # dispatch timing on a 1-core box can drift
            SCHEDULER.reset_stats()
            on = dr.execute(Q1).rows
            assert on == off
            if SCHEDULER.batched_launches >= 1:
                packed = True
                break
        assert packed, (
            f"no ragged launch in 3 runs: singles={SCHEDULER.single_launches}"
        )

    def test_lane_occupancy_histogram_observes(self, batching, baselines):
        from trino_tpu.runtime.metrics import REGISTRY

        h = REGISTRY.histogram(
            "trino_tpu_batch_lane_occupancy", buckets=[1, 2, 4, 8, 16, 32]
        )
        before = h.count
        _replay(batching, baselines, n_clients=4, per_client=1)
        assert h.count > before

    def test_batched_launch_counts_strictly_fewer(self, runner, baselines):
        """The attribution metric: the same concurrent burst dispatches
        strictly fewer device programs with batching on."""
        from trino_tpu.runtime.device_scheduler import program_launches

        runner.execute(Q1)  # warm
        n0 = program_launches()
        _replay(runner, baselines, n_clients=8, per_client=1)
        off_launches = program_launches() - n0
        runner.session.set("device_batching", True)
        try:
            runner.execute(Q1)  # warm the batched path
            SCHEDULER.reset_stats()
            n1 = program_launches()
            _replay(runner, baselines, n_clients=8, per_client=1)
            on_launches = program_launches() - n1
        finally:
            runner.session.properties.pop("device_batching", None)
        assert on_launches < off_launches, (
            f"batching on dispatched {on_launches} programs vs "
            f"{off_launches} off"
        )


# --------------------------------------------------------------------------- #
# priority admission
# --------------------------------------------------------------------------- #


class TestPriorityAdmission:
    def test_gate_admits_highest_weight_first(self):
        gate = _LaunchGate()
        order = []
        gate.acquire(1.0)  # hold the gate
        ready = threading.Barrier(3)

        def waiter(name, weight):
            ready.wait(timeout=30)
            time.sleep({"low": 0.0, "high": 0.05}[name])  # low queues FIRST
            gate.acquire(weight)
            order.append(name)
            gate.release()

        ts = [
            threading.Thread(target=waiter, args=("low", 1.0)),
            threading.Thread(target=waiter, args=("high", 8.0)),
        ]
        for t in ts:
            t.start()
        ready.wait(timeout=30)
        time.sleep(0.3)  # both queued behind the held gate
        gate.release()
        for t in ts:
            t.join(30)
        assert order == ["high", "low"], order

    def test_priority_scope_rides_the_thread(self):
        assert current_priority() == 1.0
        with priority_scope(7):
            assert current_priority() == 7.0
            with priority_scope(2):
                assert current_priority() == 2.0
            assert current_priority() == 7.0
        assert current_priority() == 1.0

    def test_fair_executor_drains_heavier_group_first(self):
        """Regression (ISSUE 11 satellite): the per-query FIFO used to
        ignore resource-group weight when popping — with equal accumulated
        usage, the weight-4 query's task must pop BEFORE the weight-1
        query's even though it was submitted later."""
        from trino_tpu.server.worker import FairTaskExecutor

        ex = FairTaskExecutor(n_threads=1)
        try:
            done = threading.Event()

            def prime():
                time.sleep(0.05)

            # both queries accrue ~equal usage so the weighted key decides
            for q, w in (("qa", 1.0), ("qb", 4.0)):
                fin = threading.Event()

                def task(fin=fin):
                    prime()
                    fin.set()

                ex.submit(q, f"{q}_prime", task, weight=w)
                assert fin.wait(30)
            blocker_go = threading.Event()
            blocked = threading.Event()

            def blocker():
                blocked.set()
                blocker_go.wait(30)

            ex.submit("qc", "qc_block", blocker)
            assert blocked.wait(30)
            order = []

            def mk(name):
                def run():
                    order.append(name)
                    if len(order) == 2:
                        done.set()
                return run

            # qa submitted FIRST; qb's weight must still pop it first
            ex.submit("qa", "qa_t", mk("qa"), weight=1.0)
            ex.submit("qb", "qb_t", mk("qb"), weight=4.0)
            blocker_go.set()
            assert done.wait(30)
            assert order == ["qb", "qa"], order
        finally:
            ex.stop()

    def test_task_descriptor_carries_priority(self):
        from trino_tpu.server.worker import (
            TaskDescriptor,
            decode_task,
            encode_task,
        )

        desc = TaskDescriptor(root=None, types={}, priority=4.0)
        assert decode_task(encode_task(desc)).priority == 4.0
        # default stays off the wire and decodes to 1.0
        d2 = decode_task(encode_task(TaskDescriptor(root=None, types={})))
        assert d2.priority == 1.0


# --------------------------------------------------------------------------- #
# knobs
# --------------------------------------------------------------------------- #


class TestKnobs:
    def test_declared_in_registry(self):
        from trino_tpu.knobs import SESSION_PROPERTIES

        names = {p.name for p in SESSION_PROPERTIES}
        assert {
            "device_batching", "batch_max_lanes", "batch_admit_window_ms",
        } <= names

    def test_batching_knobs_do_not_split_cache_keys(self, runner):
        from trino_tpu.metadata import Session
        from trino_tpu.runtime.cachestore import session_props_key

        a = Session(catalog="tpch", schema="sf0_01")
        b = Session(catalog="tpch", schema="sf0_01")
        b.set("device_batching", True)
        b.set("batch_max_lanes", 4)
        assert session_props_key(a) == session_props_key(b)

    def test_plan_flight_shares_and_gates(self, batching, baselines):
        """Concurrent identical statements share one planning pass; the
        plan-cache correctness gates (nondeterministic text) bypass it."""
        batching.execute(Q6)  # prime
        SCHEDULER.reset_stats()
        errors = []
        barrier = threading.Barrier(8)

        def go(i):
            try:
                barrier.wait(timeout=60)
                if batching.execute(Q6).rows != baselines[Q6]:
                    errors.append(f"{i} diverged")
            except Exception as e:  # noqa: BLE001
                errors.append(f"{i}: {e}")

        threads = [threading.Thread(target=go, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert SCHEDULER.plans_shared > 0
        # nondeterministic text must never ride a shared plan
        n0 = SCHEDULER.plans_shared
        r1 = batching.execute("SELECT random() < 2 FROM nation LIMIT 1")
        r2 = batching.execute("SELECT random() < 2 FROM nation LIMIT 1")
        assert r1.rows == r2.rows == [(True,)]
        assert SCHEDULER.plans_shared == n0

    def test_plan_flight_never_keys_execute_text(self, batching):
        """Regression (review finding): re-PREPAREing a name with a new
        body and EXECUTE-ing within the linger window must never serve the
        OLD body's plan — EXECUTE text never keys a plan flight."""
        batching.execute("PREPARE pf FROM SELECT count(*) FROM nation")
        r1 = batching.execute("EXECUTE pf")
        batching.execute("PREPARE pf FROM SELECT count(*) FROM region")
        r2 = batching.execute("EXECUTE pf")
        assert r1.rows == [(25,)]
        assert r2.rows == [(5,)]

    def test_max_lanes_one_still_correct(self, runner, baselines):
        runner.session.set("device_batching", True)
        runner.session.set("batch_max_lanes", 1)
        try:
            assert runner.execute(Q1).rows == baselines[Q1]
        finally:
            runner.session.properties.pop("device_batching", None)
            runner.session.properties.pop("batch_max_lanes", None)
