"""Relational kernels: group-by, join, sort/TopN, limit — XLA-native, static shapes.

Reference blueprint (SURVEY.md §2.5, §3.2 "hot loops"): FlatHash.putIfAbsent
(operator/FlatHash.java:251), PagesHash/JoinProbe (operator/join/), TopNOperator.
Trino's hot structures are open-addressing hash tables built row-at-a-time; on TPU
scatter-heavy hashing is hostile to the memory model, so every kernel here is
*sort-based* (SURVEY.md §7 "sort-based fallback" promoted to the primary strategy):

- group-by: lexsort keys -> boundary detection -> segment reductions. O(n log n)
  but fully vectorized on the VPU, no data-dependent shapes.
- join: argsort build keys -> searchsorted probes -> rank-space expansion. The
  expansion trick (searchsorted over match-offset prefix sums) produces arbitrary
  1:N matches into a *static* output capacity.
- TopN/sort: lexsort with direction/null-order encoded as extra key columns.

All kernels are mask-oblivious: inactive rows ride along with sentinel keys and are
dropped by the output ``active`` mask. Everything traces under jit/shard_map.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT64_MAX = np.iinfo(np.int64).max
INT64_MIN = np.iinfo(np.int64).min


def float_order_key(data: jnp.ndarray) -> jnp.ndarray:
    """IEEE doubles -> order-preserving signed int64 (sign-magnitude unfold:
    positives keep their bits, negatives map to ~bits with the sign bit set)."""
    bits = data.astype(jnp.float64).view(jnp.int64)
    return jnp.where(bits < 0, jnp.bitwise_xor(~bits, jnp.int64(INT64_MIN)), bits)


def order_key(data: jnp.ndarray) -> jnp.ndarray:
    if jnp.issubdtype(data.dtype, jnp.floating):
        return float_order_key(data)
    if data.dtype == jnp.bool_:
        return data.astype(jnp.int64)
    return data.astype(jnp.int64)


def encode_sort_column(
    data: jnp.ndarray, valid: jnp.ndarray, ascending: bool = True, nulls_first: bool = False
) -> jnp.ndarray:
    k = order_key(data)
    if not ascending:
        # avoid overflow on INT64_MIN: bitwise not (== -x-1) is order-reversing
        k = ~k
    sentinel = jnp.int64(INT64_MIN) if nulls_first else jnp.int64(INT64_MAX)
    return jnp.where(valid, k, sentinel)


def encode_sort_columns(
    data: jnp.ndarray, valid: jnp.ndarray, ascending: bool = True, nulls_first: bool = False
) -> List[jnp.ndarray]:
    """Sort keys for one column, most-significant first — usually one key;
    Int128 limb columns (ndim 2) contribute TWO (hi, then unsigned lo), the
    pad-and-mask long-decimal ordering (ref spi/type/Int128.java compareTo)."""
    if data.ndim == 2:
        from . import int128 as i128

        h, l = i128.order_key_pair(data)
        if not ascending:
            h, l = ~h, ~l
        sentinel = jnp.int64(INT64_MIN) if nulls_first else jnp.int64(INT64_MAX)
        return [jnp.where(valid, h, sentinel), jnp.where(valid, l, sentinel)]
    return [encode_sort_column(data, valid, ascending, nulls_first)]


def splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """SplitMix64 finalizer: int64 -> well-mixed int64 (wrapping arithmetic)."""
    x = x.astype(jnp.int64) + jnp.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15
    x = (x ^ jax.lax.shift_right_logical(x, jnp.int64(30))) * jnp.int64(
        -4658895280553007687  # 0xBF58476D1CE4E5B9
    )
    x = (x ^ jax.lax.shift_right_logical(x, jnp.int64(27))) * jnp.int64(
        -7723592293110705685  # 0x94D049BB133111EB
    )
    return x ^ jax.lax.shift_right_logical(x, jnp.int64(31))


HLL_BITS = 11  # 2048 registers -> standard error 1.04/sqrt(2048) ~= 2.3%,
# matching the reference's default (spi/block -> airlift HyperLogLog,
# operator/aggregation/ApproximateCountDistinctAggregations default 0.023).


def hll_registers(
    vals: jnp.ndarray,
    weight: jnp.ndarray,
    gid: jnp.ndarray,
    num_groups: int,
    bits: int = HLL_BITS,
) -> jnp.ndarray:
    """Per-group HyperLogLog registers [num_groups, 2**bits] (int32).

    Each row hashes its value (SplitMix64 over the order key), takes the top
    ``bits`` bits as the bucket and the leading-zero count of the rest (+1) as
    rho; registers are the per-(group, bucket) max of rho via one scatter-max.
    This replaces the exact path's full cosort with a single scatter and a
    bounded [G, m] state — the property that matters at SF100 cardinalities.
    """
    m = 1 << bits
    h = splitmix64(order_key(vals))
    bucket = jax.lax.shift_right_logical(h, jnp.int64(64 - bits))
    rest = jax.lax.shift_left(h, jnp.int64(bits))
    rho = jnp.where(rest == 0, jnp.int64(64 - bits + 1), jax.lax.clz(rest) + 1)
    ids = jnp.where(weight, gid.astype(jnp.int64) * m + bucket, num_groups * m)
    regs = jax.ops.segment_max(
        rho.astype(jnp.int32), ids.astype(jnp.int32), num_segments=num_groups * m + 1
    )[: num_groups * m].reshape(num_groups, m)
    return jnp.maximum(regs, 0)  # empty slots come back as int32 min


def hll_estimate(regs: jnp.ndarray) -> jnp.ndarray:
    """Bias-corrected HLL estimate per group from [G, m] registers -> int64[G].

    Standard estimator with the linear-counting small-range correction; the
    64-bit hash makes the large-range correction unnecessary."""
    m = regs.shape[1]
    z = jnp.sum(jnp.exp2(-regs.astype(jnp.float32)), axis=1)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    e = alpha * m * m / z
    v = jnp.sum((regs == 0).astype(jnp.int32), axis=1)
    small = (e <= 2.5 * m) & (v > 0)
    linear = m * jnp.log(m / jnp.maximum(v, 1).astype(jnp.float32))
    return jnp.round(jnp.where(small, linear, e)).astype(jnp.int64)


def cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """1-D inclusive cumsum that scales on TPU.

    XLA lowers big 1-D cumsums to a reduce-window whose scoped VMEM blows past
    the 16MB limit around a few million elements (observed at SF1). Two-level
    blocked scan: row-wise cumsum of (n/K, K) + exclusive prefix of row totals —
    every window stays K elements."""
    n = x.shape[0]
    K = 2048
    if n <= K * 4:
        return jnp.cumsum(x)
    pad = (-n) % K
    xp = jnp.pad(x, (0, pad)) if pad else x
    rows = xp.reshape(-1, K)
    within = jnp.cumsum(rows, axis=1)
    row_totals = within[:, -1]
    prefix = jnp.cumsum(row_totals) - row_totals
    out = (within + prefix[:, None]).reshape(-1)
    return out[:n] if pad else out


def lexsort_perm(keys: Sequence[jnp.ndarray], active: jnp.ndarray) -> jnp.ndarray:
    """Permutation sorting by keys (first = most significant); inactive rows last.

    Implemented as a chain of stable single-operand argsorts (least-significant
    key first) instead of one variadic lexsort: XLA's variadic sort comparator
    compiles catastrophically slowly on CPU as operand count x size grows, while
    single-key argsort + gather compiles linearly and runs equally fast.
    """
    perm = None
    cols = list(keys)[::-1] + [(~active).astype(jnp.int8)]
    for k in cols:
        if perm is None:
            perm = jnp.argsort(k)
        else:
            perm = perm[jnp.argsort(k[perm])]  # stable: earlier order preserved
    return perm


def cosort(pass_keys: Sequence[jnp.ndarray], payloads: Sequence[jnp.ndarray]):
    """Stable multi-pass sort carrying payloads inside lax.sort.

    ``pass_keys`` are applied least-significant first (the last is primary).
    Returns (sorted_pass_keys, sorted_payloads). Co-sorting avoids separate
    permutation gathers, which cost ~60ns/element on TPU — the sort itself
    moves the payload rows. Multi-pass single-key sorts are deliberate: the
    variadic lexicographic comparator (num_keys > 1) compiles catastrophically
    slowly in the TPU backend (>9 min for a 16-operand sort)."""
    arrays = list(pass_keys) + list(payloads)
    nkeys = len(pass_keys)
    for idx in range(nkeys):
        ops = (arrays[idx], *arrays[:idx], *arrays[idx + 1 :])
        res = jax.lax.sort(ops, num_keys=1, is_stable=True)
        arrays = list(res[1 : idx + 1]) + [res[0]] + list(res[idx + 1 :])
    return arrays[:nkeys], arrays[nkeys:]


def last_active_prev(vals: jnp.ndarray, active: jnp.ndarray):
    """For each row i, the value at the most recent ACTIVE row strictly before
    i (and whether one exists). One associative scan — lets presorted grouping
    skip sorts even when inactive (filtered) rows are interleaved."""

    def combine(a, b):
        av, ah = a
        bv, bh = b
        return jnp.where(bh, bv, av), ah | bh

    inc = jax.lax.associative_scan(
        combine, (jnp.where(active, vals, 0), active)
    )
    # exclusive: shift the inclusive scan right by one
    prev_vals = jnp.roll(inc[0], 1).at[0].set(0)
    prev_has = jnp.roll(inc[1], 1).at[0].set(False)
    return prev_vals, prev_has


def boundary_positions(new_group: jnp.ndarray, out_cap: int) -> jnp.ndarray:
    """Indices of the first out_cap True entries of ``new_group`` (ascending),
    padded with n for absent slots — computed with a sort, not nonzero()."""
    n = new_group.shape[0]
    idx = jnp.arange(n)
    keys, payload = cosort([(~new_group).astype(jnp.int8)], [idx])
    starts = payload[0][:out_cap]
    if starts.shape[0] < out_cap:  # out_cap may exceed tiny input capacities
        starts = jnp.pad(starts, (0, out_cap - starts.shape[0]), constant_values=n)
    rank = jnp.arange(out_cap)
    count = jnp.sum(new_group.astype(jnp.int32))
    return jnp.where(rank < count, starts, n)


# --------------------------------------------------------------------------- #
# group-by
# --------------------------------------------------------------------------- #


def group_ids(
    key_cols: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
    active: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort-based grouping (the FlatGroupByHash analogue).

    Returns (perm, gid_sorted, new_group_sorted, num_groups):
    - perm: sort permutation placing equal keys adjacent, inactive rows last
    - gid_sorted[i]: dense group id of sorted row i (valid where active)
    - new_group_sorted[i]: True at each group's first sorted row
    - num_groups: scalar count of groups
    """
    cap = active.shape[0]
    norm_keys = []
    for data, valid in key_cols:
        if data.ndim == 2:  # Int128 limbs: two grouping keys
            from . import int128 as i128

            h, l = i128.order_key_pair(data)
            norm_keys.append(jnp.where(valid, h, jnp.int64(INT64_MAX)))
            norm_keys.append(jnp.where(valid, l, jnp.int64(INT64_MAX)))
        else:
            k = order_key(data)
            k = jnp.where(valid, k, jnp.int64(INT64_MAX))  # nulls group last
            norm_keys.append(k)
        v = valid.astype(jnp.int8)  # distinguishes null from a real INT64_MAX
        norm_keys.append(v)
    if not norm_keys:
        # global aggregation: single group of active rows
        perm = jnp.arange(cap)
        gid = jnp.zeros(cap, dtype=jnp.int32)
        new_group = jnp.zeros(cap, dtype=bool).at[0].set(True)
        return perm, gid, new_group, jnp.int32(1)
    perm = lexsort_perm(norm_keys, active)
    active_s = active[perm]
    sorted_keys = [k[perm] for k in norm_keys]
    diff = jnp.zeros(cap, dtype=bool)
    for k in sorted_keys:
        diff = diff | (k != jnp.roll(k, 1))
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    prev_active = jnp.roll(active_s, 1).at[0].set(False)
    new_group = active_s & (first | diff | ~prev_active)
    gid = (cumsum(new_group.astype(jnp.int32)) - 1).astype(jnp.int32)
    num_groups = jnp.sum(new_group.astype(jnp.int32))
    return perm, gid, new_group, num_groups


# bitwise aggregate reduces (BitwiseAndAggregation/BitwiseOrAggregation —
# xor_agg added in newer reference versions): op + identity
_BIT_OPS = {
    "band": (lambda a, b: a & b, -1),
    "bor": (lambda a, b: a | b, 0),
    "bxor": (lambda a, b: a ^ b, 0),
}


def segment_reduce(
    values_sorted: jnp.ndarray,
    weight_sorted: jnp.ndarray,  # bool: row participates
    gid_sorted: jnp.ndarray,
    capacity: int,
    kind: str,
    new_group_sorted: Optional[jnp.ndarray] = None,
    bounds: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
):
    """Masked segment reduction into ``capacity`` output slots.

    For sum/count with segment boundaries available (``new_group_sorted``), uses
    the cumsum-at-boundaries formulation instead of scatter-add: rows are sorted
    by group, so segment g's sum is csum[end_g] - csum[start_g] + v[start_g].
    TPU scatters serialize; cumsum + two small gathers vectorize fully.
    """
    if capacity == 1:
        # global aggregation: plain masked reduction
        if kind == "sum":
            vals = jnp.where(weight_sorted, values_sorted, jnp.zeros_like(values_sorted))
            return jnp.sum(vals, keepdims=True)
        if kind == "count":
            return jnp.sum(weight_sorted.astype(jnp.int64), keepdims=True)
        if kind == "min":
            return jnp.min(values_sorted, keepdims=True)
        if kind == "max":
            return jnp.max(values_sorted, keepdims=True)
        if kind in _BIT_OPS:
            op, ident = _BIT_OPS[kind]
            vals = jnp.where(
                weight_sorted, values_sorted.astype(jnp.int64), jnp.int64(ident)
            )
            return jax.lax.reduce(vals, jnp.int64(ident), op, (0,))[None]
        raise ValueError(kind)
    if kind in ("sum", "count") and new_group_sorted is not None:
        vals = (
            weight_sorted.astype(jnp.int64)
            if kind == "count"
            else jnp.where(weight_sorted, values_sorted, jnp.zeros_like(values_sorted))
        )
        csum = cumsum(vals)
        n = values_sorted.shape[0]
        if bounds is not None:
            start, end = bounds
        else:
            idx = jnp.arange(n)
            # start[g] = first sorted row of group g; slots with no group default
            # to n so that end[g] = start[g+1] - 1 is n-1 for the last real group
            ids = jnp.where(new_group_sorted, gid_sorted, capacity).astype(jnp.int32)
            start = jnp.full((capacity + 1,), n).at[ids].set(idx, mode="drop")[:capacity]
            end = jnp.concatenate([start[1:], jnp.array([n])]) - 1
        end = jnp.clip(end, 0, n - 1)
        start = jnp.clip(start, 0, n - 1)
        return csum[end] - csum[start] + vals[start]
    if kind in _BIT_OPS:
        # segmented associative scan (rows are group-sorted): carry =
        # (segment-start flag, accumulated value); combining across a
        # boundary restarts the accumulator — the classic segmented-scan
        # trick, which TPU/XLA lowers to a log-depth scan instead of the
        # serialized scatter a segment_or would need
        op, ident = _BIT_OPS[kind]
        n = values_sorted.shape[0]
        vals = jnp.where(
            weight_sorted, values_sorted.astype(jnp.int64), jnp.int64(ident)
        )
        # rows of a group are CONTIGUOUS (group-sorted) but group ids are not
        # monotone along the array, and padding rows carry junk ids — so the
        # read point per group is the scatter-max row index over its
        # PARTICIPATING rows, not a start[g+1]-1 walk
        boundary = (
            new_group_sorted
            if new_group_sorted is not None
            else jnp.concatenate(
                [jnp.ones((1,), bool), gid_sorted[1:] != gid_sorted[:-1]]
            )
        )

        def combine(a, b):
            af, av = a
            bf, bv = b
            return af | bf, jnp.where(bf, bv, op(av, bv))

        _, scanned = jax.lax.associative_scan(combine, (boundary, vals))
        idx = jnp.arange(n, dtype=jnp.int32)
        ids = jnp.where(weight_sorted, gid_sorted, capacity).astype(jnp.int32)
        ends = (
            jnp.zeros((capacity + 1,), dtype=jnp.int32)
            .at[ids].max(idx, mode="drop")[:capacity]
        )
        # groups with zero participants read scanned[0] — callers mask their
        # validity by the participant count
        return scanned[ends]
    ids = jnp.where(weight_sorted, gid_sorted, capacity).astype(jnp.int32)
    if kind == "sum":
        vals = jnp.where(weight_sorted, values_sorted, jnp.zeros_like(values_sorted))
        out = jax.ops.segment_sum(vals, ids, num_segments=capacity + 1)
    elif kind == "count":
        out = jax.ops.segment_sum(
            weight_sorted.astype(jnp.int64), ids, num_segments=capacity + 1
        )
    elif kind == "min":
        out = jax.ops.segment_min(values_sorted, ids, num_segments=capacity + 1)
    elif kind == "max":
        out = jax.ops.segment_max(values_sorted, ids, num_segments=capacity + 1)
    else:
        raise ValueError(kind)
    return out[:capacity]


def direct_group_reduce(
    values: jnp.ndarray,
    weight: jnp.ndarray,  # bool: row participates
    gid: jnp.ndarray,
    num_groups: int,
    kind: str,
) -> jnp.ndarray:
    """Grouped reduction for SMALL static group counts — no sort, no scatter.

    out[g] = reduce(values[i] for rows with gid[i]==g and weight[i]). The
    [G, n] broadcast-mask formulation: XLA fuses the compare/select producers
    into one row-wise reduction pass over the data, so a whole Q1-style
    aggregation is bandwidth-bound instead of sort-bound. Use only when the
    group-key domain is statically known and small (dictionary-coded keys);
    for large/unknown G the sort path (group_ids + segment_reduce) wins.
    (ref: BigintGroupByHash's small-domain fast path, GroupByHash.java:82)
    """
    if jax.default_backend() == "cpu":
        # XLA:CPU materializes the [G, n] mask per reduction (measured 181 ms
        # per reduce at n=6M vs 18 ms for segment_sum); its scatter-add is
        # fine. On TPU the opposite holds — scatter serializes, the masked
        # form streams at HBM rate — so this branch is backend-keyed at
        # trace time (programs are compiled per backend anyway).
        import jax.ops as jops

        if kind == "sum":
            vals = jnp.where(weight, values, jnp.zeros((), dtype=values.dtype))
            return jops.segment_sum(vals, gid, num_segments=num_groups)
        if kind == "count":
            return jops.segment_sum(
                weight.astype(jnp.int64), gid, num_segments=num_groups
            )
        if kind in ("min", "max"):
            ident = _reduce_identity(values.dtype, kind)
            vals = jnp.where(weight, values, ident)
            seg = jops.segment_min if kind == "min" else jops.segment_max
            out = seg(vals, gid, num_segments=num_groups)
            # segment_min/max yield dtype-extreme for EMPTY groups already
            # (identity fill) — matches the masked formulation
            return out
    onehot = gid[None, :] == jnp.arange(num_groups, dtype=gid.dtype)[:, None]
    w = onehot & weight[None, :]
    if kind == "sum":
        vals = jnp.where(w, values[None, :], jnp.zeros((), dtype=values.dtype))
        return jnp.sum(vals, axis=1)
    if kind == "count":
        return jnp.sum(w.astype(jnp.int64), axis=1)
    if kind in ("min", "max"):
        ident = _reduce_identity(values.dtype, kind)
        masked = jnp.where(w, values[None, :], ident)
        return (jnp.min if kind == "min" else jnp.max)(masked, axis=1)
    if kind in _BIT_OPS:
        op, ident = _BIT_OPS[kind]
        masked = jnp.where(w, values[None, :].astype(jnp.int64), jnp.int64(ident))
        return jax.lax.reduce(masked, jnp.int64(ident), op, (1,))
    raise ValueError(kind)


def _reduce_identity(dtype, kind: str):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if kind == "min" else -jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(kind == "min", dtype=jnp.bool_)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if kind == "min" else info.min, dtype=dtype)


def direct_group_first(
    values: jnp.ndarray, weight: jnp.ndarray, gid: jnp.ndarray, num_groups: int
) -> jnp.ndarray:
    """out[g] = value of some participating row of group g (num_groups gathers)."""
    n = values.shape[0]
    onehot = (gid[None, :] == jnp.arange(num_groups, dtype=gid.dtype)[:, None]) & weight[None, :]
    idx = jnp.max(jnp.where(onehot, jnp.arange(n)[None, :], -1), axis=1)
    return values[jnp.clip(idx, 0, n - 1)]


def scatter_first(
    values_sorted: jnp.ndarray,
    new_group_sorted: jnp.ndarray,
    gid_sorted: jnp.ndarray,
    capacity: int,
) -> jnp.ndarray:
    """out[gid] = value at the group's first sorted row (for group keys)."""
    ids = jnp.where(new_group_sorted, gid_sorted, capacity).astype(jnp.int32)
    zero = jnp.zeros((capacity + 1,) + values_sorted.shape[1:], dtype=values_sorted.dtype)
    return zero.at[ids].set(values_sorted, mode="drop")[:capacity]


# --------------------------------------------------------------------------- #
# join
# --------------------------------------------------------------------------- #


def dense_ranks(values: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving map of int64 values to dense ranks in [0, ndv).

    Sort-based renumbering: equal values get equal ranks, distinct values get
    distinct ranks, rank order == value order. The building block that makes
    multi-column key packing exact without range-product overflow."""
    n = values.shape[0]
    idx = jnp.arange(n)
    (sk,), (si,) = cosort([values], [idx])
    new = jnp.zeros(n, dtype=bool).at[0].set(True) | (sk != jnp.roll(sk, 1))
    rank_sorted = cumsum(new.astype(jnp.int64)) - 1
    # invert the permutation with another stable sort — scatter-free (TPU
    # scatters serialize; sorting by the original index restores row order)
    _, (ranks,) = cosort([si], [rank_sorted])
    return ranks


def pack_key_pair(
    probe_cols: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
    build_cols: Sequence[Tuple[jnp.ndarray, jnp.ndarray]],
):
    """Pack multi-column join keys with renumbering shared across BOTH sides
    (per-side renumbering would pack the same key to different codes).

    Exact and overflow-free: columns are dense-ranked over the union of the two
    sides and the partial pack re-densified between columns, bounding packed
    values by (|probe|+|build|)^2 < 2^63 — no hash collisions, so no equality
    confirmation pass is needed (ref: JoinCompiler hashes then CONFIRMS
    equality, operator/join/PagesHash.java; here the pack is collision-free)."""
    p_valid = probe_cols[0][1]
    for _, v in probe_cols[1:]:
        p_valid = p_valid & v
    b_valid = build_cols[0][1]
    for _, v in build_cols[1:]:
        b_valid = b_valid & v
    if len(probe_cols) == 1:
        return order_key(probe_cols[0][0]), p_valid, order_key(build_cols[0][0]), b_valid
    cap_p = probe_cols[0][0].shape[0]
    n = cap_p + build_cols[0][0].shape[0]
    p_packed = b_packed = None
    for (pd, _), (bd, _) in zip(probe_cols, build_cols):
        u = dense_ranks(jnp.concatenate([order_key(pd), order_key(bd)]))
        if p_packed is None:
            p_packed, b_packed = u[:cap_p], u[cap_p:]
        else:
            both = jnp.concatenate([p_packed, b_packed]) * jnp.int64(n) + u
            both = dense_ranks(both)
            p_packed, b_packed = both[:cap_p], both[cap_p:]
    return p_packed, p_valid, b_packed, b_valid


def join_match(
    build_key: jnp.ndarray,
    build_active: jnp.ndarray,
    probe_key: jnp.ndarray,
    probe_active: jnp.ndarray,
):
    """Sorted-build matching: returns (perm_b, lo, hi, count) where sorted build
    rows [lo, hi) match each probe row. (PagesHash/JoinProbe analogue.)

    Inactive build rows are keyed INT64_MAX but sort strictly AFTER active
    rows of the same key (secondary sort on ~active), and ``hi`` is capped at
    the active-row count — so a probe key that genuinely equals INT64_MAX can
    never falsely match the inactive tail (PagesHash confirms equality after
    the hash lookup for the same reason)."""
    key_norm = jnp.where(build_active, build_key, jnp.int64(INT64_MAX))
    perm_b = jnp.lexsort(((~build_active).astype(jnp.int8), key_norm))
    n = probe_key.shape[0]
    m = build_key.shape[0]
    # probe ranks via ONE stable merge sort, not searchsorted: binary search
    # is ~20 dependent gather rounds over the probe (measured 2.5s for 6M
    # probes into 1M build on v5e) while a stable sort of the concatenated
    # keys is HBM-streaming (23ms at 6M). Concat order IS the tie-break:
    # [lo-queries, active builds, hi-queries] — a stable sort keeps equal
    # keys in segment order, so a lo-query ranks before its equal builds
    # (counting keys strictly below) and a hi-query after (counting <=).
    # Inactive builds carry is_build=0 and INT64_MAX keys; a genuine
    # INT64_MAX probe still matches genuine INT64_MAX ACTIVE builds, and
    # its hi-query precedes the inactive tail by segment order.
    merged_key = jnp.concatenate([probe_key, key_norm, probe_key])
    is_build = jnp.concatenate(
        [
            jnp.zeros(n, dtype=jnp.int32),
            build_active.astype(jnp.int32),
            jnp.zeros(n, dtype=jnp.int32),
        ]
    )
    # query id: lo-query i -> i, hi-query i -> n + i, builds -> 2n (dropped)
    qid = jnp.concatenate(
        [
            jnp.arange(n, dtype=jnp.int32),
            jnp.full(m, 2 * n, dtype=jnp.int32),
            jnp.arange(n, 2 * n, dtype=jnp.int32),
        ]
    )
    _, (s_is_build, s_qid) = cosort([merged_key], [is_build, qid])
    builds_before = cumsum(s_is_build) - s_is_build  # exclusive
    ranks = jnp.zeros(2 * n, dtype=jnp.int32).at[s_qid].set(
        builds_before.astype(jnp.int32), mode="drop"
    )
    lo = ranks[:n]
    hi = ranks[n:]
    count = jnp.where(probe_active, jnp.maximum(hi - lo, 0), 0)
    return perm_b, lo, hi, count


def expand_probe_slots(emit: jnp.ndarray, out_capacity: int):
    """Slot-assignment half of rank-space match expansion, shared between the
    sort-based join (expand_matches) and the hash-probe megakernel
    (ops/megakernels.py) — both paths MUST place probe row i's output rows at
    the same slots for the fused/serial bit-identity contract to hold.

    Returns (probe_idx, d, out_active, total):
    - probe_idx[p]: probe row for output slot p (last i with start[i] <= p)
    - d[p]: ordinal of slot p within its probe row's emission
    - out_active[p]: slot p holds a real output row (p < total)
    - total: number of output rows (traced scalar)
    """
    start = cumsum(emit) - emit  # exclusive prefix sum
    total = jnp.sum(emit)
    p = jnp.arange(out_capacity)
    # probe_idx[p] = last i with start[i] <= p, via scatter-max + cummax
    # (searchsorted is ~20 dependent gather rounds; this is one scatter at
    # probe size + one scan at output size). Ties on start (zero-emit rows)
    # resolve to the max i — the searchsorted('right')-1 behavior.
    marks = (
        jnp.zeros(out_capacity, dtype=jnp.int32)
        .at[start]
        .max(jnp.arange(start.shape[0], dtype=jnp.int32), mode="drop")
    )
    probe_idx = jax.lax.cummax(marks)
    probe_idx = jnp.clip(probe_idx, 0, start.shape[0] - 1)
    d = p - start[probe_idx]
    out_active = p < total
    return probe_idx, d, out_active, total


def expand_matches(
    emit: jnp.ndarray,
    match_count: jnp.ndarray,
    lo: jnp.ndarray,
    perm_b: jnp.ndarray,
    out_capacity: int,
):
    """Rank-space expansion of 1:N matches into a static output.

    ``emit[i]``: output slots probe row i produces (0 for inactive rows; for a
    left outer join, 1 for active-but-unmatched rows). ``match_count[i]``: how
    many of those slots are real matches (the rest are null-padded).

    Returns (probe_idx, build_pos, matched, out_active, total):
    - probe_idx[p]: probe row for output slot p
    - build_pos[p]: build row (original index) for output slot p
    - matched[p]: False for null-padded (outer) slots
    - out_active[p]: slot p holds a real output row
    - total: number of output rows (traced scalar)

    Selection invariant: slot p maps to the last probe row i with start[i] <= p;
    zero-emit rows share their successor's start and are never selected within
    [0, total).
    """
    probe_idx, d, out_active, total = expand_probe_slots(emit, out_capacity)
    matched = d < match_count[probe_idx]
    build_sorted_pos = jnp.clip(lo[probe_idx] + d, 0, perm_b.shape[0] - 1)
    build_pos = perm_b[build_sorted_pos]
    return probe_idx, build_pos, matched, out_active, total


def semijoin_mask(
    build_key: jnp.ndarray,
    build_active: jnp.ndarray,
    probe_key: jnp.ndarray,
    probe_active: jnp.ndarray,
) -> jnp.ndarray:
    """matched[i] for each probe row (HashSemiJoinOperator/SetBuilderOperator)."""
    _, lo, hi, count = join_match(build_key, build_active, probe_key, probe_active)
    return count > 0


# --------------------------------------------------------------------------- #
# sort / topn / limit
# --------------------------------------------------------------------------- #


def topn_perm(
    sort_keys: Sequence[jnp.ndarray],  # already encoded (encode_sort_column)
    active: jnp.ndarray,
    count: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full-sort permutation + output active mask (first min(count, n) rows)."""
    perm = lexsort_perm(list(sort_keys), active)
    n_active = jnp.sum(active.astype(jnp.int32))
    cap = active.shape[0]
    idx = jnp.arange(cap)
    limit = n_active if count is None else jnp.minimum(n_active, count)
    out_active = idx < limit
    return perm, out_active


def limit_mask(active: jnp.ndarray, count: int, offset: int = 0) -> jnp.ndarray:
    """Keep active rows with ordinal in [offset, offset+count) (LimitOperator)."""
    ordinal = cumsum(active.astype(jnp.int64)) - 1
    keep = active & (ordinal >= offset)
    if count >= 0:
        keep = keep & (ordinal < offset + count)
    return keep
