"""Hierarchical resource groups: admission control with per-group concurrency
and queue limits, weighted-fair dequeue, and selector-based group resolution.

Reference blueprint: io.trino.execution.resourcegroups.InternalResourceGroup
(hardConcurrencyLimit/maxQueued state machine, canRunMore/internalStartNext),
InternalResourceGroupManager + db/file resource-group configuration managers
(selector rules with user/source regexes and ``${USER}`` templates), and
ResourceGroupId paths. The engine analogue keeps the same observable
semantics — a query QUEUES when any ancestor is at its hard concurrency
limit, is REJECTED when the leaf queue is full, and dequeue picks among
eligible subgroups by scheduling weight then FIFO — behind one manager lock
(the reference uses a single synchronized root for the same reason).
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class QueryQueueFullError(RuntimeError):
    """Leaf (or ancestor) queue limit exceeded — the reference fails the query
    with QUERY_QUEUE_FULL (InternalResourceGroup.run)."""


@dataclass(frozen=True)
class ResourceGroupSpec:
    """Static configuration for one group (file manager's ResourceGroupSpec).

    ``name`` may be a template (``${USER}``/``${SOURCE}``): matching children
    are materialized on demand, one per expansion (dynamic subgroups)."""

    name: str
    hard_concurrency_limit: int = 1
    max_queued: int = 100
    scheduling_weight: int = 1
    # memory share (ref: InternalResourceGroup softMemoryLimit): a group at
    # or over this many pool bytes stops DEQUEUING until usage drops —
    # running queries are never interrupted by it (the low-memory killer
    # handles those). None = unlimited.
    soft_memory_limit_bytes: Optional[int] = None
    sub_groups: Tuple["ResourceGroupSpec", ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "ResourceGroupSpec":
        from .memory import parse_bytes

        soft = d.get("softMemoryLimitBytes", d.get("softMemoryLimit"))
        return ResourceGroupSpec(
            name=d["name"],
            hard_concurrency_limit=int(d.get("hardConcurrencyLimit", 1)),
            max_queued=int(d.get("maxQueued", 100)),
            scheduling_weight=int(d.get("schedulingWeight", 1)),
            soft_memory_limit_bytes=(
                parse_bytes(soft) if soft is not None else None
            ),
            sub_groups=tuple(
                ResourceGroupSpec.from_dict(s) for s in d.get("subGroups", ())
            ),
        )


@dataclass(frozen=True)
class SelectorSpec:
    """Routes (user, source) to a group path (file manager's SelectorSpec)."""

    group: Tuple[str, ...]  # path segments, may contain ${USER}/${SOURCE}
    user_pattern: Optional[str] = None
    source_pattern: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_pattern and not re.fullmatch(self.user_pattern, user):
            return False
        if self.source_pattern and not re.fullmatch(self.source_pattern, source):
            return False
        return True

    def resolve(self, user: str, source: str) -> Tuple[str, ...]:
        return tuple(
            seg.replace("${USER}", user).replace("${SOURCE}", source)
            for seg in self.group
        )


class _Group:
    """Runtime state of one group node (InternalResourceGroup analogue)."""

    def __init__(self, spec: ResourceGroupSpec, name: str, parent: Optional["_Group"]):
        self.spec = spec
        self.name = name
        self.parent = parent
        self.children: Dict[str, _Group] = {}
        self.running = 0
        self.queued: List[_Ticket] = []  # only leaves hold queued tickets
        # pool bytes charged to queries running in this subtree (memory-pool
        # listener feedback via ResourceGroupManager.note_memory)
        self.memory_bytes = 0

    @property
    def path(self) -> str:
        parts = []
        g: Optional[_Group] = self
        while g is not None and g.parent is not None:
            parts.append(g.name)
            g = g.parent
        return ".".join(reversed(parts))

    def descendant_queued(self) -> int:
        n = len(self.queued)
        for c in self.children.values():
            n += c.descendant_queued()
        return n

    def over_memory(self) -> bool:
        limit = self.spec.soft_memory_limit_bytes
        return limit is not None and self.memory_bytes >= limit

    def can_run_more(self) -> bool:
        g: Optional[_Group] = self
        while g is not None:
            if g.running >= g.spec.hard_concurrency_limit:
                return False
            if g.over_memory():
                # over the memory share: stop dequeuing until usage drops
                # (queued queries wait; running ones are untouched)
                return False
            g = g.parent
        return True

    def info(self) -> dict:
        return {
            "id": self.path or "global",
            "hardConcurrencyLimit": self.spec.hard_concurrency_limit,
            "maxQueued": self.spec.max_queued,
            "schedulingWeight": self.spec.scheduling_weight,
            "softMemoryLimitBytes": self.spec.soft_memory_limit_bytes,
            "memoryUsageBytes": self.memory_bytes,
            "running": self.running,
            "queued": len(self.queued),
            "subGroups": [c.info() for c in self.children.values()],
        }


class _Ticket:
    """One admission request; the submitting thread blocks on ``event`` until
    the manager grants a slot (or the query is canceled)."""

    def __init__(self, group: "_Group", user: str, source: str):
        self.group = group
        self.user = user
        self.source = source
        self.enqueue_time = time.monotonic()
        self.event = threading.Event()
        self.admitted = False
        self.canceled = False


class ResourceGroupManager:
    """Selector resolution + the synchronized admission state machine."""

    def __init__(self, root_specs: List[ResourceGroupSpec], selectors: List[SelectorSpec]):
        self._lock = threading.Lock()
        root_spec = ResourceGroupSpec(
            name="", hard_concurrency_limit=1 << 30, max_queued=1 << 30
        )
        self._root = _Group(root_spec, "", None)
        self._static_specs = {s.name: s for s in root_specs}
        self._selectors = selectors
        self._by_path: Dict[str, _Group] = {"": self._root}

    @staticmethod
    def from_config(config: dict) -> "ResourceGroupManager":
        """Build from the file-manager JSON shape:
        {"rootGroups": [...], "selectors": [{"user": ..., "group": "a.b.${USER}"}]}"""
        roots = [ResourceGroupSpec.from_dict(d) for d in config.get("rootGroups", ())]
        sels = [
            SelectorSpec(
                group=tuple(s["group"].split(".")),
                user_pattern=s.get("user"),
                source_pattern=s.get("source"),
            )
            for s in config.get("selectors", ())
        ]
        return ResourceGroupManager(roots, sels)

    @staticmethod
    def default(max_concurrent: int, max_queued: int = 1000) -> "ResourceGroupManager":
        """Single root group — the pre-resource-group admission semaphore."""
        spec = ResourceGroupSpec(
            name="global",
            hard_concurrency_limit=max_concurrent,
            max_queued=max_queued,
        )
        return ResourceGroupManager(
            [spec], [SelectorSpec(group=("global",))]
        )

    # ------------------------------------------------------------ resolution

    def _resolve_group(self, user: str, source: str) -> _Group:
        for sel in self._selectors:
            if sel.matches(user, source):
                path = sel.resolve(user, source)
                return self._materialize(path)
        raise QueryQueueFullError(
            f"no resource group selector matches user={user!r} source={source!r}"
        )

    def _materialize(self, path: Tuple[str, ...]) -> _Group:
        node = self._root
        specs = self._static_specs
        spec_list: Dict[str, ResourceGroupSpec] = specs
        for seg in path:
            spec = spec_list.get(seg)
            if spec is None:
                # template child (${USER} expanded) or undeclared: inherit from
                # a template spec if present, else a permissive leaf
                template = next(
                    (s for n, s in spec_list.items() if "${" in n), None
                )
                spec = template or ResourceGroupSpec(
                    name=seg, hard_concurrency_limit=1 << 30, max_queued=1 << 30
                )
            child = node.children.get(seg)
            if child is None:
                child = _Group(spec, seg, node)
                node.children[seg] = child
                self._by_path[child.path] = child
            node = child
            spec_list = {s.name: s for s in spec.sub_groups}
        return node

    def group_path(self, user: str = "user", source: str = "") -> str:
        """Selector resolution WITHOUT admission — the fleet plane hashes
        the resolved group path for statement ownership
        (``$TRINO_TPU_FLEET_PARTITION_BY=group``)."""
        with self._lock:
            return self._resolve_group(user, source).path

    # ------------------------------------------------------------- admission

    def submit(self, user: str = "user", source: str = "") -> _Ticket:
        """Returns a ticket; caller blocks on ``ticket.event`` until admitted.
        Raises QueryQueueFullError when the target group's queue is full."""
        with self._lock:
            group = self._resolve_group(user, source)
            ticket = _Ticket(group, user, source)
            if group.can_run_more() and not group.queued:
                self._admit(ticket)
            else:
                g: Optional[_Group] = group
                while g is not None and g.parent is not None:
                    if g.descendant_queued() >= g.spec.max_queued:
                        raise QueryQueueFullError(
                            f"Too many queued queries for {g.path!r} "
                            f"(maxQueued {g.spec.max_queued})"
                        )
                    g = g.parent
                group.queued.append(ticket)
            return ticket

    def _admit(self, ticket: _Ticket) -> None:
        g: Optional[_Group] = ticket.group
        while g is not None:
            g.running += 1
            g = g.parent
        ticket.admitted = True
        ticket.event.set()

    def cancel(self, ticket: _Ticket) -> None:
        with self._lock:
            if not ticket.admitted:
                ticket.canceled = True
                try:
                    ticket.group.queued.remove(ticket)
                except ValueError:
                    pass
                ticket.event.set()

    def finish(self, ticket: _Ticket) -> None:
        if not ticket.admitted:
            return
        with self._lock:
            g: Optional[_Group] = ticket.group
            while g is not None:
                g.running -= 1
                g = g.parent
            self._start_next(self._root)

    def _start_next(self, node: _Group) -> bool:
        """Weighted-fair dequeue (InternalResourceGroup.internalStartNext):
        among children with queued descendants and spare capacity, pick the
        least-loaded by running/weight (ties: earliest waiter). Groups at or
        over their soft memory limit are skipped until usage drops."""
        if node.running >= node.spec.hard_concurrency_limit or node.over_memory():
            return False
        if node.queued:
            ticket = node.queued.pop(0)
            self._admit(ticket)
            return True
        eligible = [
            c
            for c in node.children.values()
            if c.descendant_queued() > 0
            and c.running < c.spec.hard_concurrency_limit
            and not c.over_memory()
        ]
        eligible.sort(
            key=lambda c: (
                c.running / max(c.spec.scheduling_weight, 1),
                self._earliest_wait(c),
            )
        )
        for child in eligible:
            if self._start_next(child):
                return True
        return False

    @staticmethod
    def _earliest_wait(node: _Group) -> float:
        t = min((q.enqueue_time for q in node.queued), default=float("inf"))
        for c in node.children.values():
            t = min(t, ResourceGroupManager._earliest_wait(c))
        return t

    # ---------------------------------------------------------------- memory

    def note_memory(self, path: str, delta: int) -> None:
        """Memory-pool listener feedback: charge ``delta`` bytes to the group
        at ``path`` and every ancestor. Groups over their
        ``soft_memory_limit_bytes`` stop dequeuing (can_run_more /
        _start_next); a release below the limit restarts the dequeue so
        memory-parked queues drain without a separate wakeup path."""
        with self._lock:
            g: Optional[_Group] = self._by_path.get(path)
            if g is None:
                return
            while g is not None:
                g.memory_bytes = max(0, g.memory_bytes + int(delta))
                g = g.parent
            if delta < 0:
                while self._start_next(self._root):
                    pass

    # ------------------------------------------------------------------ info

    def info(self) -> dict:
        with self._lock:
            return self._root.info()

    def flat_info(self) -> List[dict]:
        """Every materialized group as one flat row (parent-path included) —
        the system.runtime.resource_groups snapshot source."""

        def walk(node: _Group, out: List[dict]) -> List[dict]:
            row = node.info()
            row.pop("subGroups", None)
            row["parent"] = node.parent.path or None if node.parent else None
            if node.parent is not None and not row["parent"]:
                row["parent"] = "global"
            out.append(row)
            for c in node.children.values():
                walk(c, out)
            return out

        with self._lock:
            return walk(self._root, [])
