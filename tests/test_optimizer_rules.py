"""Optimizer rule tests via the plan-assertion DSL.

Coverage model: the reference's per-rule tests under
sql/planner/iterative/rule/test/ (e.g. TestMergeLimits,
TestRemoveRedundantSort, TestPushLimitThroughProject), each asserting plan
shape with PlanMatchPattern — here with tests/plan_assertions.P. Every rule
also gets an execution parity check where results could regress silently.
"""

import pytest

from tests.plan_assertions import P, assert_no_node, assert_plan, assert_plan_contains
from trino_tpu.planner.plan import (
    AggregationNode,
    EnforceSingleRowNode,
    FilterNode,
    JoinKind,
    JoinNode,
    LimitNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    WindowNode,
)
from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestSimplifyExpressions:
    def test_false_filter_becomes_empty_values(self, runner):
        plan = runner.plan_sql("SELECT n_name FROM nation WHERE 1 = 2")
        assert_plan_contains(plan, P.values(rows=0))
        assert_no_node(plan, TableScanNode)
        assert runner.execute("SELECT n_name FROM nation WHERE 1 = 2").rows == []

    def test_true_conjunct_dropped(self, runner):
        plan = runner.plan_sql(
            "SELECT n_name FROM nation WHERE 1 = 1 AND n_nationkey = 3"
        )
        # 1=1 folds away; the remaining filter reaches the scan
        assert_plan_contains(plan, P.filter(P.scan("nation")))
        rows = runner.execute(
            "SELECT n_name FROM nation WHERE 1 = 1 AND n_nationkey = 3"
        ).rows
        assert rows == [("CANADA",)]

    def test_constant_arithmetic_folds(self, runner):
        # 0.06 - 0.01 must fold so the scan constraint sees a constant range
        plan = runner.plan_sql(
            "SELECT count(*) FROM lineitem WHERE l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01"
        )

        def has_constraint(n):
            return bool(n.constraint.domains)

        assert_plan_contains(
            plan, P.node(TableScanNode, where=has_constraint)
        )


class TestEmptyPropagation:
    def test_inner_join_with_empty_side(self, runner):
        sql = (
            "SELECT n_name FROM nation "
            "JOIN (SELECT r_regionkey FROM region WHERE 1=0) r "
            "ON n_regionkey = r_regionkey"
        )
        plan = runner.plan_sql(sql)
        assert_plan_contains(plan, P.values(rows=0))
        assert_no_node(plan, TableScanNode)
        assert runner.execute(sql).rows == []

    def test_union_drops_empty_branch(self, runner):
        sql = (
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 2 "
            "UNION ALL SELECT n_nationkey FROM nation WHERE false"
        )
        plan = runner.plan_sql(sql)
        # the union collapses to a single branch (projected)
        from trino_tpu.planner.plan import UnionNode

        assert_no_node(plan, UnionNode)
        assert sorted(r[0] for r in runner.execute(sql).rows) == [0, 1]

    def test_grouped_agg_over_empty(self, runner):
        sql = "SELECT n_regionkey, count(*) FROM nation WHERE false GROUP BY n_regionkey"
        assert runner.execute(sql).rows == []
        plan = runner.plan_sql(sql)
        assert_no_node(plan, TableScanNode)

    def test_global_agg_over_empty_still_one_row(self, runner):
        # a global aggregation over no rows yields one row — must NOT prune
        sql = "SELECT count(*) FROM nation WHERE false"
        assert runner.execute(sql).rows == [(0,)]


class TestLimitRules:
    def test_merge_limits(self, runner):
        plan = runner.plan_sql(
            "SELECT * FROM (SELECT n_name FROM nation LIMIT 10) LIMIT 3"
        )
        limits = [n for n in _walk_nodes(plan) if isinstance(n, LimitNode)]
        assert len(limits) == 1 and limits[0].count == 3

    def test_limit_zero_is_empty(self, runner):
        plan = runner.plan_sql("SELECT n_name FROM nation LIMIT 0")
        assert_plan_contains(plan, P.values(rows=0))
        assert runner.execute("SELECT n_name FROM nation LIMIT 0").rows == []

    def test_limit_pushes_through_project(self, runner):
        # LIMIT commutes below the projection so the scan+limit fuse
        plan = runner.plan_sql("SELECT n_nationkey + 1 FROM nation LIMIT 5")
        assert_plan_contains(plan, P.project(P.limit(P.scan("nation"), count=5)))

    def test_limit_through_union(self, runner):
        sql = (
            "SELECT * FROM ("
            "SELECT n_nationkey FROM nation UNION ALL SELECT r_regionkey FROM region"
            ") LIMIT 2"
        )
        plan = runner.plan_sql(sql)
        # each branch now carries its own bound
        assert_plan_contains(plan, P.limit(P.scan("nation"), count=2))
        assert_plan_contains(plan, P.limit(P.scan("region"), count=2))
        assert len(runner.execute(sql).rows) == 2

    def test_limit_over_global_agg_removed(self, runner):
        plan = runner.plan_sql("SELECT count(*) FROM nation LIMIT 5")
        assert_no_node(plan, LimitNode)
        assert runner.execute("SELECT count(*) FROM nation LIMIT 5").rows == [(25,)]


class TestSortRules:
    def test_sort_under_aggregation_removed(self, runner):
        sql = (
            "SELECT count(*) FROM "
            "(SELECT n_name FROM nation ORDER BY n_name)"
        )
        plan = runner.plan_sql(sql)
        assert_no_node(plan, SortNode)
        assert runner.execute(sql).rows == [(25,)]

    def test_order_insensitive_agg_ordering_pruned(self, runner):
        # sum(x ORDER BY y) == sum(x): ordering dropped, sort removed
        sql = "SELECT sum(n_nationkey ORDER BY n_name) FROM nation"
        plan = runner.plan_sql(sql)
        assert_no_node(plan, SortNode)
        assert runner.execute(sql).rows == [(300,)]

    def test_array_agg_ordering_kept(self, runner):
        sql = (
            "SELECT array_agg(n_name ORDER BY n_nationkey DESC) FROM nation "
            "WHERE n_nationkey < 3"
        )
        rows = runner.execute(sql).rows
        assert rows[0][0] == ["BRAZIL", "ARGENTINA", "ALGERIA"]


class TestSingleRowRules:
    def test_scalar_subquery_enforce_removed(self, runner):
        # the subquery is a global aggregation — always one row, so the
        # EnforceSingleRow guard is redundant
        sql = (
            "SELECT n_name FROM nation "
            "WHERE n_nationkey = (SELECT max(r_regionkey) FROM region)"
        )
        plan = runner.plan_sql(sql)
        assert_no_node(plan, EnforceSingleRowNode)
        assert runner.execute(sql).rows == [("CHINA",)]


class TestJoinInference:
    def test_equality_inference_reaches_both_scans(self, runner):
        # n_regionkey = r_regionkey AND r_regionkey = 1: nation's scan must
        # also receive a regionkey constraint
        sql = (
            "SELECT n_name FROM nation JOIN region ON n_regionkey = r_regionkey "
            "WHERE r_regionkey = 1"
        )
        plan = runner.plan_sql(sql)

        def nation_scan_constrained(n):
            return (
                isinstance(n, TableScanNode)
                and n.table.schema_table.table == "nation"
                and bool(n.constraint.domains)
            )

        assert_plan_contains(plan, P.node(TableScanNode, where=nation_scan_constrained))
        rows = runner.execute(sql).rows
        assert {r[0] for r in rows} == {
            "ARGENTINA", "BRAZIL", "CANADA", "PERU", "UNITED STATES",
        }


class TestWindowPushdown:
    def test_partition_key_filter_pushes_below_window(self, runner):
        sql = (
            "SELECT * FROM ("
            "SELECT n_name, n_regionkey, "
            "row_number() OVER (PARTITION BY n_regionkey ORDER BY n_name) rn "
            "FROM nation) WHERE n_regionkey = 2"
        )
        plan = runner.plan_sql(sql)
        assert_plan_contains(plan, P.window(P.filter(P.scan("nation"))))
        rows = runner.execute(sql).rows
        assert len(rows) == 5 and all(r[1] == 2 for r in rows)

    def test_non_partition_filter_stays_above(self, runner):
        sql = (
            "SELECT * FROM ("
            "SELECT n_name, row_number() OVER (ORDER BY n_name) rn "
            "FROM nation) WHERE rn <= 3"
        )
        plan = runner.plan_sql(sql)
        assert_plan_contains(plan, P.filter(P.window(P.scan("nation"))))
        rows = runner.execute(sql).rows
        assert [r[0] for r in rows] == ["ALGERIA", "ARGENTINA", "BRAZIL"]


def _walk_nodes(plan):
    out = []

    def rec(n):
        out.append(n)
        for s in n.sources:
            rec(s)

    rec(plan.root)
    return out


class TestRound3FilterPushdown:
    def test_filter_through_sort(self, runner):
        sql = ("SELECT * FROM (SELECT n_name, n_regionkey FROM nation "
               "ORDER BY n_name) WHERE n_regionkey = 1")
        plan = runner.plan_sql(sql)
        # the filter must sit below the sort (fewer rows to sort)
        assert_plan_contains(
            plan, P.node(SortNode, P.any_tree(P.filter(P.scan("nation"))))
        )
        rows = runner.execute(sql).rows
        assert [r[0] for r in rows] == sorted(r[0] for r in rows)
        assert all(r[1] == 1 for r in rows)

    def test_filter_on_group_keys_through_aggregation(self, runner):
        sql = ("SELECT * FROM (SELECT n_regionkey, count(*) c FROM nation "
               "GROUP BY n_regionkey) WHERE n_regionkey IN (1, 2)")
        plan = runner.plan_sql(sql)
        assert_plan_contains(
            plan,
            P.node(AggregationNode, P.any_tree(P.filter(P.scan("nation")))),
        )
        assert sorted(runner.execute(sql).rows) == [(1, 5), (2, 5)]

    def test_filter_through_union(self, runner):
        sql = ("SELECT * FROM (SELECT n_nationkey k FROM nation "
               "UNION ALL SELECT r_regionkey k FROM region) WHERE k < 2")
        plan = runner.plan_sql(sql)
        # both branches carry the filter below the union
        assert_plan_contains(
            plan,
            P.node(UnionNode,
                   P.any_tree(P.filter(P.scan("nation"))),
                   P.any_tree(P.filter(P.scan("region")))),
        )
        assert sorted(runner.execute(sql).rows) == [(0,), (0,), (1,), (1,)]


class TestRound3LimitRules:
    def test_limit_through_left_join(self, runner):
        sql = ("SELECT o_orderkey FROM orders LEFT JOIN lineitem "
               "ON o_orderkey = l_orderkey LIMIT 7")
        plan = runner.plan_sql(sql)

        def bounded_left(n):
            return isinstance(n.left, LimitNode) or (
                isinstance(n.left, TableScanNode) and n.left.limit is not None
            )

        assert_plan_contains(
            plan, P.node(JoinNode, where=bounded_left)
        )
        assert len(runner.execute(sql).rows) == 7

    def test_limit_into_scan_hint(self, runner):
        plan = runner.plan_sql("SELECT l_orderkey FROM lineitem LIMIT 5")

        def has_hint(n):
            return n.limit is not None and n.limit >= 5

        assert_plan_contains(plan, P.node(TableScanNode, where=has_hint))
        assert len(runner.execute("SELECT l_orderkey FROM lineitem LIMIT 5").rows) == 5

    def test_topn_through_union(self, runner):
        sql = ("SELECT k FROM (SELECT n_nationkey k FROM nation "
               "UNION ALL SELECT r_regionkey k FROM region) "
               "ORDER BY k DESC LIMIT 3")
        plan = runner.plan_sql(sql)
        assert_plan_contains(
            plan,
            P.node(UnionNode,
                   P.any_tree(P.node(TopNNode, P.scan("nation"))),
                   P.any_tree(P.node(TopNNode, P.scan("region")))),
        )
        assert runner.execute(sql).rows == [(24,), (23,), (22,)]


class TestMergeAdjacentWindows:
    def test_two_windows_same_spec_merge(self, runner):
        sql = ("SELECT n_name, rank() OVER (PARTITION BY n_regionkey ORDER BY n_name), "
               "row_number() OVER (PARTITION BY n_regionkey ORDER BY n_name) "
               "FROM nation")
        plan = runner.plan_sql(sql)
        windows = []
        from trino_tpu.planner.plan import visit_plan

        visit_plan(plan.root, lambda n: windows.append(n)
                   if isinstance(n, WindowNode) else None)
        assert len(windows) == 1
        assert len(windows[0].functions) == 2
        rows = runner.execute(sql).rows
        assert len(rows) == 25

    def test_dependent_windows_not_merged(self, runner):
        # the outer window consumes the inner's output — must stay two passes
        sql = ("SELECT * FROM (SELECT n_name, n_regionkey, "
               "sum(n_nationkey) OVER (PARTITION BY n_regionkey) s FROM nation) "
               "WHERE s > 50")
        rows = runner.execute(sql).rows
        assert all(r[2] > 50 for r in rows)


class TestAdviceR3Lows:
    def test_nondeterministic_conjunct_not_mirrored(self, runner):
        # ADVICE r3: k > random() must NOT be mirrored across the equi-join —
        # the copy would draw an independent random stream on the other side
        from trino_tpu.planner.plan import FilterNode, visit_plan

        sql = (
            "SELECT n_name FROM nation JOIN region ON n_regionkey = r_regionkey "
            "WHERE r_regionkey >= random() * 0"
        )
        plan = runner.plan_sql(sql)
        rand_filters = []

        def walk(n):
            if isinstance(n, FilterNode) and "random" in str(n.predicate):
                rand_filters.append(n)

        visit_plan(plan.root, walk)
        assert len(rand_filters) <= 1
        assert len(runner.execute(sql).rows) == 25

    def test_limit_with_offset_not_single_row(self, runner):
        # Limit(count=1, offset=1) over one row yields ZERO rows; the
        # EnforceSingleRow above a scalar subquery must then produce NULL,
        # not be optimized away
        sql = (
            "SELECT count(*) FROM nation WHERE n_nationkey = "
            "(SELECT max(r_regionkey) FROM region LIMIT 1 OFFSET 1)"
        )
        assert runner.execute(sql).rows == [(0,)]

    def test_checksum_empty_input_is_null(self, runner):
        rows = runner.execute(
            "SELECT checksum(n_nationkey) FROM nation WHERE n_nationkey < 0"
        ).rows
        assert rows == [(None,)]
