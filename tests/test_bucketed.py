"""Bucketed connector partitioning: co-located joins skip the shuffle.

ref: spi/connector/ConnectorNodePartitioningProvider.java:22,
TpchNodePartitioningProvider, planner/BucketNodeMap — a table that declares
its splits hash-partitioned on the join keys joins another table with the
SAME rule + bucket count without any REPARTITION exchange; split i is
bucket i on both sides, so co-scheduling aligns them.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.metadata import Session
from trino_tpu.planner.fragmenter import add_exchanges, create_fragments
from trino_tpu.planner.plan import ExchangeNode, ExchangeType, visit_plan
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
from trino_tpu.spi.page import Column, Page
from trino_tpu.spi.types import BIGINT, DOUBLE


def _page(types, arrs):
    n = len(arrs[0])
    return Page(
        tuple(
            Column.from_numpy(t, np.asarray(a), np.ones(n, bool), capacity=n)
            for t, a in zip(types, arrs)
        ),
        jnp.asarray(np.ones(n, bool)),
    )


@pytest.fixture()
def setup():
    r = LocalQueryRunner(Session(catalog="mem", schema="default"))
    mc = MemoryConnector()
    r.register_catalog("mem", mc)
    rng = np.random.default_rng(7)
    facts_k = rng.integers(0, 50, 300)
    facts_v = rng.random(300)
    dims_k = np.arange(50)
    dims_w = rng.random(50)
    fa = SchemaTableName("default", "facts")
    di = SchemaTableName("default", "dims")
    mc.create_table(
        fa, [ColumnMetadata("k", BIGINT), ColumnMetadata("v", DOUBLE)],
        bucketed_by=["k"], bucket_count=4,
    )
    mc.create_table(
        di, [ColumnMetadata("k", BIGINT), ColumnMetadata("w", DOUBLE)],
        bucketed_by=["k"], bucket_count=4,
    )
    mc.insert(fa, _page([BIGINT, DOUBLE], [facts_k, facts_v]))
    mc.insert(di, _page([BIGINT, DOUBLE], [dims_k, dims_w]))
    oracle = pd.DataFrame({"k": facts_k, "v": facts_v}).merge(
        pd.DataFrame({"k": dims_k, "w": dims_w}), on="k"
    )
    return r, mc, oracle


def _repartitions(root):
    out = []
    visit_plan(
        root,
        lambda n: out.append(n)
        if isinstance(n, ExchangeNode)
        and n.exchange_type == ExchangeType.REPARTITION
        else None,
    )
    return out


JOIN_SQL = "SELECT count(*), sum(v * w) FROM facts JOIN dims ON facts.k = dims.k"


class TestPlanShape:
    def test_co_bucketed_join_has_no_repartition(self, setup):
        r, _, _ = setup
        dist = add_exchanges(r.plan_sql(JOIN_SQL), r.metadata, r.session)
        assert _repartitions(dist.root) == []
        # the join fragment contains BOTH scans (one co-scheduled stage)
        sub = create_fragments(dist)
        from trino_tpu.planner.plan import TableScanNode

        per_frag = []
        for f in sub.fragments:
            scans = []
            visit_plan(
                f.root,
                lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
            )
            per_frag.append(len(scans))
        assert 2 in per_frag

    def test_mismatched_bucket_count_keeps_exchange(self, setup):
        r, mc, _ = setup
        other = SchemaTableName("default", "dims8")
        mc.create_table(
            other, [ColumnMetadata("k", BIGINT), ColumnMetadata("w", DOUBLE)],
            bucketed_by=["k"], bucket_count=8,
        )
        mc.insert(other, _page([BIGINT, DOUBLE], [np.arange(50), np.random.rand(50)]))
        sql = "SELECT count(*) FROM facts JOIN dims8 ON facts.k = dims8.k"
        r.session.set("join_distribution_type", "PARTITIONED")
        dist = add_exchanges(r.plan_sql(sql), r.metadata, r.session)
        assert _repartitions(dist.root)

    def test_non_key_join_keeps_exchange(self, setup):
        r, _, _ = setup
        sql = "SELECT count(*) FROM facts JOIN dims ON facts.v = dims.w"
        r.session.set("join_distribution_type", "PARTITIONED")
        dist = add_exchanges(r.plan_sql(sql), r.metadata, r.session)
        assert _repartitions(dist.root)

    def test_co_bucketed_beats_forced_partitioned(self, setup):
        # even under forced PARTITIONED distribution the co-located path wins
        r, _, _ = setup
        r.session.set("join_distribution_type", "PARTITIONED")
        dist = add_exchanges(r.plan_sql(JOIN_SQL), r.metadata, r.session)
        assert _repartitions(dist.root) == []


class TestExecution:
    def test_local_result_matches_oracle(self, setup):
        r, _, oracle = setup
        ((cnt, s),) = r.execute(JOIN_SQL).rows
        assert cnt == len(oracle)
        assert abs(s - (oracle.v * oracle.w).sum()) < 1e-9

    def test_grouped_join_on_buckets(self, setup):
        r, _, oracle = setup
        rows = r.execute(
            "SELECT facts.k, count(*), sum(v) FROM facts JOIN dims ON facts.k = dims.k "
            "GROUP BY 1 ORDER BY 1 LIMIT 5"
        ).rows
        want = (
            oracle.groupby("k")
            .agg(c=("v", "size"), s=("v", "sum"))
            .reset_index()
            .sort_values("k")
            .head(5)
        )
        for (k, c, s), (_, wrow) in zip(rows, want.iterrows()):
            assert k == wrow.k and c == wrow.c and abs(s - wrow.s) < 1e-9

    def test_insert_rebucketing_preserves_layout(self, setup):
        r, mc, oracle = setup
        fa = SchemaTableName("default", "facts")
        # a second insert must land rows in their key buckets, not append
        mc.insert(fa, _page([BIGINT, DOUBLE], [np.array([1, 2]), np.array([0.5, 0.25])]))
        ((cnt, _),) = r.execute(JOIN_SQL).rows
        assert cnt == len(oracle) + 2
        t = mc.table(fa)
        # every stored bucket page holds only rows that hash to its bucket
        from trino_tpu.parallel.runner import host_partition_targets, _page_to_host

        for b, p in enumerate(t.pages):
            if p is None:
                continue
            cols = _page_to_host(p)
            targets = host_partition_targets(cols, [0], t.bucket_count)
            assert (targets == b).all()
