"""Shared page builder for metadata-backed synthetic tables.

``information_schema`` and the ``system`` catalog both materialize tiny
host-built pages from live engine state at scan time (ref: the reference's
InformationSchemaPageSource / SystemPageSourceProvider both funnel through
InMemoryRecordSet). One builder keeps the null/empty-page conventions —
pad-and-mask, 1 inactive row instead of zero-capacity arrays — in one place.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..spi.connector import ColumnMetadata
from ..spi.page import Column, Page
from ..spi.types import BooleanType, DoubleType, IntegralType


def _numeric_column(type_, values: List[object]) -> Column:
    """Numeric/boolean column from python values; None -> masked-out row."""
    valid = np.array([v is not None for v in values], dtype=np.bool_)
    dtype = type_.storage_dtype
    data = np.array(
        [v if v is not None else 0 for v in values], dtype=dtype
    )
    return Column.from_numpy(type_, data, valid, None)


def synthetic_page(
    all_cols: Sequence[ColumnMetadata],
    rows: List[tuple],
    column_indexes: Sequence[int],
) -> Page:
    """Rows of python values -> a Page over the requested column indexes.

    Conventions shared by every synthetic source:
    - ``None`` cell -> invalid (NULL) position, any column type
    - zero rows -> a 1-row page with nothing active (zero-capacity arrays
      break downstream kernels' ``.at[0]`` initializers)
    """
    import jax.numpy as jnp

    if not rows:
        cols = []
        for idx in column_indexes:
            cm = all_cols[idx]
            if isinstance(cm.type, (IntegralType, DoubleType, BooleanType)):
                cols.append(_numeric_column(cm.type, [None]))
            else:
                cols.append(Column.from_strings([""], cm.type))
        return Page(tuple(cols), jnp.zeros(1, dtype=jnp.bool_))
    cols = []
    for idx in column_indexes:
        cm = all_cols[idx]
        values = [r[idx] for r in rows]
        if isinstance(cm.type, (IntegralType, DoubleType, BooleanType)):
            cols.append(_numeric_column(cm.type, values))
        else:
            cols.append(
                Column.from_strings(
                    [None if v is None else str(v) for v in values], cm.type
                )
            )
    return Page(tuple(cols), jnp.ones(len(rows), dtype=jnp.bool_))
