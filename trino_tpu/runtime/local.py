"""LocalQueryRunner — the single-process engine entry point.

Reference blueprint: io.trino.testing.PlanTester (SURVEY.md §4: "a single-process,
no-HTTP mini engine that plans and can locally execute queries") and
LocalQueryRunner in older Trino. This is both the user-facing embedded API and the
fixture every engine test builds on.
"""

from __future__ import annotations

import datetime
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..metadata import CatalogManager, Metadata, Session
from ..sql import parse_statement
from ..sql import tree as t
from ..planner import LogicalPlanner, optimize, format_plan
from ..planner.plan import LogicalPlan
from .executor import PlanExecutor


def _exclusive_times(executor, node, s):
    """(own_wall, own_device, own_host, own_compile) for one executed plan
    node. Exclusive time = inclusive minus children's inclusive;
    device_secs is already exclusive (each child is fenced before its
    parent dispatches); compile subtracts children; host is the remainder.
    Shared by EXPLAIN ANALYZE's per-operator annotations and the
    dominant-cost diagnosis line so the two can never disagree."""
    kids = [
        executor.stats[id(c)] for c in node.sources if id(c) in executor.stats
    ]
    own_wall = max(s.wall_secs - sum(k.wall_secs for k in kids), 0.0)
    own_compile = max(s.compile_secs - sum(k.compile_secs for k in kids), 0.0)
    own_device = s.device_secs
    own_host = max(own_wall - own_device - own_compile, 0.0)
    return own_wall, own_device, own_host, own_compile


@dataclass
class QueryResult:
    column_names: List[str]
    rows: List[tuple]
    # output Types, parallel to column_names (None for utility statements —
    # the protocol layer then reports varchar, matching Trino's SHOW output)
    column_types: Optional[List[object]] = None
    # tracing: the query's trace id (runtime.tracing.TRACER holds the spans)
    trace_id: Optional[str] = None
    # observability plane: QueryStatsCollector.snapshot() of this execution
    # (device/host/compile attribution + spill/exchange/prefetch counters)
    query_stats: Optional[dict] = None

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def to_dicts(self) -> List[dict]:
        return [dict(zip(self.column_names, r)) for r in self.rows]


@dataclass
class ClientContext:
    """Protocol-level client session state (ref: io.trino.Session's
    preparedStatements + transactionId, carried on the wire by the
    X-Trino-Prepared-Statement / X-Trino-Transaction-Id headers,
    client-protocol.md). Prepared statements and the open explicit
    transaction belong to the CLIENT SESSION, not to whichever pool thread
    happens to run the statement — dispatching COMMIT to a different thread
    than START TRANSACTION must still see the same transaction.

    ``updates`` records session-state changes made by the last statement so
    the protocol layer can mirror them to response headers
    (X-Trino-Added-Prepare / X-Trino-Started-Transaction-Id / ...)."""

    prepared: Dict[str, Any] = field(default_factory=dict)
    txn: Optional[Any] = None
    updates: Dict[str, Any] = field(default_factory=dict)


class LocalQueryRunner:
    def __init__(self, session: Optional[Session] = None, access_control=None):
        from ..spi.security import AllowAllAccessControl
        from .transactions import TransactionManager

        self.catalogs = CatalogManager()
        self.metadata = Metadata(self.catalogs)
        self.session = session or Session()
        self.access_control = access_control or AllowAllAccessControl()
        self.transactions = TransactionManager()
        # per-query principal is thread-local: the QueryManager pool runs
        # concurrent queries as different authenticated users. Transaction
        # and prepared-statement state lives in a ClientContext keyed by the
        # protocol session (embedded callers share the runner default).
        import threading

        self._user_tls = threading.local()
        self._ctx_tls = threading.local()

    @property
    def _client(self) -> ClientContext:
        """The active protocol client context, or — for embedded callers that
        pass none — a PER-THREAD default: QueryManager pool threads run
        concurrent queries, and one thread's START TRANSACTION must not
        capture another thread's autocommit writes in its undo log."""
        ctx = getattr(self._ctx_tls, "ctx", None)
        if ctx is not None:
            return ctx
        default = getattr(self._ctx_tls, "default", None)
        if default is None:
            default = ClientContext()
            self._ctx_tls.default = default
        return default

    @property
    def _txn(self):
        return self._client.txn

    @_txn.setter
    def _txn(self, value):
        self._client.txn = value

    @staticmethod
    def tpch(scale: float = 0.01, schema: Optional[str] = None) -> "LocalQueryRunner":
        """Runner with the tpch catalog mounted (the standard test fixture,
        like Trino's TpchQueryRunner). Default schema matches ``scale``."""
        from ..connectors.tpch import TpchConnector

        if schema is None:
            schema = "sf" + f"{scale:g}".replace(".", "_")
        runner = LocalQueryRunner(Session(catalog="tpch", schema=schema))
        runner.register_catalog("tpch", TpchConnector(scale=scale))
        return runner

    def register_catalog(self, name: str, connector) -> None:
        # invalidate only when REPLACING a name in this registry: cached
        # plans may embed the old connector's handles/types. A fresh name
        # (or a brand-new runner mounting its catalogs) cannot alias — plan
        # keys carry this registry's cache_nonce — and wiping on every
        # runner construction would destroy a warm process-wide cache (and
        # truncate the persisted $TRINO_TPU_RESULT_CACHE file) for nothing.
        replacing = self.catalogs.get(name) is not None
        self.catalogs.register(name, connector)
        if replacing:
            from .cachestore import CACHES

            CACHES.on_ddl()

    # ------------------------------------------------------------------ plans

    def plan_sql(self, sql: str) -> LogicalPlan:
        stmt = parse_statement(sql)
        if isinstance(stmt, t.Explain):
            raise ValueError("use explain() for EXPLAIN statements")
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        return optimize(plan, self.metadata, self.session)

    def explain(self, sql: str) -> str:
        stmt = parse_statement(sql)
        if isinstance(stmt, t.Explain):
            stmt = stmt.statement
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        return format_plan(plan)

    # ---------------------------------------------------------------- execute

    def execute(
        self,
        sql: str,
        user: Optional[str] = None,
        client: Optional[ClientContext] = None,
    ) -> QueryResult:
        self._user_tls.user = user or self.session.user
        self._ctx_tls.ctx = client  # None -> runner-default embedded context
        self._client.updates.clear()
        try:
            self.access_control.check_can_execute_query(self._current_user())
            # warm path tier (c): a textually-identical statement under
            # identical session state skips parse/analysis/optimization —
            # the cached optimized plan goes straight to execution (where
            # the result tier may short-circuit the rest)
            from .cachestore import CACHES

            if CACHES.plan_enabled(self.session) and self._txn is None:
                hit = CACHES.plan.lookup(
                    sql, self.session, self.catalogs.cache_nonce
                )
                if hit is not None:
                    return self._execute_query(None, sql, cached=hit)
            stmt = parse_statement(sql)
            if isinstance(stmt, t.QueryStatement):
                return self._execute_query(stmt, sql, plan_sql=sql)
            return self._dispatch(stmt, sql)
        finally:
            self._ctx_tls.ctx = None

    def peek_cached_result(
        self, sql: str, user: Optional[str] = None
    ) -> Optional[QueryResult]:
        """Cache-aware admission probe (runtime/query_manager._serve_cached):
        a PURE result-cache lookup that never executes anything — a plan
        (via the plan tier when warm, a fresh parse/optimize otherwise),
        the fingerprint+versions key, and the result-tier entry, or None on
        any miss. The QueryManager serves a hit BEFORE the resource-group
        queue gate, so a warm hit returns in ~ms while the group is
        saturated (ROADMAP item 5). Access control still runs: a user who
        may not read the tables gets None here and the real denial on the
        queued path."""
        from .cachestore import CACHES, profile_plan, resolve_versions

        if self._txn is not None or not CACHES.result_enabled(self.session):
            return None
        try:
            if not bool(self.session.get("cache_aware_admission")):
                return None
        except KeyError:
            pass
        prev_user = getattr(self._user_tls, "user", None)
        self._user_tls.user = user or self.session.user
        try:
            self.access_control.check_can_execute_query(self._current_user())
            plan = profile = None
            if CACHES.plan_enabled(self.session):
                hit = CACHES.plan.lookup(
                    sql, self.session, self.catalogs.cache_nonce
                )
                if hit is not None:
                    plan, profile = hit
            if plan is None:
                stmt = parse_statement(sql)
                if not isinstance(stmt, t.QueryStatement):
                    return None
                planner = LogicalPlanner(self.metadata, self.session)
                plan = optimize(planner.plan(stmt), self.metadata, self.session)
            self._check_select_access(plan)
            if profile is None:
                profile = profile_plan(plan)
            versions = resolve_versions(self.metadata, profile.tables)
            rkey = CACHES.result.key_for(
                profile, versions, self.session,
                registry=self.catalogs.cache_nonce,
            )
            if rkey is None:
                return None
            # peek, not lookup: the probe must stay PURE — no hit/miss
            # counters, no LRU touch, and above all no shared-tier
            # single-flight claim for a query that may then sit queued (or
            # be rejected) without ever materializing. The session lets the
            # peek read (never claim) the shared warm tier, so a fleet
            # follower serves another coordinator's published result
            hit = CACHES.result.peek(rkey, session=self.session)
            if hit is not None and hit.unversioned:
                ttl = float(self.session.get("result_cache_ttl") or 0)
                if ttl > 0 and time.time() - hit.created > ttl:
                    hit = None  # expired TTL-fallback entry: let the
                    # queued path take the real lookup's expiry bookkeeping
            if hit is None:
                return None
            result = QueryResult(
                list(hit.names), list(hit.rows),
                list(hit.types) if hit.types is not None else None,
            )
            result.query_stats = {"cacheHitTier": "result"}
            return result
        except Exception:  # noqa: BLE001 — probe only; the queued path decides
            return None
        finally:
            self._user_tls.user = prev_user

    def _dispatch(self, stmt: t.Statement, sql: str) -> QueryResult:
        if isinstance(stmt, t.Prepare):
            # session-scoped prepared statements (ref: execution/PrepareTask —
            # which likewise rejects nested prepared-statement control verbs,
            # closing the EXECUTE-of-EXECUTE recursion hole)
            if isinstance(
                stmt.statement, (t.Prepare, t.ExecuteStmt, t.Deallocate)
            ):
                raise ValueError(
                    "PREPARE body cannot be PREPARE/EXECUTE/DEALLOCATE"
                )
            self._client.prepared[stmt.name] = stmt.statement
            self._client.updates["added_prepare"] = (stmt.name, stmt.body_text)
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.Deallocate):
            if self._client.prepared.pop(stmt.name, None) is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            self._client.updates["deallocated_prepare"] = stmt.name
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.ExecuteStmt):
            prepared = self._client.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            n_params = t.count_parameters(prepared)
            if n_params != len(stmt.parameters):
                raise ValueError(
                    f"prepared statement {stmt.name} expects {n_params} "
                    f"parameters, got {len(stmt.parameters)}"
                )
            bound = t.substitute_parameters(prepared, stmt.parameters)
            return self._dispatch(bound, sql)
        if isinstance(stmt, t.DescribeInput):
            prepared = self._client.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            n_params = t.count_parameters(prepared)
            # parameter types are inferred at EXECUTE time; report unknown
            # like the reference does for untyped positions
            return QueryResult(
                ["Position", "Type"],
                [(i, "unknown") for i in range(n_params)],
            )
        if isinstance(stmt, t.DescribeOutput):
            prepared = self._client.prepared.get(stmt.name)
            if prepared is None:
                raise ValueError(f"prepared statement not found: {stmt.name}")
            if not isinstance(prepared, t.QueryStatement):
                return QueryResult(["Column Name", "Type"], [])
            nulls = tuple(
                t.NullLiteral() for _ in range(t.count_parameters(prepared))
            )
            bound = t.substitute_parameters(prepared, nulls)
            planner = LogicalPlanner(self.metadata, self.session)
            plan = planner.plan(bound)
            plan = optimize(plan, self.metadata, self.session)
            out = plan.root
            names = getattr(out, "column_names", None) or out.output_symbols
            syms = getattr(out, "symbols", None) or out.output_symbols
            return QueryResult(
                ["Column Name", "Type"],
                [
                    (name, plan.types[s].display())
                    for name, s in zip(names, syms)
                ],
            )
        if isinstance(stmt, t.StartTransaction):
            from .transactions import TransactionError

            if self._txn is not None:
                raise TransactionError("a transaction is already in progress")
            self._txn = self.transactions.begin(
                read_only=stmt.read_only, isolation=stmt.isolation
            )
            self._client.updates["started_txn"] = self._txn.txn_id
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.Commit):
            from .transactions import TransactionError

            if self._txn is None:
                raise TransactionError("no transaction in progress")
            try:
                self.transactions.commit(self._txn)
            finally:
                # a failed commit (e.g. idle-expired txn) must not wedge the
                # session in transaction mode forever
                self._txn = None
                self._client.updates["clear_txn"] = True
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.Rollback):
            from .transactions import TransactionError

            if self._txn is None:
                raise TransactionError("no transaction in progress")
            try:
                self.transactions.rollback(self._txn)
            finally:
                self._txn = None
                self._client.updates["clear_txn"] = True
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.Explain):
            inner = stmt.statement
            if stmt.analyze:
                text = self._explain_analyze(inner, verbose=stmt.verbose)
            elif stmt.explain_type == "DISTRIBUTED":
                text = self._explain_distributed(inner)
            else:
                text = self.explain_statement(inner)
            return QueryResult(["Query Plan"], [(line,) for line in text.split("\n")])
        if isinstance(stmt, t.CreateCatalog):
            # dynamic catalogs (ref: the reference's CREATE CATALOG task over
            # CatalogStore + ConnectorFactory resolution; StaticCatalogManager
            # becomes registrable at runtime here)
            from .catalog_factories import create_connector

            self._check_catalog_ddl(stmt.name, "create")
            if self.catalogs.get(stmt.name) is not None:
                if stmt.if_not_exists:
                    return QueryResult(["result"], [(True,)])
                raise ValueError(f"catalog already exists: {stmt.name}")
            connector = create_connector(stmt.connector, dict(stmt.properties))
            self.register_catalog(stmt.name, connector)
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.DropCatalog):
            self._check_catalog_ddl(stmt.name, "drop")
            if self.catalogs.get(stmt.name) is None:
                if stmt.if_exists:
                    return QueryResult(["result"], [(True,)])
                raise ValueError(f"catalog not found: {stmt.name}")
            self.catalogs.deregister(stmt.name)
            from .cachestore import CACHES

            CACHES.on_ddl()
            if self.session.catalog == stmt.name:
                # clear the PAIR: a stale schema against no catalog would
                # half-resolve later unqualified names
                self.session.catalog = None
                self.session.schema = None
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.Use):
            if stmt.catalog is not None:
                if self.metadata.connector_by_name(stmt.catalog) is None:
                    raise ValueError(f"catalog not found: {stmt.catalog}")
                self.session.catalog = stmt.catalog
                self._client.updates["set_catalog"] = stmt.catalog
            self.session.schema = stmt.schema
            self._client.updates["set_schema"] = stmt.schema
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.ShowFunctions):
            from ..sql.functions import AGGREGATE_FUNCTIONS, SCALAR_FUNCTIONS

            rows = []
            for name in sorted(SCALAR_FUNCTIONS):
                if not name.startswith("$"):
                    rows.append((name, "scalar"))
            for name in sorted(AGGREGATE_FUNCTIONS):
                rows.append((name, "aggregate"))
            for r in self.metadata.functions.list():
                rows.append((r.name, "sql routine"))
            return QueryResult(["Function", "Kind"], sorted(rows))
        if isinstance(stmt, t.ShowTables):
            return self._show_tables(stmt)
        if isinstance(stmt, t.ShowSchemas):
            return self._show_schemas(stmt)
        if isinstance(stmt, t.ShowCatalogs):
            # metadata listings go through the access control filter hooks
            # (SystemAccessControl.filterCatalogs)
            names = self.access_control.filter_catalogs(
                self._current_user(), self.catalogs.names()
            )
            return QueryResult(["Catalog"], [(c,) for c in names])
        if isinstance(stmt, t.ShowColumns):
            return self._show_columns(stmt)
        if isinstance(stmt, t.ShowSession):
            rows = [
                (name, str(self.session.get(name)), str(default))
                for name, default in sorted(Session.DEFAULTS.items())
            ]
            return QueryResult(["Name", "Value", "Default"], rows)
        if isinstance(stmt, t.SetSession):
            name = str(stmt.name)
            from ..planner.logical_planner import ExpressionTranslator, Scope

            planner = LogicalPlanner(self.metadata, self.session)
            translator = ExpressionTranslator(planner, Scope([], None))
            const = translator.translate(stmt.value)
            value = getattr(const, "value", None)
            self.session.set(name, value)
            self._client.updates["set_session"] = (name, str(value))
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.ResetSession):
            # back to the default (execution/ResetSessionTask analogue)
            name = str(stmt.name)
            if name not in Session.DEFAULTS:
                raise ValueError(f"unknown session property: {name}")
            self.session.properties.pop(name, None)
            self._client.updates["clear_session"] = name
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.CreateView):
            from ..metadata import ViewDefinition

            catalog, schema, vname = self.metadata.resolve_name(
                self.session, stmt.name
            )
            self.access_control.check_can_create_view(
                self._current_user(), catalog, schema, vname
            )
            # validate the body NOW (ref: CreateViewTask analyzes the query
            # before storing) — a view that can't plan should fail at CREATE
            planner = LogicalPlanner(self.metadata, self.session)
            planner.plan(t.QueryStatement(query=stmt.query))
            self.metadata.views.create(
                catalog, schema, vname,
                ViewDefinition(
                    sql=stmt.query_text,
                    catalog=self.session.catalog,
                    schema=self.session.schema,
                    owner=self._current_user(),
                ),
                replace=stmt.replace,
            )
            from .cachestore import CACHES

            CACHES.on_ddl()  # cached plans may inline a replaced view body
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, (t.Grant, t.Revoke)):
            catalog, st = self._resolve_name(stmt.table)
            privs = tuple(stmt.privileges) or (
                "SELECT", "INSERT", "DELETE", "UPDATE",
            )
            op = (
                self.access_control.grant
                if isinstance(stmt, t.Grant)
                else self.access_control.revoke
            )
            op(self._current_user(), privs, catalog, st.schema, st.table,
               stmt.grantee)
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.CreateFunction):
            from ..metadata import SqlRoutine
            from ..spi.types import parse_type

            fname = stmt.name.parts[-1]
            params = tuple(
                (p, parse_type(ttext)) for p, ttext in stmt.parameters
            )
            routine = SqlRoutine(
                name=fname,
                parameters=params,
                return_type=parse_type(stmt.return_type),
                body=stmt.body,
                body_text=stmt.body_text,
                owner=self._current_user(),
            )
            # validate NOW (CreateFunctionTask analyzes before storing): plan
            # a probe expression over the declared parameter types
            probe = self.metadata.functions.get(fname, len(params))
            self.metadata.functions.create(routine, replace=stmt.replace)
            try:
                planner = LogicalPlanner(self.metadata, self.session)
                args = ", ".join(
                    f"CAST(NULL AS {ttext})" for _, ttext in stmt.parameters
                )
                planner.plan(parse_statement(f"SELECT {fname}({args})"))
            except Exception:
                # roll back the registration on a body that cannot plan
                self.metadata.functions.drop(fname)
                if probe is not None:
                    self.metadata.functions.create(probe, replace=True)
                raise
            from .cachestore import CACHES

            CACHES.on_ddl()  # cached plans inline routine bodies
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.DropFunction):
            dropped = self.metadata.functions.drop(stmt.name.parts[-1])
            if not dropped and not stmt.if_exists:
                raise ValueError(f"function not found: {stmt.name.parts[-1]}")
            if dropped:
                from .cachestore import CACHES

                CACHES.on_ddl()
            return QueryResult(["result"], [(dropped,)])
        if isinstance(stmt, t.DropView):
            catalog, schema, vname = self.metadata.resolve_name(
                self.session, stmt.name
            )
            self.access_control.check_can_drop_view(
                self._current_user(), catalog, schema, vname
            )
            if not self.metadata.views.drop(catalog, schema, vname):
                if stmt.if_exists:
                    return QueryResult(["result"], [(True,)])
                raise ValueError(
                    f"view not found: {catalog}.{schema}.{vname}"
                )
            from .cachestore import CACHES

            CACHES.on_ddl()
            return QueryResult(["result"], [(True,)])
        if isinstance(stmt, t.ShowCreate):
            catalog, schema, oname = self.metadata.resolve_name(
                self.session, stmt.name
            )
            if stmt.kind == "view":
                view = self.metadata.views.get(catalog, schema, oname)
                if view is None:
                    raise ValueError(
                        f"view not found: {catalog}.{schema}.{oname}"
                    )
                text = (
                    f"CREATE VIEW {catalog}.{schema}.{oname} AS\n{view.sql}"
                )
                return QueryResult(["Create View"], [(text,)])
            handle, meta = self.metadata.resolve_table(self.session, stmt.name)
            col_lines = ",\n".join(
                f"   {c.name} {c.type.display()}" for c in meta.columns
            )
            text = (
                f"CREATE TABLE {catalog}.{schema}.{oname} (\n{col_lines}\n)"
            )
            return QueryResult(["Create Table"], [(text,)])
        if isinstance(stmt, (t.CreateTable, t.CreateTableAsSelect, t.InsertInto, t.DropTable)):
            self._pre_mutation(stmt)
            return self._execute_dml(stmt)
        if isinstance(stmt, t.Call):
            # procedure dispatch (execution/CallTask): arguments must fold to
            # constants, like the reference's bound-expression evaluation
            from ..connectors.system import call_procedure
            from ..planner.logical_planner import ExpressionTranslator, Scope

            parts = self.metadata.resolve_name(self.session, stmt.name)
            planner = LogicalPlanner(self.metadata, self.session)
            translator = ExpressionTranslator(planner, Scope([], None))
            args = []
            for expr in stmt.arguments:
                const = translator.translate(expr)
                if not hasattr(const, "value"):
                    raise ValueError(
                        "CALL arguments must be constant expressions"
                    )
                args.append(const.value)
            names, rows = call_procedure(self, parts, args)
            return QueryResult(names, rows)
        if isinstance(stmt, (t.Delete, t.Update, t.Merge)):
            from .dml import execute_delete, execute_merge, execute_update

            self._pre_mutation(stmt)
            if isinstance(stmt, t.Delete):
                n = execute_delete(self, stmt)
            elif isinstance(stmt, t.Update):
                n = execute_update(self, stmt)
            else:
                n = execute_merge(self, stmt)
            from .cachestore import CACHES

            target = stmt.target if isinstance(stmt, t.Merge) else stmt.table
            catalog, st = self._resolve_name(target)
            CACHES.invalidate_table(catalog, st.schema, st.table)
            return QueryResult(["rows"], [(n,)])
        if not isinstance(stmt, t.QueryStatement):
            raise ValueError(f"unsupported statement: {type(stmt).__name__}")
        # EXECUTE'd prepared statements land here carrying the EXECUTE text —
        # never plan-cache under it (parameters vary call to call); the
        # result tier still applies (bound literals ride the fingerprint)
        return self._execute_query(stmt, sql)

    def _execute_query(
        self, stmt: Optional[t.Statement], sql: str,
        cached=None, plan_sql: Optional[str] = None,
    ) -> QueryResult:
        """The SELECT path, warm-path caches wired through it
        (runtime/cachestore.py): ``cached`` is a plan-cache hit
        ``(plan, PlanProfile)`` — parse/analysis/optimization are skipped;
        ``plan_sql`` set means ``stmt`` is the direct parse of that text and
        the optimized plan may be plan-cached under it. The result tier then
        short-circuits execution entirely on a fingerprint+versions hit."""
        from . import observability as obs
        from .cachestore import (
            CACHES,
            ResultEntry,
            encode_result_rows,
            profile_plan,
            resolve_versions,
        )
        from .tracing import TRACER

        def run_once(_sql_unused=None):
            # observability plane: a per-query collector is active for the
            # whole statement — spill/exchange/compile hooks report to it.
            # sync mode (query_stats_sync) fences every operator for exact
            # device/host/compile attribution; async (default) keeps today's
            # dispatch behavior and reports query-level deltas + counters.
            try:
                sync = bool(self.session.get("query_stats_sync"))
            except KeyError:
                sync = False
            # statement-scoped recording (refcounted): one client's property
            # must not leave the process-wide recorder on forever, and a
            # finishing query must not truncate a concurrent one's recording
            recorder_held = False
            try:
                if self.session.get("flight_recorder"):
                    obs.RECORDER.acquire()
                    recorder_held = True
            except KeyError:
                pass
            # host-path plane (runtime/hostprof.py): same refcounted scope —
            # the sampler runs while any host_profile statement executes
            profiler_held = False
            try:
                if self.session.get("host_profile"):
                    from .hostprof import PROFILER

                    PROFILER.acquire()
                    profiler_held = True
            except KeyError:
                pass
            collector = obs.QueryStatsCollector()
            collector.sync_mode = sync
            # span structure mirrors the reference's planning spans
            # (TracingMetadata: "planner"/"optimizer"/per-stage execution)
            cache_tier = None
            rkey = versions = None
            try:
                with obs.collecting(collector), obs.compile_window(), TRACER.span(
                    "query", sql=sql[:200]
                ) as root:
                    if cached is not None:
                        # plan tier hit: parse/analysis/optimization skipped
                        plan, profile = cached
                        cache_tier = "plan"
                    else:
                        profile = None

                        def _plan_once():
                            with TRACER.span("planner"):
                                planner = LogicalPlanner(
                                    self.metadata, self.session
                                )
                                p = planner.plan(stmt)
                            with TRACER.span("optimizer"):
                                return optimize(
                                    p, self.metadata, self.session
                                )

                        # plan flights only for directly-parsed statements:
                        # EXECUTE text must never key a shared plan — the
                        # same name can be re-PREPAREd with a different
                        # body (the plan cache refuses these for the same
                        # reason: plan_sql is None here)
                        if plan_sql is not None:
                            plan = self._maybe_plan_flight(sql, _plan_once)
                        else:
                            plan = _plan_once()
                    self._check_select_access(plan)
                    # result tier: fingerprint + versions resolved at ONE
                    # point pre-execution (see the mixed-snapshot guard at
                    # the store below); bypass inside explicit transactions
                    rkey = versions = None
                    if CACHES.result_enabled(self.session) and self._txn is None:
                        if profile is None:
                            profile = profile_plan(plan)
                        versions = resolve_versions(self.metadata, profile.tables)
                        rkey = CACHES.result.key_for(
                            profile, versions, self.session,
                            registry=self.catalogs.cache_nonce,
                        )
                    if rkey is not None:
                        hit = CACHES.result.lookup(rkey, self.session)
                        if hit is not None:
                            result = QueryResult(
                                list(hit.names), list(hit.rows),
                                list(hit.types) if hit.types is not None
                                else None,
                            )
                            result.trace_id = root.trace_id
                            root.attributes["rows"] = len(result.rows)
                            root.attributes["cache"] = "result"
                            snap = collector.snapshot()
                            snap["cacheHitTier"] = "result"
                            snap["cacheProvenance"] = (
                                f"result cache HIT @ {hit.provenance}"
                            )
                            result.query_stats = snap
                            return result
                    if (
                        plan_sql is not None
                        and cached is None
                        and self._txn is None
                        and CACHES.plan_enabled(self.session)
                    ):
                        if profile is None:
                            profile = profile_plan(plan)
                        CACHES.plan.store(
                            plan_sql, self.session, plan, profile,
                            registry=self.catalogs.cache_nonce,
                        )
                    with TRACER.span("execution"), obs.RECORDER.span(
                        "execution", "query", sql=sql[:200]
                    ):
                        import time as _time

                        import jax as _jax

                        t0 = _time.perf_counter()
                        executor = PlanExecutor(
                            plan, self.metadata, self.session, collect_stats=sync
                        )
                        if (
                            CACHES.fragment_enabled(self.session)
                            and self._txn is None
                        ):
                            from .cachestore import FragmentBinding
                            from .statstore import current_query_id

                            executor.fragment_cache = FragmentBinding(
                                CACHES.fragment, self.metadata, self.session,
                                query_id=current_query_id()
                                or root.trace_id or "",
                                registry=self.catalogs.cache_nonce,
                            )
                        # device batching plane: route batchable subtrees
                        # through the scheduler (off by default — attach()
                        # is a no-op leaving the path byte-identical)
                        from .device_scheduler import attach as _attach_batching

                        _attach_batching(
                            executor, self.metadata, self.session,
                            catalogs=self.catalogs,
                        )
                        # cardinality actuals ride every execution (one async
                        # row-count scalar per operator; host reads deferred
                        # past the drain)
                        try:
                            executor.collect_actuals = bool(
                                self.session.get("statistics_feedback")
                            )
                        except KeyError:
                            executor.collect_actuals = True
                        names, page = executor.execute()
                        dispatch_secs = _time.perf_counter() - t0
                        # drain = waiting on in-flight device work only; row
                        # conversion below is pure-Python host time and must
                        # NOT be booked as device time
                        _jax.block_until_ready(page.active)
                        drain_secs = (
                            _time.perf_counter() - t0 - dispatch_secs
                        )
                        result = QueryResult(
                            names, page.to_pylist(),
                            [c.type for c in page.columns],
                        )
                    result.trace_id = root.trace_id
                    root.attributes["rows"] = len(result.rows)
                    if executor.fragment_cache_hits and cache_tier is None:
                        cache_tier = "fragment"
                    # result tier store, gated on the mixed-snapshot guard:
                    # versions re-resolved AFTER the drain must equal the
                    # pre-execution snapshot — a DML that committed mid-run
                    # (concurrent INSERT) would otherwise record a row set
                    # that is half old snapshot, half new. The raced run
                    # still RETURNS its rows; it just never caches them.
                    if rkey is not None:
                        v_after = resolve_versions(self.metadata, profile.tables)
                        if v_after != versions:
                            # the raced run never publishes: free a claimed
                            # shared-tier flight so peers stop waiting on it
                            CACHES.result.release_flight(rkey, self.session)
                        if v_after == versions:
                            from .statstore import current_query_id

                            nbytes, rows_enc = encode_result_rows(result.rows)
                            entry = ResultEntry(
                                names=list(result.column_names),
                                types=result.column_types,
                                rows=list(result.rows),
                                nbytes=nbytes,
                                rows_encoded=rows_enc,
                                created=_time.time(),
                                tables=profile.tables,
                                versions=versions,
                                query_id=current_query_id()
                                or root.trace_id or "",
                                unversioned=any(v is None for v in versions),
                            )
                            CACHES.result.store(rkey, entry, self.session)
                    # statistics feedback plane: fold per-node actuals into
                    # the collector, flag mis-estimates, feed the history
                    # store (runtime/statstore.py). Post-drain, off the hot
                    # path; a feedback failure must never fail the query.
                    if executor.collect_actuals:
                        try:
                            from . import statstore

                            statstore.observe_query(
                                plan, self.metadata, self.session, collector,
                                executor.finalize_actuals(),
                                query_id=self._feedback_query_id(root),
                            )
                        except Exception:  # noqa: BLE001 — observability only
                            pass
            except BaseException:
                if rkey is not None:
                    # a shared-tier single-flight lease claimed at lookup
                    # time must not outlive a failed/canceled run — free it
                    # now instead of stalling the fleet until the TTL lapses
                    # (end_flight no-ops when this process holds nothing)
                    CACHES.result.release_flight(rkey, self.session)
                raise
            finally:
                if recorder_held:
                    obs.RECORDER.release()
                if profiler_held:
                    from .hostprof import PROFILER

                    PROFILER.release()
            if sync:
                # wall/compile are inclusive of children — convert to
                # EXCLUSIVE before aggregating, or nested operators would
                # double-count (device_secs is already exclusive: each
                # child is fenced before its parent dispatches)
                for s in executor.stats.values():
                    kids = [
                        executor.stats[id(c)]
                        for c in s.node.sources
                        if id(c) in executor.stats
                    ]
                    wall = max(
                        s.wall_secs - sum(k.wall_secs for k in kids), 0.0
                    )
                    comp = max(
                        s.compile_secs - sum(k.compile_secs for k in kids), 0.0
                    )
                    collector.add_operator(
                        type(s.node).__name__,
                        device_secs=s.device_secs,
                        host_secs=max(wall - s.device_secs - comp, 0.0),
                        compile_secs=comp,
                        rows=s.output_rows,
                    )
                collector.add_time(
                    "device_busy_secs",
                    sum(s.device_secs for s in executor.stats.values()),
                )
            else:
                # async attribution: the drain observed by the result fetch
                # is a device-time floor; dispatch covers host + overlapped
                # device work (exact splits need query_stats_sync)
                collector.add_time("device_busy_secs", drain_secs)
                collector.add_time("dispatch_secs", max(dispatch_secs, 0.0))
            snap = collector.snapshot()
            snap["cacheHitTier"] = cache_tier
            if executor.cache_provenance:
                snap["cacheProvenance"] = sorted(
                    set(executor.cache_provenance.values())
                )
            result.query_stats = snap
            return result

        from .failure import execute_with_retry

        return execute_with_retry(
            run_once, sql, retry_policy=str(self.session.get("retry_policy"))
        )

    def _maybe_plan_flight(self, sql: str, compute):
        """Device batching plane: concurrent identical statements share ONE
        parse/plan/optimize pass (single-flight with the continuous-batching
        linger, runtime/device_scheduler.py) — the wave-of-N planning herd
        that otherwise serializes on the host. Gated exactly like the plan
        cache tier: nondeterministic statement text, history_based_stats
        (replanning is the point there), and open transactions bypass; the
        key carries user/catalog/schema/set-props and the catalog registry
        nonce, so a plan can never cross resolution contexts."""
        try:
            enabled = bool(self.session.get("device_batching"))
        except KeyError:
            enabled = False
        if not enabled or self._txn is not None:
            return compute()
        from .cachestore import session_props_key, sql_mentions_nondeterminism

        if sql_mentions_nondeterminism(sql):
            return compute()
        if bool(self.session.get("history_based_stats")):
            return compute()
        from .device_scheduler import SCHEDULER

        key = (
            "plan", sql, self.session.user,
            getattr(self.catalogs, "cache_nonce", ""),
            session_props_key(self.session),
        )
        return SCHEDULER.plan_flight(key, compute)

    @staticmethod
    def _feedback_query_id(root) -> str:
        """Operator-stats attribution id: the QueryManager's query id when
        one is installed on this thread, else the trace id."""
        from .statstore import current_query_id

        return current_query_id() or root.trace_id or ""

    def _check_catalog_ddl(self, catalog: str, op: str) -> None:
        """Catalog DDL authz (SystemAccessControl checkCanCreateCatalog /
        checkCanDropCatalog): honored when the installed access control
        implements the hooks; the built-in rule-based impl may not."""
        hook = getattr(self.access_control, f"check_can_{op}_catalog", None)
        if hook is not None:
            hook(self._current_user(), catalog)

    def _current_user(self) -> str:
        return getattr(self._user_tls, "user", None) or self.session.user

    def _resolve_name(self, qname):
        """Qualified-name -> (catalog, SchemaTableName) with session defaults
        (the write-target variant of Metadata.resolve_table — the target may
        not exist yet, so this can't go through table resolution)."""
        from ..spi.connector import SchemaTableName

        parts = qname.parts
        if len(parts) == 3:
            return parts[0], SchemaTableName(parts[1], parts[2])
        if self.session.catalog is None:
            raise ValueError(f"no default catalog set for table {qname}")
        if len(parts) == 2:
            return self.session.catalog, SchemaTableName(parts[0], parts[1])
        return self.session.catalog, SchemaTableName(
            self.session.schema or "default", parts[0]
        )

    def _pre_mutation(self, stmt: t.Statement) -> None:
        """Access-control checks + transaction pre-image capture before any
        write statement runs (ref: the checkCanXxx calls in the statement
        tasks, e.g. CreateTableTask/DeleteTask; TransactionManager undo)."""
        ac = self.access_control
        user = self._current_user()
        if isinstance(stmt, (t.CreateTable, t.CreateTableAsSelect)):
            catalog, st = self._resolve_name(stmt.name)
            ac.check_can_create_table(user, catalog, st.schema, st.table)
        elif isinstance(stmt, t.DropTable):
            catalog, st = self._resolve_name(stmt.name)
            ac.check_can_drop_table(user, catalog, st.schema, st.table)
        elif isinstance(stmt, t.InsertInto):
            catalog, st = self._resolve_name(stmt.table)
            ac.check_can_insert(user, catalog, st.schema, st.table)
        elif isinstance(stmt, t.Delete):
            catalog, st = self._resolve_name(stmt.table)
            ac.check_can_delete(user, catalog, st.schema, st.table)
        elif isinstance(stmt, t.Update):
            catalog, st = self._resolve_name(stmt.table)
            ac.check_can_update(user, catalog, st.schema, st.table)
        elif isinstance(stmt, t.Merge):
            catalog, st = self._resolve_name(stmt.target)
            for case in stmt.cases:
                if not case.matched:
                    ac.check_can_insert(user, catalog, st.schema, st.table)
                elif case.operation == "delete":
                    ac.check_can_delete(user, catalog, st.schema, st.table)
                else:
                    ac.check_can_update(user, catalog, st.schema, st.table)
        else:
            return
        if self._txn is not None:
            from .transactions import TransactionError, TxnState

            if self._txn.state is not TxnState.ACTIVE:
                # idle-expired (already rolled back by the manager): leave
                # transaction mode so the session can recover
                self._txn = None
                raise TransactionError(
                    "transaction was idle-expired and rolled back"
                )
            connector = self.catalogs.get(catalog)
            if connector is not None and hasattr(connector, "table"):
                self.transactions.record_pre_image(self._txn, catalog, connector, st)

    def _check_select_access(self, plan) -> None:
        """check_can_select on every scanned table (AccessControl.checkCanSelect
        at analysis time in the reference; post-optimize here so pruned scans
        are not re-checked)."""
        from ..planner.plan import TableScanNode

        user = self._current_user()

        def walk(node):
            if isinstance(node, TableScanNode):
                h = node.table
                self.access_control.check_can_select(
                    user,
                    h.catalog,
                    h.schema_table.schema,
                    h.schema_table.table,
                    [c for _, c in node.assignments],
                )
            for s in node.sources:
                walk(s)

        root = getattr(plan, "root", plan)
        walk(root)

    def _execute_dml(self, stmt: t.Statement) -> QueryResult:
        """DDL/DML statements (ref: execution/CreateTableTask.java et al. — the
        ~70 DataDefinitionTask classes; round 1 covers CTAS/INSERT/DROP against
        writable connectors like memory/blackhole)."""
        from ..spi.connector import ColumnMetadata, SchemaTableName
        from ..planner.plan import OutputNode
        from .executor import PlanExecutor

        resolve = self._resolve_name

        def writable(catalog, op, attr):
            connector = self.catalogs.get(catalog)
            if connector is None:
                raise ValueError(f"catalog not found: {catalog}")
            if not hasattr(connector, attr):
                raise ValueError(f"catalog {catalog} does not support {op}")
            return connector

        from .cachestore import CACHES

        if isinstance(stmt, t.DropTable):
            catalog, st = resolve(stmt.name)
            connector = writable(catalog, "DROP TABLE", "drop_table")
            connector.drop_table(st, if_exists=stmt.if_exists)
            CACHES.on_ddl()
            return QueryResult(["result"], [(True,)])

        if isinstance(stmt, t.CreateTable):
            from ..spi.types import parse_type

            catalog, st = resolve(stmt.name)
            connector = writable(catalog, "CREATE TABLE", "create_table")
            if connector.metadata().get_table_metadata(st) is not None:
                if stmt.if_not_exists:
                    return QueryResult(["result"], [(True,)])
                raise ValueError(f"table already exists: {st}")
            columns = [
                ColumnMetadata(cname, parse_type(ttext))
                for cname, ttext in stmt.columns
            ]
            connector.create_table(st, columns)
            CACHES.on_ddl()
            return QueryResult(["result"], [(True,)])

        # target checks happen BEFORE executing the source query (Trino's
        # CreateTableTask order — don't burn the query on a doomed/no-op DML)
        if isinstance(stmt, t.CreateTableAsSelect):
            catalog, st = resolve(stmt.name)
            connector = writable(catalog, "CREATE TABLE", "create_table")
            if connector.metadata().get_table_metadata(st) is not None:
                if stmt.if_not_exists:
                    return QueryResult(["rows"], [(0,)])
                raise ValueError(f"table already exists: {st}")
        else:
            catalog, st = resolve(stmt.table)
            connector = writable(catalog, "INSERT", "insert")
            if connector.metadata().get_table_metadata(st) is None:
                raise ValueError(f"table not found: {st}")

        query = stmt.query
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(t.QueryStatement(query=query))
        plan = optimize(plan, self.metadata, self.session)
        self._check_select_access(plan)
        executor = PlanExecutor(plan, self.metadata, self.session)
        names, page = executor.execute()

        if isinstance(stmt, t.CreateTableAsSelect):
            columns = [
                ColumnMetadata(name, col.type)
                for name, col in zip(names, page.columns)
            ]
            connector.create_table(st, columns)
            n = connector.insert(st, page)
            CACHES.on_ddl()
            return QueryResult(["rows"], [(n,)])

        # INSERT INTO
        meta = connector.metadata().get_table_metadata(st)
        target_cols = list(meta.columns)
        if stmt.columns:
            if list(stmt.columns) != [c.name for c in target_cols]:
                raise ValueError(
                    "INSERT column list must match table columns in order (round 1)"
                )
        if page.num_columns != len(target_cols):
            raise ValueError(
                f"INSERT has {page.num_columns} columns, table has {len(target_cols)}"
            )
        from ..spi.types import (
            ArrayType,
            VectorType,
            common_super_type,
            is_numeric,
        )

        converted = list(page.columns)
        for i, (col, target) in enumerate(zip(page.columns, target_cols)):
            if isinstance(target.type, VectorType) and col.type != target.type:
                # tensor plane ingest: array literals/columns land on the
                # dense vector layout here (host boundary — length
                # mismatches raise loudly, unlike the expression-level CAST)
                from ..spi.types import UnknownType

                if isinstance(col.type, UnknownType):
                    # an all-NULL VALUES column: the NULL vector column
                    import jax.numpy as _jnp

                    from ..spi.page import Column

                    cap = int(col.valid.shape[0])
                    converted[i] = Column(
                        target.type,
                        _jnp.zeros(
                            (cap, target.type.dimension), dtype=_jnp.float64
                        ),
                        _jnp.zeros((cap,), dtype=_jnp.bool_),
                    )
                    continue
                if not (
                    isinstance(col.type, ArrayType)
                    and is_numeric(col.type.element)
                ) and not isinstance(col.type, VectorType):
                    raise ValueError(
                        f"INSERT column {i} ({target.name}): cannot insert "
                        f"{col.type.display()} into {target.type.display()}"
                    )
                from ..ops.tensor import column_to_vector

                try:
                    converted[i] = column_to_vector(col, target.type)
                except ValueError as e:
                    raise ValueError(
                        f"INSERT column {i} ({target.name}): {e}"
                    ) from e
                continue
            if col.type != target.type and common_super_type(col.type, target.type) != target.type:
                raise ValueError(
                    f"INSERT column {i} ({target.name}): cannot insert "
                    f"{col.type.display()} into {target.type.display()}"
                )
        if any(c is not o for c, o in zip(converted, page.columns)):
            page = page.with_columns(converted)
        n = connector.insert(st, page)
        # exact invalidation on the snapshot bump (iceberg-lite commits a new
        # snapshot above; memory tables bump their mutation counter): every
        # warm entry touching the table drops NOW, not at TTL expiry
        CACHES.invalidate_table(catalog, st.schema, st.table)
        return QueryResult(["rows"], [(n,)])

    def explain_statement(self, stmt: t.Statement) -> str:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        return format_plan(plan, annotate=self._cache_annotator(plan)) \
            if self._caches_on() else format_plan(plan)

    # ------------------------------------------------------- cache provenance

    def _caches_on(self) -> bool:
        from .cachestore import CACHES

        return (
            CACHES.result_enabled(self.session)
            or CACHES.fragment_enabled(self.session)
        )

    def _cache_annotator(self, plan):
        """EXPLAIN per-node + per-query cache provenance (rendered only when
        a cache tier is enabled, so default plans print byte-identically).
        The result-tier line rides the root node; fragment-tier entries
        annotate the subtree they would serve."""
        from .cachestore import (
            CACHES,
            FragmentBinding,
            profile_plan,
            resolve_versions,
            versions_provenance,
        )

        root = plan.root
        lines: Dict[int, str] = {}
        if CACHES.result_enabled(self.session) and self._txn is None:
            profile = profile_plan(plan)
            versions = resolve_versions(self.metadata, profile.tables)
            key = CACHES.result.key_for(
                profile, versions, self.session,
                registry=self.catalogs.cache_nonce,
            )
            hit = CACHES.result.peek(key)
            if hit is not None:
                lines[id(root)] = (
                    f"   [result cache HIT @ {hit.provenance}]"
                )
            elif key is not None:
                lines[id(root)] = (
                    f"   [result cache MISS @ "
                    f"{versions_provenance(profile.tables, versions)}]"
                )
            else:
                lines[id(root)] = "   [result cache BYPASS]"
        if CACHES.fragment_enabled(self.session) and self._txn is None:
            from ..planner.plan import AggregationNode

            binding = FragmentBinding(
                CACHES.fragment, self.metadata, self.session,
                registry=self.catalogs.cache_nonce,
            )

            class _Probe:
                pass  # subtree_cacheable memoizes per-"executor" object

            probe = _Probe()

            def walk(node):
                if isinstance(node, AggregationNode) \
                        and CACHES.fragment.subtree_cacheable(node, probe):
                    e = CACHES.fragment.peek(node, binding)
                    if e is not None:
                        who = e.query_id or "an earlier query"
                        lines[id(node)] = (
                            f"   [fragment reused from query {who}]"
                        )
                for s in node.sources:
                    walk(s)

            walk(root)

        def annotate(node) -> str:
            return lines.get(id(node), "")

        return annotate

    def _explain_distributed(self, stmt: t.Statement) -> str:
        """EXPLAIN (TYPE DISTRIBUTED): the fragmented plan, one section per
        stage with its partitioning (ref: sql/planner/planprinter's
        distributed output + PlanFragmenter)."""
        from ..planner.fragmenter import add_exchanges, create_fragments

        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, self.metadata, self.session)
        sub = create_fragments(plan)
        lines = []
        for frag in sorted(sub.fragments, key=lambda f: f.fragment_id, reverse=True):
            lines.append(
                f"Fragment {frag.fragment_id} [{frag.partitioning.value}] "
                f"<- {sorted(frag.input_fragments)}"
            )
            body = format_plan(LogicalPlan(frag.root, sub.types))
            lines.extend("    " + ln for ln in body.split("\n"))
            lines.append("")
        return "\n".join(lines).rstrip()

    def _explain_analyze(self, stmt: t.Statement, verbose: bool = False) -> str:
        """EXPLAIN ANALYZE: execute with per-operator stats (the
        ExplainAnalyzeOperator path, SURVEY.md §5.1), rendering per-node
        ESTIMATED vs ACTUAL rows with the q-error — the statistics feedback
        plane's primary human surface. VERBOSE adds the observability
        plane's per-operator device/host/compile attribution (stats
        collection fences each operator, so the splits are exact)."""
        from .statstore import q_error

        if not isinstance(stmt, t.QueryStatement):
            raise ValueError("EXPLAIN ANALYZE supports queries only")
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        # EXPLAIN ANALYZE executes the query — same access checks as execute()
        self._check_select_access(plan)
        executor = PlanExecutor(plan, self.metadata, self.session, collect_stats=True)
        executor.collect_actuals = True
        if verbose:
            # VERBOSE is the kernel cost plane's human surface: force
            # attribution on regardless of the kernel_cost session property
            # (stats mode already fences every operator, so the roofline's
            # device_secs denominator is exact)
            executor.kernel_cost_enabled = True
        from .cachestore import CACHES, FragmentBinding

        if CACHES.fragment_enabled(self.session) and self._txn is None:
            from .statstore import current_query_id

            executor.fragment_cache = FragmentBinding(
                CACHES.fragment, self.metadata, self.session,
                query_id=current_query_id() or "",
                registry=self.catalogs.cache_nonce,
            )
        executor.execute()

        from . import observability as obs
        from . import statstore
        from ..planner.stats import make_estimator

        # the estimator must snapshot history BEFORE this run records its
        # own actuals: under history_based_stats the just-recorded rows
        # would otherwise overlay the rendering and every node would show
        # est == actual (q=1.0) — hiding exactly the mis-estimates the
        # est-vs-actual output exists to surface
        estimator = make_estimator(self.metadata, plan.types, self.session)

        # the analyzed run feeds the same history/misestimate plane a plain
        # execution does (Presto HBO records from analyze too)
        try:
            collector = obs.current_collector() or obs.QueryStatsCollector()
            statstore.observe_query(
                plan, self.metadata, self.session, collector,
                executor.finalize_actuals(),
                query_id=statstore.current_query_id() or "",
            )
        except Exception:  # noqa: BLE001 — observability only
            pass

        def fmt_rows(v) -> str:
            if v is None:
                return "?"
            v = float(v)
            for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
                if v >= div:
                    return f"{v / div:.2g}{unit}"
            return f"{v:.0f}"

        def annotate(node) -> str:
            prov = executor.cache_provenance.get(id(node))
            prov_text = f" [{prov}]" if prov else ""
            s = executor.stats.get(id(node))
            if s is None:
                return prov_text
            own_wall, own_device, own_host, own_compile = _exclusive_times(
                executor, node, s
            )
            try:
                est = estimator.rows(node)
            except Exception:  # noqa: BLE001
                est = None
            q = q_error(est, s.output_rows)
            qtext = f" (q={q:.1f})" if q is not None else ""
            base = (
                f"   [rows: est {fmt_rows(est)} -> actual "
                f"{s.output_rows:,}{qtext} capacity={s.output_capacity:,} "
                f"time={own_wall * 1000:.2f}ms"
            )
            if not verbose:
                return base + "]" + prov_text
            kc_text = ""
            kc = executor.kernel_costs.get(id(node))
            if kc and kc.get("programs"):
                from . import kernelcost

                line = kernelcost.render_roofline(
                    kc.get("flops"), kc.get("bytes_accessed"),
                    kc.get("peak_hbm_bytes"),
                    device_secs=own_device if own_device > 0 else None,
                )
                if line:
                    kc_text = f" [kernel: {line}]"
                elif kc.get("unavailable"):
                    kc_text = " [kernel: cost_unavailable]"
            return (
                base
                + f" device={own_device * 1000:.2f}ms"
                + f" host={own_host * 1000:.2f}ms"
                + f" compile={own_compile * 1000:.2f}ms]"
                + prov_text
                + kc_text
            )

        text = format_plan(plan, annotate=annotate)
        if verbose and self._cluster_obs_enabled():
            # cluster observability plane: the dominant-cost diagnosis line
            # ("stage 2: 61% exchange pull" on FTE profiles; here the per-
            # operator device/host/compile split plays the stage role)
            diag = self._dominant_cost_line(plan, executor)
            if diag:
                text += f"\n\ndominant cost — {diag}"
        return text

    def _cluster_obs_enabled(self) -> bool:
        try:
            return bool(self.session.get("cluster_obs"))
        except KeyError:
            return False

    def _dominant_cost_line(self, plan, executor) -> Optional[str]:
        """EXPLAIN ANALYZE VERBOSE's diagnosis: which operator owns the
        query's time and which component (device/host/compile) dominates
        it — the same renderer FTE query profiles use per stage. Splits
        come from the same :func:`_exclusive_times` the per-operator
        annotations render, so the line can never contradict them."""
        from .clusterobs import dominant_cost

        entries = []

        def walk(node) -> None:
            s = executor.stats.get(id(node))
            if s is not None:
                own_wall, own_device, own_host, own_compile = (
                    _exclusive_times(executor, node, s)
                )
                entries.append((
                    type(node).__name__, own_wall,
                    {"device_secs": own_device, "host_secs": own_host,
                     "compile_secs": own_compile},
                ))
            for c in node.sources:
                walk(c)

        walk(plan.root)
        return dominant_cost(entries)

    # ------------------------------------------------------------------ show

    def _show_tables(self, stmt: t.ShowTables) -> QueryResult:
        catalog = self.session.catalog
        schema = self.session.schema
        if stmt.schema is not None:
            parts = stmt.schema.parts
            if len(parts) == 2:
                catalog, schema = parts
            else:
                schema = parts[0]
        connector = self.metadata.connector_by_name(catalog) if catalog else None
        if connector is None:
            raise ValueError(f"catalog not set or not found: {catalog}")
        tables = connector.metadata().list_tables(schema)
        tables = self.access_control.filter_tables(
            self._current_user(), catalog, tables
        )
        return QueryResult(["Table"], [(st.table,) for st in tables])

    def _show_schemas(self, stmt: t.ShowSchemas) -> QueryResult:
        catalog = stmt.catalog or self.session.catalog
        connector = self.metadata.connector_by_name(catalog) if catalog else None
        if connector is None:
            raise ValueError(f"catalog not set or not found: {catalog}")
        schemas = self.access_control.filter_schemas(
            self._current_user(), catalog, connector.metadata().list_schemas()
        )
        return QueryResult(["Schema"], [(s,) for s in schemas])

    def _show_columns(self, stmt: t.ShowColumns) -> QueryResult:
        from ..sql.tree import QualifiedName

        handle, meta = self.metadata.resolve_table(self.session, stmt.table)
        # schema of a fully-denied table must not leak (checkCanShowColumns)
        visible = self.access_control.filter_tables(
            self._current_user(), handle.catalog, [handle.schema_table]
        )
        if not visible:
            from ..spi.security import AccessDeniedError

            raise AccessDeniedError(
                f"Cannot show columns of table {handle.schema_table}"
            )
        return QueryResult(
            ["Column", "Type"],
            [(c.name, c.type.display()) for c in meta.columns],
        )
