"""Test configuration: force a hermetic 8-device virtual CPU "cluster".

Mirrors the reference's DistributedQueryRunner idea (testing/trino-testing/.../
DistributedQueryRunner.java:108 — a multi-node cluster in one process): we get a
multi-"chip" TPU topology in one process via XLA's host-platform device count, so
sharding/collective paths are exercised without TPU hardware.

Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tpch_tiny():
    """Tiny deterministic TPC-H catalog shared across the session."""
    from trino_tpu.connectors.tpch import TpchConnector

    return TpchConnector(scale=0.001)
