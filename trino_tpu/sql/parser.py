"""Recursive-descent SQL parser producing the AST in :mod:`trino_tpu.sql.tree`.

Reference blueprint: core/trino-parser/src/main/java/io/trino/sql/parser/
SqlParser.java:104 (`createStatement`) + AstBuilder.java (the ANTLR visitor, 4,770
LoC) over core/trino-grammar/.../SqlBase.g4. The grammar subset implemented here is
the SELECT core plus the statements the engine executes in round 1; the structure
mirrors the g4 rules (queryNoWith / queryTerm / querySpecification / booleanExpression
/ valueExpression / primaryExpression) so coverage can be widened rule by rule.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .lexer import Token, TokenType, tokenize, NON_RESERVED
from . import tree as t


class ParseError(ValueError):
    pass


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.pos = 0
        self._param_count = 0  # positional ? parameters seen so far

    # ------------------------------------------------------------------ utils

    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.pos + offset, len(self.tokens) - 1)]

    def at_keyword(self, *words: str) -> bool:
        tok = self.peek()
        return tok.type == TokenType.KEYWORD and tok.value in words

    def at_op(self, *ops: str) -> bool:
        tok = self.peek()
        return tok.type == TokenType.OP and tok.value in ops

    def advance(self) -> Token:
        tok = self.tokens[self.pos]
        if tok.type != TokenType.EOF:
            self.pos += 1
        return tok

    def accept_keyword(self, *words: str) -> bool:
        if self.at_keyword(*words):
            self.advance()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_keyword(self, word: str) -> Token:
        if not self.at_keyword(word):
            raise ParseError(f"expected {word} but found {self.peek().value!r} at {self.peek().pos}")
        return self.advance()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise ParseError(f"expected {op!r} but found {self.peek().value!r} at {self.peek().pos}")
        return self.advance()

    def identifier(self) -> str:
        tok = self.peek()
        if tok.type == TokenType.IDENT:
            self.advance()
            return tok.value
        if tok.type == TokenType.QUOTED_IDENT:
            self.advance()
            return tok.value
        if tok.type == TokenType.KEYWORD and tok.value in NON_RESERVED:
            self.advance()
            return tok.value.lower()
        raise ParseError(f"expected identifier but found {tok.value!r} at {tok.pos}")

    def qualified_name(self) -> t.QualifiedName:
        parts = [self.identifier()]
        while self.at_op(".") and self.peek(1).type in (
            TokenType.IDENT,
            TokenType.QUOTED_IDENT,
            TokenType.KEYWORD,
        ):
            self.advance()
            parts.append(self.identifier())
        return t.QualifiedName(tuple(parts))

    # -------------------------------------------------------------- statements

    def parse_statement(self) -> t.Statement:
        stmt = self._statement()
        self.accept_op(";")
        if self.peek().type != TokenType.EOF:
            raise ParseError(f"unexpected trailing input at {self.peek().pos}: {self.peek().value!r}")
        return stmt

    def _statement(self) -> t.Statement:
        if self.accept_keyword("EXPLAIN"):
            explain_type = "LOGICAL"
            if self.accept_op("("):
                self.expect_keyword("TYPE")
                explain_type = self.advance().value.upper()
                self.expect_op(")")
            analyze = self.accept_keyword("ANALYZE")
            # VERBOSE lexes as a plain identifier (not in KEYWORDS)
            verbose = False
            if analyze and (
                self.peek().type == TokenType.IDENT
                and self.peek().value == "verbose"
            ):
                self.advance()
                verbose = True
            inner = self._statement()
            return t.Explain(
                statement=inner, analyze=analyze, explain_type=explain_type,
                verbose=verbose,
            )
        # CATALOG lexes as a plain identifier (not in KEYWORDS)
        if self.at_keyword("DROP") and (
            self.peek(1).type == TokenType.IDENT and self.peek(1).value == "catalog"
        ):
            self.advance()  # DROP
            self.advance()  # CATALOG
            if_exists = False
            if self.accept_keyword("IF"):
                self.expect_keyword("EXISTS")
                if_exists = True
            return t.DropCatalog(name=self.identifier(), if_exists=if_exists)
        if self.accept_keyword("USE"):
            qn = self.qualified_name()
            if len(qn.parts) == 1:
                return t.Use(schema=qn.parts[0])
            if len(qn.parts) == 2:
                return t.Use(catalog=qn.parts[0], schema=qn.parts[1])
            raise ParseError("USE expects [catalog.]schema")
        if self.at_keyword("SHOW"):
            return self._show()
        if self.accept_keyword("SET"):
            self.expect_keyword("SESSION")
            name = self.qualified_name()
            self.expect_op("=")
            value = self.expression()
            return t.SetSession(name=name, value=value)
        if self.accept_keyword("RESET"):
            self.expect_keyword("SESSION")
            return t.ResetSession(name=self.qualified_name())
        if self.accept_keyword("CREATE"):
            if (
                self.peek().type == TokenType.IDENT
                and self.peek().value == "catalog"
            ):
                self.advance()
                if_not_exists = False
                if self.accept_keyword("IF"):
                    self.expect_keyword("NOT")
                    self.expect_keyword("EXISTS")
                    if_not_exists = True
                name = self.identifier()
                self.expect_keyword("USING")
                connector = self.identifier()
                props = []
                if self.accept_keyword("WITH"):
                    self.expect_op("(")
                    while True:
                        k = self.identifier() if self.peek().type != TokenType.STRING else self.advance().value
                        self.expect_op("=")
                        neg = self.accept_op("-")
                        tok = self.peek()
                        if tok.type == TokenType.INTEGER:
                            self.advance()
                            v: object = -int(tok.value) if neg else int(tok.value)
                        elif tok.type in (TokenType.DECIMAL, TokenType.FLOAT):
                            self.advance()
                            v = -float(tok.value) if neg else float(tok.value)
                        elif not neg and tok.type == TokenType.STRING:
                            self.advance()
                            v = tok.value
                        elif not neg and tok.type == TokenType.KEYWORD and tok.value in ("TRUE", "FALSE"):
                            self.advance()
                            v = tok.value == "TRUE"
                        else:
                            raise ParseError(
                                f"catalog property value must be a literal, "
                                f"found {tok.value!r} at {tok.pos}"
                            )
                        props.append((str(k), v))
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                return t.CreateCatalog(
                    name=name, connector=connector,
                    properties=tuple(props), if_not_exists=if_not_exists,
                )
            if self.accept_keyword("OR"):
                self.expect_keyword("REPLACE")
                if self.accept_keyword("FUNCTION"):
                    return self._create_function(replace=True)
                self.expect_keyword("VIEW")
                name = self.qualified_name()
                self.expect_keyword("AS")
                body_start = self.peek().pos
                query = self.parse_query()
                return t.CreateView(
                    name=name, query=query, replace=True,
                    query_text=self.sql[body_start:].strip().rstrip(";").strip(),
                )
            if self.accept_keyword("FUNCTION"):
                return self._create_function(replace=False)
            if self.accept_keyword("VIEW"):
                name = self.qualified_name()
                self.expect_keyword("AS")
                body_start = self.peek().pos
                query = self.parse_query()
                return t.CreateView(
                    name=name, query=query,
                    query_text=self.sql[body_start:].strip().rstrip(";").strip(),
                )
            self.expect_keyword("TABLE")
            if_not_exists = False
            if self.accept_keyword("IF"):
                self.expect_keyword("NOT")
                self.expect_keyword("EXISTS")
                if_not_exists = True
            name = self.qualified_name()
            if self.accept_op("("):
                # CREATE TABLE t (col type, ...) — explicit column definitions
                cols = []
                while True:
                    cname = self.identifier()
                    cols.append((cname, self._type_name()))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                return t.CreateTable(
                    name=name, columns=tuple(cols), if_not_exists=if_not_exists
                )
            self.expect_keyword("AS")
            query = self.parse_query()
            return t.CreateTableAsSelect(name=name, query=query, if_not_exists=if_not_exists)
        if self.at_keyword("GRANT", "REVOKE"):
            is_grant = self.advance().value == "GRANT"
            privs: List[str] = []
            if self.accept_keyword("ALL"):
                self.accept_keyword("PRIVILEGES")
            else:
                while True:
                    privs.append(self.advance().value.upper())
                    if not self.accept_op(","):
                        break
            self.expect_keyword("ON")
            self.accept_keyword("TABLE")
            table = self.qualified_name()
            self.expect_keyword("TO" if is_grant else "FROM")
            self.accept_keyword("USER")
            grantee = self.identifier()
            cls = t.Grant if is_grant else t.Revoke
            return cls(privileges=tuple(privs), table=table, grantee=grantee)
        if self.accept_keyword("DROP"):
            if self.accept_keyword("FUNCTION"):
                if_exists = False
                if self.accept_keyword("IF"):
                    self.expect_keyword("EXISTS")
                    if_exists = True
                return t.DropFunction(name=self.qualified_name(), if_exists=if_exists)
            if self.accept_keyword("VIEW"):
                if_exists = False
                if self.accept_keyword("IF"):
                    self.expect_keyword("EXISTS")
                    if_exists = True
                return t.DropView(name=self.qualified_name(), if_exists=if_exists)
            self.expect_keyword("TABLE")
            if_exists = False
            if self.accept_keyword("IF"):
                self.expect_keyword("EXISTS")
                if_exists = True
            return t.DropTable(name=self.qualified_name(), if_exists=if_exists)
        if self.accept_keyword("INSERT"):
            self.expect_keyword("INTO")
            name = self.qualified_name()
            cols: Tuple[str, ...] = ()
            if self.at_op("(") and self._looks_like_column_list():
                self.expect_op("(")
                names = [self.identifier()]
                while self.accept_op(","):
                    names.append(self.identifier())
                self.expect_op(")")
                cols = tuple(names)
            query = self.parse_query()
            return t.InsertInto(table=name, columns=cols, query=query)
        if self.accept_keyword("DESCRIBE"):
            if self.accept_keyword("INPUT"):
                return t.DescribeInput(name=self.identifier())
            if self.accept_keyword("OUTPUT"):
                return t.DescribeOutput(name=self.identifier())
            return t.ShowColumns(table=self.qualified_name())
        if self.accept_keyword("PREPARE"):
            name = self.identifier()
            self.expect_keyword("FROM")
            body_start = self.peek().pos
            stmt = self._statement()
            body = self.sql[body_start:].strip().rstrip(";").strip()
            return t.Prepare(name=name, statement=stmt, body_text=body)
        if self.accept_keyword("EXECUTE"):
            name = self.identifier()
            params: List[t.Expression] = []
            if self.accept_keyword("USING"):
                params.append(self.expression())
                while self.accept_op(","):
                    params.append(self.expression())
            return t.ExecuteStmt(name=name, parameters=tuple(params))
        if self.accept_keyword("DEALLOCATE"):
            self.accept_keyword("PREPARE")
            return t.Deallocate(name=self.identifier())
        if self.accept_keyword("DELETE"):
            self.expect_keyword("FROM")
            name = self.qualified_name()
            where = self.expression() if self.accept_keyword("WHERE") else None
            return t.Delete(table=name, where=where)
        if self.accept_keyword("UPDATE"):
            name = self.qualified_name()
            self.expect_keyword("SET")
            assignments = [self._update_assignment()]
            while self.accept_op(","):
                assignments.append(self._update_assignment())
            where = self.expression() if self.accept_keyword("WHERE") else None
            return t.Update(table=name, assignments=tuple(assignments), where=where)
        if self.accept_keyword("MERGE"):
            return self._merge()
        if self.accept_keyword("START"):
            self.expect_keyword("TRANSACTION")
            read_only = False
            isolation = "SERIALIZABLE"
            while True:
                self.accept_op(",")
                if self.accept_keyword("ISOLATION"):
                    self.expect_keyword("LEVEL")
                    if self.accept_keyword("SERIALIZABLE"):
                        isolation = "SERIALIZABLE"
                    elif self.accept_keyword("REPEATABLE"):
                        self.expect_keyword("READ")
                        isolation = "REPEATABLE READ"
                    elif self.accept_keyword("READ"):
                        if self.accept_keyword("COMMITTED"):
                            isolation = "READ COMMITTED"
                        else:
                            self.expect_keyword("UNCOMMITTED")
                            isolation = "READ UNCOMMITTED"
                    else:
                        raise ParseError(
                            f"expected isolation level at {self.peek().pos}"
                        )
                elif self.accept_keyword("READ"):
                    if self.accept_keyword("ONLY"):
                        read_only = True
                    else:
                        self.expect_keyword("WRITE")
                        read_only = False
                else:
                    break
            return t.StartTransaction(read_only=read_only, isolation=isolation)
        if self.accept_keyword("COMMIT"):
            self.accept_keyword("WORK")
            return t.Commit()
        if self.accept_keyword("ROLLBACK"):
            self.accept_keyword("WORK")
            return t.Rollback()
        # CALL lexes as a plain identifier (not in KEYWORDS); only treat it
        # as a statement head when followed by a procedure name
        if (
            self.peek().type == TokenType.IDENT
            and self.peek().value == "call"
            and self.peek(1).type in (TokenType.IDENT, TokenType.QUOTED_IDENT)
        ):
            self.advance()  # CALL
            name = self.qualified_name()
            self.expect_op("(")
            args: List[t.Expression] = []
            if not self.accept_op(")"):
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
                self.expect_op(")")
            return t.Call(name=name, arguments=tuple(args))
        return t.QueryStatement(query=self.parse_query())

    def _update_assignment(self):
        col = self.identifier()
        self.expect_op("=")
        return (col, self.expression())

    def _merge(self) -> t.Statement:
        self.expect_keyword("INTO")
        target = self.qualified_name()
        target_alias = None
        if self.accept_keyword("AS"):
            target_alias = self.identifier()
        elif self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT) and not self.at_keyword("USING"):
            target_alias = self.identifier()
        self.expect_keyword("USING")
        source = self._relation()
        self.expect_keyword("ON")
        on = self.expression()
        cases = []
        while self.at_keyword("WHEN"):
            self.expect_keyword("WHEN")
            matched = True
            if self.accept_keyword("NOT"):
                matched = False
            self.expect_keyword("MATCHED")
            condition = None
            if self.accept_keyword("AND"):
                condition = self.expression()
            self.expect_keyword("THEN")
            if self.accept_keyword("UPDATE"):
                self.expect_keyword("SET")
                assignments = [self._update_assignment()]
                while self.accept_op(","):
                    assignments.append(self._update_assignment())
                cases.append(
                    t.MergeCase(matched, condition, "update", tuple(assignments))
                )
            elif self.accept_keyword("DELETE"):
                cases.append(t.MergeCase(matched, condition, "delete"))
            else:
                self.expect_keyword("INSERT")
                cols: list = []
                if self.accept_op("("):
                    cols.append(self.identifier())
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                self.expect_keyword("VALUES")
                self.expect_op("(")
                values = [self.expression()]
                while self.accept_op(","):
                    values.append(self.expression())
                self.expect_op(")")
                cases.append(
                    t.MergeCase(
                        matched, condition, "insert",
                        insert_columns=tuple(cols), insert_values=tuple(values),
                    )
                )
        if not cases:
            raise ParseError("MERGE requires at least one WHEN clause")
        return t.Merge(
            target=target, target_alias=target_alias, source=source, on=on,
            cases=tuple(cases),
        )

    def _looks_like_column_list(self) -> bool:
        # distinguish INSERT INTO t (a, b) SELECT ... from INSERT INTO t (SELECT ...)
        i = self.pos + 1
        tok = self.tokens[i]
        return tok.type in (TokenType.IDENT, TokenType.QUOTED_IDENT) or (
            tok.type == TokenType.KEYWORD and tok.value in NON_RESERVED
        )

    def _show(self) -> t.Statement:
        self.expect_keyword("SHOW")
        if self.accept_keyword("FUNCTIONS"):
            return t.ShowFunctions()
        if self.accept_keyword("TABLES"):
            schema = None
            if self.accept_keyword("FROM") or self.accept_keyword("IN"):
                schema = self.qualified_name()
            return t.ShowTables(schema=schema)
        if self.accept_keyword("SCHEMAS"):
            catalog = None
            if self.accept_keyword("FROM") or self.accept_keyword("IN"):
                catalog = self.identifier()
            return t.ShowSchemas(catalog=catalog)
        if self.accept_keyword("CATALOGS"):
            return t.ShowCatalogs()
        if self.accept_keyword("COLUMNS"):
            if not (self.accept_keyword("FROM") or self.accept_keyword("IN")):
                raise ParseError("expected FROM after SHOW COLUMNS")
            return t.ShowColumns(table=self.qualified_name())
        if self.accept_keyword("SESSION"):
            return t.ShowSession()
        if self.accept_keyword("CREATE"):
            if self.accept_keyword("VIEW"):
                return t.ShowCreate(kind="view", name=self.qualified_name())
            self.expect_keyword("TABLE")
            return t.ShowCreate(kind="table", name=self.qualified_name())
        raise ParseError(f"unsupported SHOW statement at {self.peek().pos}")

    # ------------------------------------------------------------------ query

    def parse_query(self) -> t.Query:
        with_queries: Tuple[t.WithQuery, ...] = ()
        if self.accept_keyword("WITH"):
            items = [self._with_query()]
            while self.accept_op(","):
                items.append(self._with_query())
            with_queries = tuple(items)
        body = self._query_term()
        order_by, limit, offset = self._order_limit()
        # If the body is a bare QuerySpecification, fold ORDER BY/LIMIT into it
        # (matches Trino's queryNoWith handling, AstBuilder.java visitQueryNoWith).
        if isinstance(body, t.QuerySpecification) and (order_by or limit is not None or offset):
            body = t.QuerySpecification(
                select_items=body.select_items,
                distinct=body.distinct,
                from_=body.from_,
                where=body.where,
                group_by=body.group_by,
                having=body.having,
                order_by=order_by,
                limit=limit,
                offset=offset,
            )
            return t.Query(body=body, with_queries=with_queries)
        return t.Query(body=body, with_queries=with_queries, order_by=order_by, limit=limit, offset=offset)

    def _with_query(self) -> t.WithQuery:
        name = self.identifier()
        cols: Tuple[str, ...] = ()
        if self.accept_op("("):
            names = [self.identifier()]
            while self.accept_op(","):
                names.append(self.identifier())
            self.expect_op(")")
            cols = tuple(names)
        self.expect_keyword("AS")
        self.expect_op("(")
        q = self.parse_query()
        self.expect_op(")")
        return t.WithQuery(name=name, query=q, column_names=cols)

    def _order_limit(self):
        order_by: Tuple[t.SortItem, ...] = ()
        limit: Optional[int] = None
        offset = 0
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            items = [self._sort_item()]
            while self.accept_op(","):
                items.append(self._sort_item())
            order_by = tuple(items)
        # OFFSET/LIMIT accepted in either order (Trino uses OFFSET-then-LIMIT;
        # the Postgres/MySQL LIMIT-then-OFFSET spelling is ubiquitous), but each
        # clause kind at most once
        seen_offset = seen_limit = False
        for _ in range(2):
            if self.at_keyword("OFFSET"):
                if seen_offset:
                    raise ParseError(f"duplicate OFFSET at {self.peek().pos}")
                seen_offset = True
                self.advance()
                offset = int(self.advance().value)
                self.accept_keyword("ROWS") or self.accept_keyword("ROW")
            elif self.at_keyword("LIMIT", "FETCH"):
                if seen_limit:
                    raise ParseError(f"duplicate LIMIT/FETCH at {self.peek().pos}")
                seen_limit = True
                if self.accept_keyword("LIMIT"):
                    tok = self.advance()
                    if tok.type == TokenType.KEYWORD and tok.value == "ALL":
                        limit = None
                    else:
                        limit = int(tok.value)
                else:
                    self.expect_keyword("FETCH")
                    self.accept_keyword("FIRST") or self.accept_keyword("NEXT")
                    limit = int(self.advance().value)
                    self.accept_keyword("ROWS") or self.accept_keyword("ROW")
                    self.expect_keyword("ONLY")
        return order_by, limit, offset

    def _sort_item(self) -> t.SortItem:
        key = self.expression()
        ascending = True
        if self.accept_keyword("ASC"):
            pass
        elif self.accept_keyword("DESC"):
            ascending = False
        nulls_first: Optional[bool] = None
        if self.accept_keyword("NULLS"):
            if self.accept_keyword("FIRST"):
                nulls_first = True
            else:
                self.expect_keyword("LAST")
                nulls_first = False
        return t.SortItem(key=key, ascending=ascending, nulls_first=nulls_first)

    def _query_term(self) -> t.QueryBody:
        left = self._query_primary()
        while self.at_keyword("UNION", "INTERSECT", "EXCEPT"):
            op_tok = self.advance().value
            distinct = True
            if self.accept_keyword("ALL"):
                distinct = False
            else:
                self.accept_keyword("DISTINCT")
            right = self._query_primary()
            left = t.SetOperation(op=t.SetOpType[op_tok], left=left, right=right, distinct=distinct)
        return left

    def _query_primary(self) -> t.QueryBody:
        if self.at_keyword("SELECT"):
            return self._query_specification()
        if self.accept_keyword("VALUES"):
            rows = [self.expression()]
            while self.accept_op(","):
                rows.append(self.expression())
            return t.Values(rows=tuple(rows))
        if self.accept_keyword("TABLE"):
            return t.TableRef(name=self.qualified_name())
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            # flatten: (query) as a query body
            if not q.with_queries and not q.order_by and q.limit is None and not q.offset:
                return q.body
            # keep as subquery spec via a wrapper table subquery in FROM-less select
            return q.body
        raise ParseError(f"expected query at {self.peek().pos}, found {self.peek().value!r}")

    def _query_specification(self) -> t.QuerySpecification:
        self.expect_keyword("SELECT")
        distinct = False
        if self.accept_keyword("DISTINCT"):
            distinct = True
        else:
            self.accept_keyword("ALL")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_: Optional[t.Relation] = None
        if self.accept_keyword("FROM"):
            from_ = self._relation()
            while self.accept_op(","):
                right = self._relation()
                from_ = t.Join(join_type=t.JoinType.IMPLICIT, left=from_, right=right)
        where = self.expression() if self.accept_keyword("WHERE") else None
        group_by: Tuple[t.GroupingElement, ...] = ()
        if self.accept_keyword("GROUP"):
            self.expect_keyword("BY")
            group_by = tuple(self._grouping_elements())
        having = self.expression() if self.accept_keyword("HAVING") else None
        return t.QuerySpecification(
            select_items=tuple(items),
            distinct=distinct,
            from_=from_,
            where=where,
            group_by=group_by,
            having=having,
        )

    def _grouping_elements(self) -> List[t.GroupingElement]:
        elements = []
        while True:
            if self.accept_keyword("ROLLUP"):
                self.expect_op("(")
                exprs = [self.expression()]
                while self.accept_op(","):
                    exprs.append(self.expression())
                self.expect_op(")")
                elements.append(t.GroupingElement(tuple(exprs), kind="rollup"))
            elif self.accept_keyword("CUBE"):
                self.expect_op("(")
                exprs = [self.expression()]
                while self.accept_op(","):
                    exprs.append(self.expression())
                self.expect_op(")")
                elements.append(t.GroupingElement(tuple(exprs), kind="cube"))
            elif self.at_keyword("GROUPING") and self.peek(1).value == "SETS":
                self.advance()
                self.advance()
                self.expect_op("(")
                # each set is (a, b) or a
                sets = []
                while True:
                    if self.accept_op("("):
                        exprs = []
                        if not self.at_op(")"):
                            exprs.append(self.expression())
                            while self.accept_op(","):
                                exprs.append(self.expression())
                        self.expect_op(")")
                        sets.append(tuple(exprs))
                    else:
                        sets.append((self.expression(),))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                union_exprs = tuple(e for s in sets for e in s)
                elements.append(
                    t.GroupingElement(union_exprs, kind="grouping_sets", sets=tuple(sets))
                )
            else:
                elements.append(t.GroupingElement((self.expression(),), kind="simple"))
            if not self.accept_op(","):
                break
        return elements

    def _select_item(self) -> t.SelectItem:
        if self.at_op("*"):
            self.advance()
            return t.SelectItem(expression=t.Star())
        # t.* / catalog.schema.t.*
        save = self.pos
        try:
            qn = self.qualified_name()
            if self.at_op(".") and self.peek(1).type == TokenType.OP and self.peek(1).value == "*":
                self.advance()
                self.advance()
                return t.SelectItem(expression=t.Star(qualifier=qn))
        except ParseError:
            pass
        self.pos = save
        expr = self.expression()
        alias = None
        if self.accept_keyword("AS"):
            alias = self.identifier()
        elif self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT):
            alias = self.identifier()
        return t.SelectItem(expression=expr, alias=alias)

    # -------------------------------------------------------------- relations

    def _relation(self) -> t.Relation:
        left = self._sampled_relation()
        while True:
            if self.accept_keyword("CROSS"):
                self.expect_keyword("JOIN")
                right = self._sampled_relation()
                left = t.Join(join_type=t.JoinType.CROSS, left=left, right=right)
                continue
            natural = self.accept_keyword("NATURAL")
            jt: Optional[t.JoinType] = None
            if self.accept_keyword("JOIN"):
                jt = t.JoinType.INNER
            elif self.accept_keyword("INNER"):
                self.expect_keyword("JOIN")
                jt = t.JoinType.INNER
            elif self.at_keyword("LEFT", "RIGHT", "FULL"):
                side = self.advance().value
                self.accept_keyword("OUTER")
                self.expect_keyword("JOIN")
                jt = t.JoinType[side]
            elif natural:
                raise ParseError("expected JOIN after NATURAL")
            if jt is None:
                return left
            right = self._sampled_relation()
            criteria: Optional[t.Node]
            if natural:
                criteria = t.NaturalJoin()
            elif self.accept_keyword("ON"):
                criteria = t.JoinOn(self.expression())
            elif self.accept_keyword("USING"):
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                criteria = t.JoinUsing(tuple(cols))
            else:
                raise ParseError(f"expected ON or USING for join at {self.peek().pos}")
            left = t.Join(join_type=jt, left=left, right=right, criteria=criteria)

    def _sampled_relation(self) -> t.Relation:
        rel = self._aliased_relation()
        # patternRecognition sits ABOVE aliasedRelation in SqlBase.g4: the
        # MATCH_RECOGNIZE suffix applies to the aliased input, and its result
        # may itself be aliased
        if self.accept_keyword("MATCH_RECOGNIZE"):
            rel = self._match_recognize(rel)
            rel = self._maybe_alias(rel)
        return rel

    def _aliased_relation(self) -> t.Relation:
        return self._maybe_alias(self._relation_primary())

    def _maybe_alias(self, rel: t.Relation) -> t.Relation:
        alias = None
        cols: Tuple[str, ...] = ()
        if self.accept_keyword("AS"):
            alias = self.identifier()
        elif self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT) and not self.at_keyword():
            alias = self.identifier()
        if alias is not None:
            if self.accept_op("("):
                names = [self.identifier()]
                while self.accept_op(","):
                    names.append(self.identifier())
                self.expect_op(")")
                cols = tuple(names)
            return t.AliasedRelation(relation=rel, alias=alias, column_names=cols)
        return rel

    def _create_function(self, replace: bool) -> t.Statement:
        """CREATE [OR REPLACE] FUNCTION name(p type, ...) RETURNS type
        [DETERMINISTIC] RETURN expr (sql/tree/CreateFunction.java; the
        expression-bodied routine subset)."""
        name = self.qualified_name()
        self.expect_op("(")
        params: List[Tuple[str, str]] = []
        if not self.at_op(")"):
            while True:
                pname = self.identifier()
                params.append((pname, self._type_name()))
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        self.expect_keyword("RETURNS")
        return_type = self._type_name()
        self.accept_keyword("DETERMINISTIC")
        self.expect_keyword("RETURN")
        body_start = self.peek().pos
        body = self.expression()
        return t.CreateFunction(
            name=name,
            parameters=tuple(params),
            return_type=return_type,
            body=body,
            body_text=self.sql[body_start:].strip().rstrip(";").strip(),
            replace=replace,
        )

    def _match_recognize(self, rel: t.Relation) -> t.Relation:
        """MATCH_RECOGNIZE (...) suffix (ref: patternRecognition rule in
        SqlBase.g4 + sql/tree/PatternRecognitionRelation.java)."""
        self.expect_op("(")
        partition: list = []
        order: list = []
        measures: list = []
        rows_per_match = "ONE"
        skip = t.SkipTo()
        subsets: list = []
        defines: list = []
        if self.accept_keyword("PARTITION"):
            self.expect_keyword("BY")
            partition.append(self.expression())
            while self.accept_op(","):
                partition.append(self.expression())
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            order.append(self._sort_item())
            while self.accept_op(","):
                order.append(self._sort_item())
        if self.accept_keyword("MEASURES"):
            while True:
                semantics = None
                tok = self.peek()
                if tok.type == TokenType.IDENT and tok.value in ("running", "final"):
                    semantics = tok.value.upper()
                    self.advance()
                expr = self.expression()
                self.expect_keyword("AS")
                measures.append(
                    t.MeasureItem(
                        expression=expr, name=self.identifier(), semantics=semantics
                    )
                )
                if not self.accept_op(","):
                    break
        if self.accept_keyword("ONE"):
            self.expect_keyword("ROW")
            self.expect_keyword("PER")
            self.expect_keyword("MATCH")
        elif self.accept_keyword("ALL"):
            self.expect_keyword("ROWS")
            self.expect_keyword("PER")
            self.expect_keyword("MATCH")
            rows_per_match = "ALL"
            if self.accept_keyword("OMIT"):  # OMIT EMPTY MATCHES (the default)
                self.expect_keyword("EMPTY")
                self.accept_keyword("MATCHES")
        if self.accept_keyword("AFTER"):
            self.expect_keyword("MATCH")
            self.expect_keyword("SKIP")
            if self.accept_keyword("PAST"):
                self.expect_keyword("LAST")
                self.expect_keyword("ROW")
                skip = t.SkipTo(mode="PAST_LAST")
            else:
                self.expect_keyword("TO")
                if self.accept_keyword("NEXT"):
                    self.expect_keyword("ROW")
                    skip = t.SkipTo(mode="TO_NEXT_ROW")
                elif self.accept_keyword("FIRST"):
                    skip = t.SkipTo(mode="TO_FIRST", target=self.identifier())
                else:
                    self.accept_keyword("LAST")
                    skip = t.SkipTo(mode="TO_LAST", target=self.identifier())
        self.expect_keyword("PATTERN")
        self.expect_op("(")
        pattern = self._row_pattern()
        self.expect_op(")")
        if self.accept_keyword("SUBSET"):
            while True:
                name = self.identifier()
                self.expect_op("=")
                self.expect_op("(")
                members = [self.identifier()]
                while self.accept_op(","):
                    members.append(self.identifier())
                self.expect_op(")")
                subsets.append((name, tuple(members)))
                if not self.accept_op(","):
                    break
        self.expect_keyword("DEFINE")
        while True:
            var = self.identifier()
            self.expect_keyword("AS")
            defines.append((var, self.expression()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return t.MatchRecognize(
            relation=rel,
            partition_by=tuple(partition),
            order_by=tuple(order),
            measures=tuple(measures),
            rows_per_match=rows_per_match,
            after_skip=skip,
            pattern=pattern,
            subsets=tuple(subsets),
            defines=tuple(defines),
        )

    def _row_pattern(self) -> t.Node:
        """alternation > concatenation > quantified primary (SqlBase.g4
        rowPattern / patternTerm / patternPrimary)."""
        alts = [self._row_pattern_concat()]
        while self.accept_op("|"):
            alts.append(self._row_pattern_concat())
        if len(alts) == 1:
            return alts[0]
        return t.PatternAlternation(alternatives=tuple(alts))

    def _row_pattern_concat(self) -> t.Node:
        elems = [self._row_pattern_quantified()]
        while (
            self.peek().type in (TokenType.IDENT, TokenType.QUOTED_IDENT)
            or self.at_op("(")
        ):
            elems.append(self._row_pattern_quantified())
        if len(elems) == 1:
            return elems[0]
        return t.PatternConcatenation(elements=tuple(elems))

    def _row_pattern_quantified(self) -> t.Node:
        if self.accept_op("("):
            elem: t.Node = self._row_pattern()
            self.expect_op(")")
        else:
            elem = t.PatternVariable(name=self.identifier())
        lo: Optional[int] = None
        hi: Optional[int] = None
        if self.accept_op("*"):
            lo, hi = 0, None
        elif self.accept_op("+"):
            lo, hi = 1, None
        elif self.accept_op("?"):
            lo, hi = 0, 1
        elif self.accept_op("{"):
            if self.accept_op(","):
                lo = 0
                hi = int(self.advance().value)
            else:
                lo = int(self.advance().value)
                if self.accept_op(","):
                    hi = None if self.at_op("}") else int(self.advance().value)
                else:
                    hi = lo
            self.expect_op("}")
        if lo is None:
            return elem
        greedy = not self.accept_op("?")
        return t.PatternQuantified(element=elem, min=lo, max=hi, greedy=greedy)

    def _relation_primary(self) -> t.Relation:
        if self.accept_keyword("LATERAL"):
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return t.Lateral(query=q)
        if self.accept_keyword("UNNEST"):
            self.expect_op("(")
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            with_ord = False
            if self.accept_keyword("WITH"):
                self.expect_keyword("ORDINALITY")
                with_ord = True
            return t.Unnest(expressions=tuple(exprs), with_ordinality=with_ord)
        if (
            self.at_keyword("TABLE")
            and self.peek(1).type == TokenType.OP
            and self.peek(1).value == "("
        ):
            # table function invocation: TABLE(sequence(1, 10)) or the
            # polymorphic form TABLE(exclude_columns(input => TABLE(orders),
            # columns => DESCRIPTOR(o_comment)))
            self.advance()
            self.expect_op("(")
            name = self.qualified_name()
            self.expect_op("(")
            args: List[t.Expression] = []
            named: List[tuple] = []

            def tf_argument():
                if self.at_keyword("TABLE"):
                    self.advance()
                    self.expect_op("(")
                    if self.at_keyword("SELECT", "WITH", "VALUES"):
                        rel = t.TableSubquery(query=self.parse_query())
                    else:
                        rel = t.Table(name=self.qualified_name())
                    self.expect_op(")")
                    return rel
                if (
                    self.at_keyword("DESCRIPTOR")
                    or (
                        self.peek().type == TokenType.IDENT
                        and self.peek().value.lower() == "descriptor"
                        and self.peek(1).type == TokenType.OP
                        and self.peek(1).value == "("
                    )
                ):
                    self.advance()
                    self.expect_op("(")
                    cols = [self.identifier()]
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                    return t.Descriptor(columns=tuple(str(c).lower() for c in cols))
                return self.expression()

            if not self.at_op(")"):
                while True:
                    if (
                        self.peek().type
                        in (TokenType.IDENT, TokenType.QUOTED_IDENT, TokenType.KEYWORD)
                        and self.peek(1).type == TokenType.OP
                        and self.peek(1).value == "=>"
                    ):
                        arg_name = str(self.identifier()).lower()
                        self.expect_op("=>")
                        named.append((arg_name, tf_argument()))
                    else:
                        args.append(tf_argument())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            self.expect_op(")")
            return t.TableFunctionRelation(
                name=str(name).lower(), args=tuple(args), named_args=tuple(named)
            )
        if self.accept_op("("):
            # subquery or parenthesized relation
            if self.at_keyword("SELECT", "WITH", "VALUES", "TABLE"):
                q = self.parse_query()
                self.expect_op(")")
                return t.TableSubquery(query=q)
            if self.at_op("("):
                # ambiguous: "((" starts either a nested subquery or a
                # parenthesized JOIN chain like ((a JOIN b) JOIN c) —
                # backtrack on failure (SqlBase.g4 resolves via
                # aliasedRelation | subquery alternatives)
                saved = self.pos
                try:
                    q = self.parse_query()
                    self.expect_op(")")
                    return t.TableSubquery(query=q)
                except ParseError:
                    self.pos = saved
            rel = self._relation()
            self.expect_op(")")
            return rel
        name = self.qualified_name()
        version = None
        if (
            self.at_keyword("FOR")
            and self.peek(1).type == TokenType.IDENT
            and self.peek(1).value == "version"
        ):
            # FOR VERSION AS OF <n> (time travel; ref: SqlBase.g4 queryPeriod)
            self.advance()  # FOR
            self.advance()  # version (plain identifier; not in KEYWORDS)
            self.expect_keyword("AS")
            ident = self.identifier()
            if ident != "of":
                raise ParseError(f"expected OF in FOR VERSION AS OF, found {ident!r}")
            tok = self.peek()
            if tok.type != TokenType.INTEGER:
                raise ParseError(f"FOR VERSION AS OF expects an integer at {tok.pos}")
            self.advance()
            version = int(tok.value)
        return t.Table(name=name, version=version)

    # ------------------------------------------------------------ expressions

    def expression(self) -> t.Expression:
        return self._or_expr()

    def _or_expr(self) -> t.Expression:
        terms = [self._and_expr()]
        while self.accept_keyword("OR"):
            terms.append(self._and_expr())
        return terms[0] if len(terms) == 1 else t.Logical("OR", tuple(terms))

    def _and_expr(self) -> t.Expression:
        terms = [self._not_expr()]
        while self.accept_keyword("AND"):
            terms.append(self._not_expr())
        return terms[0] if len(terms) == 1 else t.Logical("AND", tuple(terms))

    def _not_expr(self) -> t.Expression:
        if self.accept_keyword("NOT"):
            return t.Not(self._not_expr())
        return self._predicate()

    def _predicate(self) -> t.Expression:
        expr = self._value_expr()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op_text = self.advance().value
                if op_text == "!=":
                    op_text = "<>"
                right = self._value_expr()
                expr = t.Comparison(t.ComparisonOp(op_text), expr, right)
                continue
            if self.at_keyword("IS"):
                self.advance()
                negated = self.accept_keyword("NOT")
                if self.accept_keyword("NULL"):
                    expr = t.IsNotNull(expr) if negated else t.IsNull(expr)
                elif self.accept_keyword("DISTINCT"):
                    self.expect_keyword("FROM")
                    right = self._value_expr()
                    cmp = t.Comparison(t.ComparisonOp.IS_DISTINCT_FROM, expr, right)
                    expr = t.Not(cmp) if negated else cmp
                elif self.at_keyword("TRUE", "FALSE"):
                    val = self.advance().value == "TRUE"
                    cmp = t.Comparison(t.ComparisonOp.EQUAL, expr, t.BooleanLiteral(val))
                    # IS TRUE: null -> false (differs from = NULL semantics); round 1
                    # approximates with coalesce at analysis time.
                    expr = t.Not(cmp) if negated else cmp
                else:
                    raise ParseError(f"unsupported IS predicate at {self.peek().pos}")
                continue
            negated = False
            save = self.pos
            if self.accept_keyword("NOT"):
                negated = True
            if self.accept_keyword("BETWEEN"):
                lo = self._value_expr()
                self.expect_keyword("AND")
                hi = self._value_expr()
                expr = t.Between(expr, lo, hi, negated=negated)
                continue
            if self.accept_keyword("IN"):
                self.expect_op("(")
                if self.at_keyword("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    expr = t.InSubquery(expr, q, negated=negated)
                else:
                    items = [self.expression()]
                    while self.accept_op(","):
                        items.append(self.expression())
                    self.expect_op(")")
                    expr = t.InList(expr, tuple(items), negated=negated)
                continue
            if self.accept_keyword("LIKE"):
                pattern = self._value_expr()
                escape = None
                if self.accept_keyword("ESCAPE"):
                    escape = self._value_expr()
                expr = t.Like(expr, pattern, escape=escape, negated=negated)
                continue
            if negated:
                self.pos = save
            break
        return expr

    def _value_expr(self) -> t.Expression:
        return self._additive()

    def _additive(self) -> t.Expression:
        expr = self._multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.advance().value
                right = self._multiplicative()
                aop = t.ArithmeticOp.ADD if op == "+" else t.ArithmeticOp.SUBTRACT
                expr = t.ArithmeticBinary(aop, expr, right)
            elif self.at_op("||"):
                self.advance()
                right = self._multiplicative()
                expr = t.FunctionCall(t.QualifiedName(("concat",)), (expr, right))
            else:
                return expr

    def _multiplicative(self) -> t.Expression:
        expr = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.advance().value
            right = self._unary()
            aop = {
                "*": t.ArithmeticOp.MULTIPLY,
                "/": t.ArithmeticOp.DIVIDE,
                "%": t.ArithmeticOp.MODULUS,
            }[op]
            expr = t.ArithmeticBinary(aop, expr, right)
        return expr

    def _unary(self) -> t.Expression:
        if self.at_op("-"):
            self.advance()
            return t.ArithmeticUnary("-", self._unary())
        if self.at_op("+"):
            self.advance()
            return self._unary()
        expr = self._primary()
        while self.at_op("["):  # postfix subscript: a[1], m['k'], nested a[1][2]
            self.advance()
            idx = self.expression()
            self.expect_op("]")
            expr = t.Subscript(base=expr, index=idx)
        return expr

    def _primary(self) -> t.Expression:
        tok = self.peek()
        # literals
        if tok.type == TokenType.INTEGER:
            self.advance()
            return t.LongLiteral(int(tok.value))
        if tok.type == TokenType.DECIMAL:
            self.advance()
            return t.DecimalLiteral(tok.value)
        if tok.type == TokenType.FLOAT:
            self.advance()
            return t.DoubleLiteral(float(tok.value))
        if tok.type == TokenType.STRING:
            self.advance()
            return t.StringLiteral(tok.value)
        if self.at_keyword("TRUE"):
            self.advance()
            return t.BooleanLiteral(True)
        if self.at_keyword("FALSE"):
            self.advance()
            return t.BooleanLiteral(False)
        if self.at_keyword("NULL"):
            self.advance()
            return t.NullLiteral()
        if self.at_keyword("DATE") and self.peek(1).type == TokenType.STRING:
            self.advance()
            return t.DateLiteral(self.advance().value)
        if (
            (self.at_keyword("DECIMAL")
             or (tok.type == TokenType.IDENT and tok.value.lower() == "decimal"))
            and self.peek(1).type == TokenType.STRING
        ):
            # DECIMAL 'x.y' typed literal (SqlBase.g4 typeConstructor)
            self.advance()
            text = self.advance().value
            return t.DecimalLiteral(text=text)
        if self.at_keyword("TIMESTAMP") and self.peek(1).type == TokenType.STRING:
            self.advance()
            return t.TimestampLiteral(self.advance().value)
        if self.at_keyword("TIME") and self.peek(1).type == TokenType.STRING:
            self.advance()
            return t.TimeLiteral(self.advance().value)
        if self.at_keyword("INTERVAL"):
            self.advance()
            sign = 1
            if self.accept_op("-"):
                sign = -1
            else:
                self.accept_op("+")
            value = self.advance().value  # string literal
            unit = self.advance().value.lower()
            return t.IntervalLiteral(value=value, unit=unit, sign=sign)
        if self.at_keyword("CURRENT_DATE"):
            self.advance()
            return t.CurrentDate()
        if self.at_keyword("GROUPING") and self.peek(1).value == "(":
            # GROUPING(key, ...) — grouping-set membership bitmask
            # (sql/tree/GroupingOperation.java); folded per UNION branch by
            # the grouping-sets rewrite
            self.advance()
            self.expect_op("(")
            gargs = [self.expression()]
            while self.accept_op(","):
                gargs.append(self.expression())
            self.expect_op(")")
            return t.FunctionCall(t.QualifiedName(("grouping",)), tuple(gargs))
        if self.at_keyword("CASE"):
            return self._case()
        if self.at_keyword("CAST", "TRY_CAST"):
            safe = tok.value == "TRY_CAST"
            self.advance()
            self.expect_op("(")
            value = self.expression()
            self.expect_keyword("AS")
            type_name = self._type_name()
            self.expect_op(")")
            return t.Cast(value=value, type_name=type_name, safe=safe)
        if self.at_keyword("EXTRACT"):
            self.advance()
            self.expect_op("(")
            field_tok = self.advance().value
            self.expect_keyword("FROM")
            value = self.expression()
            self.expect_op(")")
            return t.Extract(field_name=field_tok.upper(), value=value)
        if self.at_keyword("SUBSTRING"):
            # SUBSTRING(x FROM start [FOR length]) — also accepts function form
            self.advance()
            self.expect_op("(")
            value = self.expression()
            if self.accept_keyword("FROM"):
                start = self.expression()
                args = [value, start]
                if self.accept_keyword("FOR"):
                    args.append(self.expression())
                self.expect_op(")")
                return t.FunctionCall(t.QualifiedName(("substring",)), tuple(args))
            args = [value]
            while self.accept_op(","):
                args.append(self.expression())
            self.expect_op(")")
            return t.FunctionCall(t.QualifiedName(("substring",)), tuple(args))
        if self.at_keyword("EXISTS"):
            self.advance()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return t.Exists(query=q)
        if (
            tok.type in (TokenType.IDENT, TokenType.KEYWORD)
            and tok.value.upper() == "ARRAY"
            and self.peek(1).type == TokenType.OP
            and self.peek(1).value == "["
        ):
            self.advance()
            self.expect_op("[")
            items = []
            if not self.at_op("]"):
                items.append(self.expression())
                while self.accept_op(","):
                    items.append(self.expression())
            self.expect_op("]")
            return t.Array(items=tuple(items))
        if self.at_keyword("ROW"):
            self.advance()
            self.expect_op("(")
            items = [self.expression()]
            while self.accept_op(","):
                items.append(self.expression())
            self.expect_op(")")
            return t.Row(items=tuple(items))
        if self.at_op("(") and self._lambda_ahead():
            # (x, y) -> body
            self.expect_op("(")
            params = [self.identifier()]
            while self.accept_op(","):
                params.append(self.identifier())
            self.expect_op(")")
            self.expect_op("->")
            return t.Lambda(params=tuple(params), body=self.expression())
        if self.accept_op("("):
            if self.at_keyword("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return t.ScalarSubquery(query=q)
            expr = self.expression()
            if self.at_op(","):
                items = [expr]
                while self.accept_op(","):
                    items.append(self.expression())
                self.expect_op(")")
                return t.Row(items=tuple(items))
            self.expect_op(")")
            return expr
        if self.at_op("?"):
            self.advance()
            idx = self._param_count
            self._param_count += 1
            return t.Parameter(index=idx)
        # function call or column reference
        if tok.type in (TokenType.IDENT, TokenType.QUOTED_IDENT) or (
            tok.type == TokenType.KEYWORD and tok.value in NON_RESERVED
        ):
            if (
                self.peek(1).type == TokenType.OP
                and self.peek(1).value == "->"
            ):
                # x -> body
                param = self.identifier()
                self.expect_op("->")
                return t.Lambda(params=(param,), body=self.expression())
            qn = self.qualified_name()
            if self.at_op("("):
                return self._function_call(qn)
            # column reference: a or a.b.c -> Dereference chain
            expr: t.Expression = t.Identifier(qn.parts[0])
            for part in qn.parts[1:]:
                expr = t.Dereference(expr, part)
            return expr
        raise ParseError(f"unexpected token {tok.value!r} at {tok.pos}")

    def _lambda_ahead(self) -> bool:
        """Lookahead for ``( ident [, ident]* ) ->`` from an opening paren."""
        i = 1
        expect_ident = True
        while True:
            tok = self.peek(i)
            if expect_ident:
                # same token classes identifier() accepts (incl. non-reserved
                # keywords like day/position as parameter names)
                if tok.type not in (TokenType.IDENT, TokenType.QUOTED_IDENT) and not (
                    tok.type == TokenType.KEYWORD and tok.value in NON_RESERVED
                ):
                    return False
                expect_ident = False
            else:
                if tok.type != TokenType.OP:
                    return False
                if tok.value == ",":
                    expect_ident = True
                elif tok.value == ")":
                    nxt = self.peek(i + 1)
                    return nxt.type == TokenType.OP and nxt.value == "->"
                else:
                    return False
            i += 1

    def _case(self) -> t.Expression:
        self.expect_keyword("CASE")
        operand = None
        if not self.at_keyword("WHEN"):
            operand = self.expression()
        whens = []
        while self.accept_keyword("WHEN"):
            cond = self.expression()
            self.expect_keyword("THEN")
            result = self.expression()
            whens.append(t.WhenClause(cond, result))
        default = None
        if self.accept_keyword("ELSE"):
            default = self.expression()
        self.expect_keyword("END")
        if operand is not None:
            return t.SimpleCase(operand=operand, when_clauses=tuple(whens), default=default)
        return t.SearchedCase(when_clauses=tuple(whens), default=default)

    def _function_call(self, name: t.QualifiedName) -> t.Expression:
        self.expect_op("(")
        distinct = False
        is_star = False
        args: List[t.Expression] = []
        if self.accept_op("*"):
            is_star = True
        elif not self.at_op(")"):
            if self.accept_keyword("DISTINCT"):
                distinct = True
            else:
                self.accept_keyword("ALL")
            args.append(self.expression())
            while self.accept_op(","):
                args.append(self.expression())
        order_by: List[t.SortItem] = []
        if self.accept_keyword("ORDER"):
            # aggregate ordering: array_agg(x ORDER BY y DESC)
            self.expect_keyword("BY")
            order_by.append(self._sort_item())
            while self.accept_op(","):
                order_by.append(self._sort_item())
        self.expect_op(")")
        if self.accept_keyword("WITHIN"):
            # listagg(x, sep) WITHIN GROUP (ORDER BY y)
            self.expect_keyword("GROUP")
            self.expect_op("(")
            self.expect_keyword("ORDER")
            self.expect_keyword("BY")
            order_by.append(self._sort_item())
            while self.accept_op(","):
                order_by.append(self._sort_item())
            self.expect_op(")")
        filter_expr = None
        if self.at_keyword("FILTER"):
            self.advance()
            self.expect_op("(")
            self.expect_keyword("WHERE")
            filter_expr = self.expression()
            self.expect_op(")")
        null_treatment = None
        if self.accept_keyword("IGNORE"):
            self.expect_keyword("NULLS")
            null_treatment = "IGNORE"
        elif self.accept_keyword("RESPECT"):
            self.expect_keyword("NULLS")
            null_treatment = "RESPECT"
        window = None
        if self.accept_keyword("OVER"):
            window = self._window_spec()
        return t.FunctionCall(
            name=name,
            args=tuple(args),
            distinct=distinct,
            is_star=is_star,
            filter=filter_expr,
            window=window,
            order_by=tuple(order_by),
            null_treatment=null_treatment,
        )

    def _window_spec(self) -> t.WindowSpec:
        self.expect_op("(")
        partition_by: List[t.Expression] = []
        order_by: List[t.SortItem] = []
        frame = None
        if self.accept_keyword("PARTITION"):
            self.expect_keyword("BY")
            partition_by.append(self.expression())
            while self.accept_op(","):
                partition_by.append(self.expression())
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            order_by.append(self._sort_item())
            while self.accept_op(","):
                order_by.append(self._sort_item())
        if self.at_keyword("ROWS", "RANGE"):
            type_ = self.advance().value.upper()
            pos = self.peek().pos
            if self.accept_keyword("BETWEEN"):
                start_kind, start_value = self._frame_bound()
                self.expect_keyword("AND")
                end_kind, end_value = self._frame_bound()
            else:
                start_kind, start_value = self._frame_bound()
                end_kind, end_value = "CURRENT_ROW", None
                if start_kind in ("FOLLOWING", "UNBOUNDED_FOLLOWING"):
                    raise ParseError(
                        f"frame start cannot be FOLLOWING without BETWEEN at {pos}"
                    )
            # bound ordering (ref: WindowFrame validation in the analyzer):
            # start must not come after end in the kind ordering
            order = {
                "UNBOUNDED_PRECEDING": 0, "PRECEDING": 1, "CURRENT_ROW": 2,
                "FOLLOWING": 3, "UNBOUNDED_FOLLOWING": 4,
            }
            if (
                start_kind == "UNBOUNDED_FOLLOWING"
                or end_kind == "UNBOUNDED_PRECEDING"
                or order[start_kind] > order[end_kind]
            ):
                raise ParseError(f"invalid window frame bounds at {pos}")
            frame = t.WindowFrame(
                type_=type_,
                start_kind=start_kind,
                end_kind=end_kind,
                start_value=start_value,
                end_value=end_value,
            )
        self.expect_op(")")
        return t.WindowSpec(
            partition_by=tuple(partition_by), order_by=tuple(order_by), frame=frame
        )

    def _frame_bound(self):
        """UNBOUNDED PRECEDING/FOLLOWING | CURRENT ROW | <n> PRECEDING/FOLLOWING."""
        if self.accept_keyword("UNBOUNDED"):
            if self.accept_keyword("PRECEDING"):
                return "UNBOUNDED_PRECEDING", None
            self.expect_keyword("FOLLOWING")
            return "UNBOUNDED_FOLLOWING", None
        if self.accept_keyword("CURRENT"):
            self.expect_keyword("ROW")
            return "CURRENT_ROW", None
        if self.accept_keyword("INTERVAL"):
            # INTERVAL 'n' DAY bounds for date-ordered RANGE frames
            tk = self.advance()
            if tk.type != TokenType.STRING:
                raise ParseError(f"expected interval literal at {tk.pos}")
            value = int(tk.value)
            unit = self.advance().value.upper()
            if unit == "DAY":
                pass
            elif unit in ("MONTH", "YEAR"):
                raise ParseError(
                    f"only DAY intervals are supported in frame bounds at {tk.pos}"
                )
            else:
                raise ParseError(f"unexpected interval unit at {tk.pos}")
        else:
            tk = self.advance()
            if tk.type == TokenType.INTEGER:
                value = int(tk.value)
            elif tk.type in (TokenType.DECIMAL, TokenType.FLOAT):
                value = float(tk.value)
            else:
                raise ParseError(f"expected frame bound at {tk.pos}")
        if self.accept_keyword("PRECEDING"):
            return "PRECEDING", value
        self.expect_keyword("FOLLOWING")
        return "FOLLOWING", value

    def _type_name(self) -> str:
        base = self.advance().value.lower()
        if base == "double" and self.at_keyword():  # DOUBLE PRECISION
            if self.peek().value == "PRECISION":
                self.advance()
        text = base
        if self.accept_op("("):
            args = [self.advance().value]
            while self.accept_op(","):
                args.append(self.advance().value)
            self.expect_op(")")
            text = f"{base}({','.join(args)})"
        if (
            base in ("timestamp", "time")
            and self.at_keyword("WITH")
            and self.peek(1).value.upper() == "TIME"
            and self.peek(2).value.upper() == "ZONE"
        ):
            self.advance()
            self.advance()
            self.advance()
            text += " with time zone"
        return text


def parse_statement(sql: str) -> t.Statement:
    """Entry point (ref: parser/SqlParser.java:104 createStatement)."""
    return Parser(sql).parse_statement()


def parse_expression(sql: str) -> t.Expression:
    p = Parser(sql)
    expr = p.expression()
    if p.peek().type != TokenType.EOF:
        raise ParseError(f"unexpected trailing input at {p.peek().pos}")
    return expr
