"""Multi-format file connector: ORC, CSV, and newline-delimited JSON tables.

Reference blueprint: lib/trino-orc (OrcReader.java:67 — stripe-granular
reading, createRecordReader:252), lib/trino-hive-formats (text/CSV/JSON line
codecs), and plugin/trino-hive's directory-per-table layout. Layout:
``root/<table>/*.{orc,csv,json}``; one catalog = one format.

Split granularity follows each format's natural unit, like the reference:
ORC splits one stripe at a time (the reference's stripe/rowgroup pruning
unit); CSV/JSON split per file (line formats have no internal index). Arrow
does the host-side decode (declared delegation, connectors/arrow_ingest.py);
everything above — splits, dictionaries, pages, pushdown — is this engine's.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Dictionary, Page
from ..spi.predicate import TupleDomain
from .arrow_ingest import arrow_table_to_page, arrow_to_type

_EXT = {"orc": ".orc", "csv": ".csv", "json": ".json"}


class FileFormatConnector(Connector):
    """``root/<table>/*.<format>`` as a catalog schema (orc | csv | json)."""

    def __init__(self, root: str, format: str, schema: str = "default"):
        if format not in _EXT:
            raise ValueError(f"unsupported file format: {format}")
        self.root = root
        self.format = format
        self.schema = schema
        self.name = format
        self._meta = _Metadata(self)
        self._splits = _Splits(self)
        self._pages = _Pages(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    def table_files(self, table: str) -> List[str]:
        d = os.path.join(self.root, table)
        if not os.path.isdir(d):
            return []
        ext = _EXT[self.format]
        return sorted(os.path.join(d, f) for f in os.listdir(d) if f.endswith(ext))

    # ------------------------------------------------------------- decoding

    def read_split(self, path: str, part: int):
        """One split's rows as an Arrow table (ORC: one stripe; text: file)."""
        if self.format == "orc":
            import pyarrow as pa
            import pyarrow.orc as orc

            # read_stripe yields a RecordBatch; normalize to a Table so the
            # shared ingest sees one chunked-array interface
            return pa.Table.from_batches([orc.ORCFile(path).read_stripe(part)])
        if self.format == "csv":
            import pyarrow.csv as pacsv

            return pacsv.read_csv(path)
        import pyarrow.json as pajson

        return pajson.read_json(path)

    def file_schema(self, path: str):
        if self.format == "orc":
            import pyarrow.orc as orc

            return orc.ORCFile(path).schema
        return self.read_split(path, 0).schema

    def split_parts(self, path: str) -> int:
        if self.format == "orc":
            import pyarrow.orc as orc

            return max(orc.ORCFile(path).nstripes, 1)
        return 1

    def file_rows(self, path: str) -> int:
        if self.format == "orc":
            import pyarrow.orc as orc

            return orc.ORCFile(path).nrows
        return self.read_split(path, 0).num_rows


class _Metadata(ConnectorMetadata):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector

    def list_schemas(self) -> List[str]:
        return [self.connector.schema]

    def list_tables(self, schema: Optional[str] = None):
        root = self.connector.root
        tables = [
            t
            for t in (sorted(os.listdir(root)) if os.path.isdir(root) else [])
            if self.connector.table_files(t)
        ]
        return [SchemaTableName(self.connector.schema, t) for t in tables]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        files = self.connector.table_files(name.table)
        if not files:
            return None
        schema = self.connector.file_schema(files[0])
        cols = []
        for field in schema:
            t = arrow_to_type(field)
            if t is not None:
                cols.append(ColumnMetadata(field.name, t))
        return TableMetadata(name, tuple(cols))

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        rows = sum(
            self.connector.file_rows(f)
            for f in self.connector.table_files(handle.schema_table.table)
        )
        return TableStatistics(row_count=float(rows))

    def apply_filter(self, handle: TableHandle, domain: TupleDomain):
        return TableHandle(handle.catalog, handle.schema_table, connector_handle=domain)


class _Splits(ConnectorSplitManager):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        parts = [
            (path, part)
            for path in self.connector.table_files(handle.schema_table.table)
            for part in range(self.connector.split_parts(path))
        ]
        return [
            Split(handle, sid, len(parts), info=p) for sid, p in enumerate(parts)
        ]


class _Pages(ConnectorPageSourceProvider):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector
        self._dicts: Dict[tuple, Dictionary] = {}

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        path, part = split.info
        meta = self.connector.metadata().get_table_metadata(split.table.schema_table)
        wanted = [meta.columns[i] for i in column_indexes]
        table = self.connector.read_split(path, part)
        # text formats may infer a wider schema per file; select by name
        table = table.select([c.name for c in wanted])
        return arrow_table_to_page(table, wanted, self._dicts, (path, part))
