"""Host-path observability plane: sampling profiler + GIL-contention probe.

ROADMAP item 4's measurement half: every device-side plane is instrumented
(flight spans, cluster traces, kernel-cost rooflines) but the host/protocol
path — the single-process coordinator front the r13 saturation replay blamed
for p99@16c — had no instrument at all. This module turns "single-core
host/GIL contention" from a hand diagnosis into three measurements:

- ``HostProfiler``: a continuous wall-clock sampling profiler. A daemon
  sampler thread walks ``sys._current_frames()`` every
  ``$TRINO_TPU_HOSTPROF_INTERVAL_MS`` (default 19ms — co-prime with common
  10/20/100ms periodic work so the sampler doesn't alias against it) and
  appends one collapsed stack per engine thread to a bounded ring
  (``$TRINO_TPU_HOSTPROF_RING`` samples; overflow counted, never blocking).
  Exports: folded collapsed-stack text (flamegraph.pl style), speedscope
  JSON (``speedscope()``, schema-checked by ``validate_speedscope``), and a
  Perfetto lane — sampler ticks land in the flight recorder on the
  ``hostprof-sampler`` thread, so the round-17 deterministic-tid contract
  (clusterobs.canonicalize_trace keys lanes on thread NAMES) merges the
  profiler into cluster traces with zero new plumbing. Default OFF: the
  off path starts no thread, touches no registry, and query results are
  byte-identical (tests/test_hostprof.py asserts it poisoning-style).

- Protocol-phase spans: ``phase_span(...)`` names the
  accept → auth/verify → parse → queue → admit → execute-dispatch →
  result-stream request phases uniformly (category ``protocol``) so a slow
  request decomposes into host scheduling vs device work in the same trace
  UI as everything else.

- ``ContentionProbe``: GIL/scheduler contention as expected-vs-actual sleep
  jitter. A probe thread sleeps a short fixed interval and records how late
  the wakeup was — under a GIL hogged by one runnable thread the lateness
  is the switch interval (default 5ms), not the scheduler's microseconds.
  Jitter feeds ``trino_tpu_host_switch_latency_secs``; the sampler's
  runnable/blocked classification feeds ``trino_tpu_host_threads{state=}``.
  Both ride ``/v1/metrics`` and the announcement metric snapshot into the
  federated cluster tables for free.

``system.runtime.host_profile`` (connectors/system.py) serves the live
collapsed-stack aggregation; ``bench.py hostpath_ab`` is the capstone
consumer (BENCH_r19_hostpath_ab.json).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import knobs

# thread states the sampler distinguishes (gauge label values)
THREAD_STATES = ("runnable", "blocked")

# leaf frame names that mean "off-CPU, waiting" — a thread parked in one of
# these is blocked (not competing for the GIL); anything else is runnable.
# Python-level sampling cannot see C-level blocking beyond the stdlib's
# named wait points, so the split is approximate but stable.
_WAIT_LEAVES = frozenset({
    "wait", "wait_for", "sleep", "select", "poll", "epoll", "accept",
    "acquire", "recv", "recv_into", "read", "readinto", "readline",
    "get", "join", "getaddrinfo", "connect", "settrace", "park",
    "serve_forever", "handle_request", "_handle_request_noblock",
})

# the request phases phase_span names; kept ordered for docs/tests.
# "route"/"proxy" are the coordinator-fleet additions (runtime/fleet.py):
# ownership hashing + non-owner forwarding cost is attributed, not hidden
PROTOCOL_PHASES = (
    "accept", "auth", "verify", "parse", "route", "proxy", "queue",
    "admit", "execute", "result_stream", "dispatch",
)


def phase_span(recorder, phase: str, **args):
    """The protocol-phase span: ``with phase_span(RECORDER, "auth"): ...``.

    One naming scheme (``proto_<phase>``, category ``protocol``) across the
    coordinator and worker so trace tooling and the hostpath bench can
    select the host/protocol side of a request with a single prefix. The
    recorder's own ``enabled`` guard makes this free when recording is off.
    """
    if phase not in PROTOCOL_PHASES:
        raise ValueError(f"unknown protocol phase: {phase!r}")
    return recorder.span(f"proto_{phase}", "protocol", **args)


def _interval_secs() -> float:
    """Sampling interval: $TRINO_TPU_HOSTPROF_INTERVAL_MS, floored at 1ms
    (a sub-millisecond Python sampler would measure mostly itself)."""
    ms = knobs.env_float("TRINO_TPU_HOSTPROF_INTERVAL_MS", 19.0)
    return max(ms, 1.0) / 1000.0


def _ring_capacity() -> int:
    """Sample-ring capacity: $TRINO_TPU_HOSTPROF_RING (per-thread samples),
    floored at 16 like the flight ring."""
    return max(knobs.env_int("TRINO_TPU_HOSTPROF_RING", 4096), 16)


def _frame_label(frame) -> str:
    code = frame.f_code
    filename = code.co_filename.rsplit("/", 1)[-1]
    return f"{code.co_name} ({filename}:{code.co_firstlineno})"


def _collapse(frame) -> Tuple[Tuple[str, ...], str]:
    """(root..leaf frame labels, leaf co_name) of one thread's live stack."""
    labels: List[str] = []
    leaf = ""
    f = frame
    while f is not None:
        labels.append(_frame_label(f))
        f = f.f_back
    labels.reverse()
    if frame is not None:
        leaf = frame.f_code.co_name
    return tuple(labels), leaf


class HostProfiler:
    """Continuous wall-clock sampling profiler over the process's threads.

    Enable/refcount semantics mirror the flight recorder: ``enable()`` /
    ``disable()`` for manual control (servers, tools), ``acquire()`` /
    ``release()`` for scoped users (the ``host_profile`` session property) —
    the sampler thread runs while anyone wants it and exits when the last
    user leaves. The ring never blocks the sampled threads: sampling reads
    interpreter state only (``sys._current_frames``), writes only its own
    deque, and skips its own thread and the probe thread.
    """

    SAMPLER_THREAD_NAME = "hostprof-sampler"

    def __init__(self, interval_secs: Optional[float] = None,
                 capacity: Optional[int] = None):
        self._interval = interval_secs
        self._capacity = capacity
        self.enabled = False  # plain attribute, same contract as RECORDER
        self._lock = threading.Lock()
        # ring of (ts_us, thread_name, (frame labels root..leaf))
        self._buf: deque = deque(maxlen=capacity or _ring_capacity())
        self.dropped_samples = 0
        self.tick_count = 0
        self._manual = False
        self._refs = 0
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # ------------------------------------------------------------- control

    def _recompute_locked(self) -> None:
        want = self._manual or self._refs > 0
        self.enabled = want
        if want and (self._thread is None or not self._thread.is_alive()):
            self._wake.clear()
            self._thread = threading.Thread(
                target=self._sample_loop, daemon=True,
                name=self.SAMPLER_THREAD_NAME,
            )
            self._thread.start()
        elif not want:
            self._wake.set()  # sampler exits at its next tick

    def enable(self) -> None:
        with self._lock:
            self._manual = True
            self._recompute_locked()

    def disable(self) -> None:
        with self._lock:
            self._manual = False
            self._recompute_locked()

    def acquire(self) -> None:
        """Scoped enable (refcounted): pair with release()."""
        with self._lock:
            self._refs += 1
            self._recompute_locked()

    def release(self) -> None:
        with self._lock:
            self._refs = max(0, self._refs - 1)
            self._recompute_locked()

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped_samples = 0
            self.tick_count = 0

    def join(self, timeout: float = 2.0) -> None:
        """Wait for the sampler thread to exit (tests; disable() first)."""
        t = self._thread
        if t is not None:
            t.join(timeout)

    # ------------------------------------------------------------ sampling

    def _sample_loop(self) -> None:
        interval = (
            self._interval if self._interval is not None else _interval_secs()
        )
        me = threading.get_ident()
        while self.enabled:
            self._sample_once(me)
            # Event.wait instead of sleep: disable() wakes the thread so a
            # released profiler stops sampling immediately, not a tick later
            if self._wake.wait(interval):
                break

    def _sample_once(self, skip_ident: int) -> None:
        ts_us = time.monotonic_ns() // 1000
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        runnable = blocked = 0
        samples: List[tuple] = []
        for ident, frame in frames.items():
            if ident == skip_ident:
                continue
            name = names.get(ident)
            if name is None or name == ContentionProbe.PROBE_THREAD_NAME:
                continue
            labels, leaf = _collapse(frame)
            if leaf in _WAIT_LEAVES:
                blocked += 1
            else:
                runnable += 1
                samples.append((ts_us, name, labels))
        dropped = 0
        with self._lock:
            self.tick_count += 1
            for s in samples:
                if len(self._buf) == self._buf.maxlen:
                    self.dropped_samples += 1
                    dropped += 1
                self._buf.append(s)
        update_thread_gauges(runnable=runnable, blocked=blocked)
        if dropped:
            _metric_counter(
                "trino_tpu_hostprof_dropped_samples_total",
                "host-profiler samples pushed off the ring by overflow",
            ).inc(dropped)
        # Perfetto lane: the tick rides the flight ring on THIS thread, so
        # the cluster-trace assembly and canonicalize_trace give the
        # profiler a deterministic "hostprof-sampler" lane for free
        from .observability import RECORDER

        if RECORDER.enabled:
            RECORDER.counter_event(
                "host_threads", "hostprof",
                runnable=runnable, blocked=blocked,
            )
            for _ts, name, labels in samples:
                RECORDER.instant(
                    "host_sample", "hostprof",
                    thread=name, stack=";".join(labels),
                )

    # -------------------------------------------------------------- export

    def samples(self) -> List[tuple]:
        with self._lock:
            return list(self._buf)

    def collapsed(self) -> Dict[str, int]:
        """``"<thread>;<root>;...;<leaf>" -> sample count`` aggregation of
        the current ring (the folded flamegraph key space, thread-rooted)."""
        agg: Dict[str, int] = {}
        for _ts, name, labels in self.samples():
            key = ";".join((name,) + labels)
            agg[key] = agg.get(key, 0) + 1
        return agg

    def collapsed_text(self) -> str:
        """flamegraph.pl folded format, sorted for deterministic output."""
        agg = self.collapsed()
        return "\n".join(f"{k} {n}" for k, n in sorted(agg.items()))

    def speedscope(self, name: str = "trino-tpu host profile") -> dict:
        """The ring as a speedscope 'sampled' document — one profile per
        thread name, frames deduplicated in the shared table, every sample
        weight 1 (wall-clock sampling at a fixed interval). Ordering is
        deterministic: frames and profiles sort on their labels."""
        by_thread: Dict[str, List[Tuple[str, ...]]] = {}
        for _ts, tname, labels in self.samples():
            by_thread.setdefault(tname, []).append(labels)
        frame_index: Dict[str, int] = {}
        all_labels = sorted({
            lab for stacks in by_thread.values() for s in stacks for lab in s
        })
        for lab in all_labels:
            frame_index[lab] = len(frame_index)
        profiles = []
        for tname in sorted(by_thread):
            stacks = by_thread[tname]
            profiles.append({
                "type": "sampled",
                "name": tname,
                "unit": "none",
                "startValue": 0,
                "endValue": len(stacks),
                "samples": [
                    [frame_index[lab] for lab in s] for s in stacks
                ],
                "weights": [1] * len(stacks),
            })
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": name,
            "exporter": "trino-tpu hostprof",
            "activeProfileIndex": 0,
            "shared": {"frames": [{"name": lab} for lab in all_labels]},
            "profiles": profiles,
        }

    def profile_rows(self) -> List[tuple]:
        """``system.runtime.host_profile`` rows: (thread, stack, samples,
        share) per collapsed stack, heaviest first, share within thread."""
        agg = self.collapsed()
        per_thread: Dict[str, int] = {}
        for key, n in agg.items():
            thread = key.split(";", 1)[0]
            per_thread[thread] = per_thread.get(thread, 0) + n
        rows = []
        for key, n in sorted(agg.items(), key=lambda kv: (-kv[1], kv[0])):
            thread, _, stack = key.partition(";")
            total = per_thread.get(thread, 0)
            rows.append((thread, stack, n, round(n / total, 4) if total else 0.0))
        return rows


def validate_speedscope(doc: dict) -> List[str]:
    """Minimal speedscope-schema validation, the collapsed-stack analogue of
    ``observability.validate_chrome_trace``: required top-level keys, a
    shared frame table of named frames, 'sampled' profiles whose sample
    frame indices are in range and whose weights align 1:1 with samples.
    Returns problems; [] = valid (the smoke check/--speedscope contract)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["not a JSON object"]
    if doc.get("$schema") != (
        "https://www.speedscope.app/file-format-schema.json"
    ):
        problems.append("missing/unknown $schema")
    shared = doc.get("shared")
    frames = shared.get("frames") if isinstance(shared, dict) else None
    if not isinstance(frames, list):
        problems.append("shared.frames missing")
        frames = []
    for i, fr in enumerate(frames):
        if not (isinstance(fr, dict) and isinstance(fr.get("name"), str)
                and fr["name"]):
            problems.append(f"frame {i} has no name")
    profiles = doc.get("profiles")
    if not isinstance(profiles, list) or not profiles:
        problems.append("profiles missing or empty")
        profiles = []
    for pi, prof in enumerate(profiles):
        if not isinstance(prof, dict):
            problems.append(f"profile {pi} not an object")
            continue
        if prof.get("type") != "sampled":
            problems.append(f"profile {pi} type != 'sampled'")
        if not isinstance(prof.get("name"), str):
            problems.append(f"profile {pi} missing name")
        if prof.get("unit") not in (
            "none", "nanoseconds", "microseconds", "milliseconds",
            "seconds", "bytes",
        ):
            problems.append(f"profile {pi} unknown unit {prof.get('unit')!r}")
        samples = prof.get("samples")
        weights = prof.get("weights")
        if not isinstance(samples, list) or not isinstance(weights, list):
            problems.append(f"profile {pi} missing samples/weights")
            continue
        if len(samples) != len(weights):
            problems.append(
                f"profile {pi} samples/weights length mismatch "
                f"({len(samples)} vs {len(weights)})"
            )
        for si, stack in enumerate(samples):
            if not isinstance(stack, list):
                problems.append(f"profile {pi} sample {si} not a list")
                continue
            for idx in stack:
                if not isinstance(idx, int) or not (0 <= idx < len(frames)):
                    problems.append(
                        f"profile {pi} sample {si} frame index {idx!r} "
                        "out of range"
                    )
    return problems


# --------------------------------------------------------------------------- #
# GIL/scheduler contention probe
# --------------------------------------------------------------------------- #


class ContentionProbe:
    """Switch-latency probe: measures how late a short timed sleep wakes up.

    The probe thread asks for ``interval_secs`` of sleep and records
    ``actual - expected`` (clamped at 0). On an idle interpreter the
    lateness is scheduler noise (tens of microseconds); when a runnable
    thread is hogging the GIL the sleeper cannot be rescheduled until the
    holder yields, so the lateness jumps toward the GIL switch interval
    (``sys.getswitchinterval()``, default 5ms) and beyond — the direct,
    per-process measurement of the r13 "host/GIL contention" claim. Jitter
    lands in a bounded ring and the
    ``trino_tpu_host_switch_latency_secs`` histogram.
    """

    PROBE_THREAD_NAME = "hostprof-gilprobe"

    def __init__(self, interval_secs: float = 0.005, capacity: int = 2048):
        self.interval_secs = float(interval_secs)
        self.enabled = False
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(capacity, 16))
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        with self._lock:
            if self.enabled:
                return
            self.enabled = True
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=self.PROBE_THREAD_NAME
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self.enabled = False
            t = self._thread
        if t is not None:
            t.join(max(self.interval_secs * 4, 0.25))

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def _loop(self) -> None:
        from .metrics import REGISTRY, exponential_buckets

        hist = REGISTRY.histogram(
            "trino_tpu_host_switch_latency_secs",
            help="observed lateness of a timed sleep vs its deadline "
                 "(GIL/scheduler contention probe; ~0 when idle, >= the "
                 "GIL switch interval under a runnable-thread hog)",
            buckets=exponential_buckets(0.0001, 2.0, 12),
        )
        while self.enabled:
            t0 = time.monotonic()
            time.sleep(self.interval_secs)
            jitter = max(time.monotonic() - t0 - self.interval_secs, 0.0)
            with self._lock:
                self._buf.append(jitter)
            hist.observe(jitter)

    def jitters(self) -> List[float]:
        with self._lock:
            return list(self._buf)

    def summary(self) -> dict:
        """p50/p99/max lateness (seconds) over the ring — the number the
        hostpath bench quotes next to p99 latency."""
        js = sorted(self.jitters())
        if not js:
            return {"samples": 0, "p50_secs": 0.0, "p99_secs": 0.0,
                    "max_secs": 0.0}
        import math

        def pct(q: float) -> float:
            return js[max(0, min(len(js) - 1, math.ceil(q * len(js)) - 1))]

        return {
            "samples": len(js),
            "p50_secs": round(pct(0.50), 6),
            "p99_secs": round(pct(0.99), 6),
            "max_secs": round(js[-1], 6),
        }


# --------------------------------------------------------------------------- #
# metrics plumbing
# --------------------------------------------------------------------------- #

_counters: Dict[str, object] = {}


def _metric_counter(name: str, help_: str):
    c = _counters.get(name)
    if c is None:
        from .metrics import REGISTRY

        c = _counters[name] = REGISTRY.counter(name, help=help_)
    return c


def update_thread_gauges(runnable: Optional[int] = None,
                         blocked: Optional[int] = None) -> Dict[str, int]:
    """Set ``trino_tpu_host_threads{state=}`` from a sampler classification,
    or (with no arguments) from a one-shot stack walk — the announcement
    path refreshes the gauges this way on hostprof-enabled servers without
    waiting for a sampler tick."""
    from .metrics import REGISTRY

    if runnable is None or blocked is None:
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        runnable = blocked = 0
        for ident, frame in sys._current_frames().items():
            if ident == me or names.get(ident) in (
                HostProfiler.SAMPLER_THREAD_NAME,
                ContentionProbe.PROBE_THREAD_NAME,
            ):
                continue
            _, leaf = _collapse(frame)
            if leaf in _WAIT_LEAVES:
                blocked += 1
            else:
                runnable += 1
    for state, value in (("runnable", runnable), ("blocked", blocked)):
        REGISTRY.gauge(
            "trino_tpu_host_threads", labels={"state": state},
            help="live engine threads by sampled state (hostprof "
                 "classification: leaf frame parked in a known wait -> "
                 "blocked, else runnable)",
        ).set(float(value))
    return {"runnable": runnable, "blocked": blocked}


# --------------------------------------------------------------------------- #
# gating + process singletons
# --------------------------------------------------------------------------- #


def server_enabled() -> bool:
    """Server-process gate: ``$TRINO_TPU_HOSTPROF`` starts the sampler and
    the contention probe at server startup. Default off — a flag-off
    process starts no threads and registers no hostprof series."""
    return knobs.env_flag("TRINO_TPU_HOSTPROF", False)


def session_enabled(session) -> bool:
    """Query-level gate: the ``host_profile`` session property."""
    if session is None:
        return False
    try:
        return bool(session.get("host_profile"))
    except KeyError:
        return False


PROFILER = HostProfiler()
PROBE = ContentionProbe()


def start_server_profiling() -> bool:
    """Idempotent server-startup hook (coordinator/worker ``start()``):
    with $TRINO_TPU_HOSTPROF on, run the sampler + probe for the process
    lifetime. Returns whether the plane is on."""
    if not server_enabled():
        return False
    PROFILER.enable()
    PROBE.start()
    return True
