"""Prepared statements: PREPARE / EXECUTE ... USING / DEALLOCATE +
DESCRIBE INPUT/OUTPUT and positional ? parameters.

Model: the reference's TestPrepareTask / TestDeallocateTask /
AbstractTestEngineOnlyQueries prepared-statement coverage
(execution/PrepareTask.java, sql/tree/Parameter.java, ParameterExtractor).
"""

import pytest


@pytest.fixture()
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.001)


def rows(runner, sql):
    return runner.execute(sql).rows


class TestPrepared:
    def test_prepare_execute(self, runner):
        rows(runner, "PREPARE q FROM SELECT n_name FROM nation WHERE n_nationkey = ?")
        assert rows(runner, "EXECUTE q USING 3") == [("CANADA",)]
        assert rows(runner, "EXECUTE q USING 5") == [("EGYPT",)]

    def test_multiple_parameters(self, runner):
        rows(
            runner,
            "PREPARE q2 FROM SELECT count(*) FROM nation "
            "WHERE n_nationkey >= ? AND n_nationkey < ?",
        )
        assert rows(runner, "EXECUTE q2 USING 0, 10") == [(10,)]

    def test_no_parameters(self, runner):
        rows(runner, "PREPARE q3 FROM SELECT count(*) FROM region")
        assert rows(runner, "EXECUTE q3") == [(5,)]

    def test_string_parameter(self, runner):
        rows(runner, "PREPARE q4 FROM SELECT n_nationkey FROM nation WHERE n_name = ?")
        assert rows(runner, "EXECUTE q4 USING 'CANADA'") == [(3,)]

    def test_expression_parameter(self, runner):
        rows(runner, "PREPARE q5 FROM SELECT ? + 10")
        assert rows(runner, "EXECUTE q5 USING 2 * 3") == [(16,)]

    def test_describe_input_output(self, runner):
        rows(runner, "PREPARE q6 FROM SELECT n_name FROM nation WHERE n_nationkey = ?")
        assert rows(runner, "DESCRIBE INPUT q6") == [(0, "unknown")]
        assert rows(runner, "DESCRIBE OUTPUT q6") == [("n_name", "varchar(25)")]

    def test_deallocate(self, runner):
        rows(runner, "PREPARE q7 FROM SELECT 1")
        rows(runner, "DEALLOCATE PREPARE q7")
        with pytest.raises(Exception, match="not found"):
            rows(runner, "EXECUTE q7")

    def test_parameter_count_mismatch(self, runner):
        rows(runner, "PREPARE q8 FROM SELECT ? + ?")
        with pytest.raises(Exception, match="expects 2 parameters"):
            rows(runner, "EXECUTE q8 USING 1")

    def test_unbound_parameter_rejected(self, runner):
        with pytest.raises(Exception, match="unbound parameter"):
            rows(runner, "SELECT ? + 1")

    def test_prepared_dml(self, runner):
        from trino_tpu.connectors.memory import MemoryConnector

        runner.register_catalog("memory", MemoryConnector())
        rows(runner, "CREATE TABLE memory.default.t AS SELECT 1 AS id, 5 AS v")
        rows(
            runner,
            "PREPARE upd FROM UPDATE memory.default.t SET v = ? WHERE id = ?",
        )
        rows(runner, "EXECUTE upd USING 99, 1")
        assert rows(runner, "SELECT v FROM memory.default.t") == [(99,)]

    def test_redefine_overwrites(self, runner):
        rows(runner, "PREPARE q9 FROM SELECT 1")
        rows(runner, "PREPARE q9 FROM SELECT 2")
        assert rows(runner, "EXECUTE q9") == [(2,)]


class TestPreparedHardening:
    def test_nested_execute_rejected(self, runner):
        with pytest.raises(Exception, match="cannot be"):
            rows(runner, "PREPARE p FROM EXECUTE p")
