"""Metrics registry + Prometheus text exposition.

Reference blueprint: io.trino.spi.metrics (Metrics/Metric — connector and
operator metrics merged up the query tree) and the JMX metrics the reference
exposes per coordinator/worker (queued/running queries, memory pools, spill
bytes); the Prometheus text format replaces the JMX transport (the reference
ecosystem scrapes those beans the same way).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Tuple


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class MetricsRegistry:
    """Name+labels -> metric; renders Prometheus text exposition format."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._types: Dict[str, str] = {}
        self._help: Dict[str, str] = {}

    def _get(self, cls, name: str, labels: Dict[str, str], help_: str):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls()
                self._metrics[key] = m
                self._types[name] = "counter" if cls is Counter else "gauge"
                self._help[name] = help_
            return m

    def counter(self, name: str, labels: Dict[str, str] = None, help: str = "") -> Counter:
        return self._get(Counter, name, labels or {}, help)

    def gauge(self, name: str, labels: Dict[str, str] = None, help: str = "") -> Gauge:
        return self._get(Gauge, name, labels or {}, help)

    def render(self) -> str:
        """Prometheus text format, grouped by metric name."""
        with self._lock:
            items = sorted(self._metrics.items())
            types = dict(self._types)
            helps = dict(self._help)
        lines: List[str] = []
        seen = set()
        for (name, labels), metric in items:
            if name not in seen:
                seen.add(name)
                if helps.get(name):
                    lines.append(f"# HELP {name} {helps[name]}")
                lines.append(f"# TYPE {name} {types.get(name, 'gauge')}")
            v = metric.value
            # full precision: %g truncates counters above ~1e6 and breaks
            # scrape deltas — integral values render as ints, others via repr
            text = str(int(v)) if float(v).is_integer() else repr(float(v))
            if labels:
                # label values escaped per the Prometheus text exposition
                # format: backslash, double-quote, and newline
                def esc(s):
                    return (
                        str(s)
                        .replace("\\", "\\\\")
                        .replace('"', '\\"')
                        .replace("\n", "\\n")
                    )

                lbl = ",".join(f'{k}="{esc(val)}"' for k, val in labels)
                lines.append(f"{name}{{{lbl}}} {text}")
            else:
                lines.append(f"{name} {text}")
        return "\n".join(lines) + "\n"


# process-wide registry (the coordinator/worker expose it at /v1/metrics)
REGISTRY = MetricsRegistry()
