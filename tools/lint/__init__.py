"""Engine lint suite: AST-based static analysis with engine-specific rules.

The second layer of the static-analysis plane (layer 1 is the plan sanity
checkers in trino_tpu/planner/sanity.py). The concurrency planes from rounds
8-11 — FTE event loop, memory pools, the process-wide cache singleton — run
on hand-enforced rules (no blocking call under a lock, paired flight spans,
HELP-registered metrics, declared knobs) that previously lived only in
reviewers' heads plus two ad-hoc lints; this package makes them executable:

    python -m tools.lint --format json          # findings as structured JSON
    python -m tools.lint                        # human-readable, exit 1 on new

Findings are compared against the checked-in baseline
(tools/lint/lint_baseline.json): NEW findings fail tier-1
(tests/test_static_analysis.py), baselined ones are tracked debt. Intentional
violations carry an inline suppression with a reason:

    something_flagged()  # lint: disable=rule-id -- why this is safe
"""

from .engine import Finding, LintEngine, load_baseline, run_lint  # noqa: F401
from .rules import ALL_RULES, registry_help_problems  # noqa: F401
