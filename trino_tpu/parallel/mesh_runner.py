"""MeshQueryRunner: whole fragment trees lowered into ONE shard_map program.

Reference blueprint: SURVEY.md §3.3 — every REMOTE exchange in Trino is a real
data plane (AddExchanges.java:145 -> PartitionedOutputOperator -> exchange
consumer chain). The TPU-native replacement executes the ENTIRE multi-stage
plan as one XLA program over a jax.sharding.Mesh:

    SOURCE fragments      -> per-shard blocks of the sharded scan pages
    REPARTITION exchange  -> all_to_all collective (parallel/exchange.py)
    BROADCAST / GATHER    -> all_gather collective (replicated consumers)
    SINGLE fragments      -> replicated SPMD compute over gathered inputs

No host round-trip between stages: stage outputs never leave HBM, the exchange
rides ICI, and XLA overlaps the collectives with compute — the role Trino's
pull/ack HTTP streams play between JVM workers (DirectExchangeClient.java:270).

Static-shape discipline: joins get a fixed output capacity and the program
returns a summed OVERFLOW scalar (join emits beyond capacity + all_to_all
bucket overflow). The runner host-checks it and retries with doubled
capacities — degrade to recompile, never to wrong answers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..metadata import CatalogManager, Metadata, Session
from ..planner import LogicalPlanner, optimize
from ..planner.fragmenter import (
    ExchangeType,
    Partitioning,
    PlanFragment,
    RemoteSourceNode,
    SubPlan,
    add_exchanges,
    create_fragments,
)
from ..planner.plan import LogicalPlan, OutputNode, PlanNode, TableScanNode, visit_plan
from ..runtime import kernelcost
from ..runtime.executor import Relation, _concat_pages, _round_capacity
from ..runtime.local import QueryResult
from ..runtime.traced import _TracedExecutor, is_traceable
from ..spi.page import Column, Page
from ..sql import parse_statement
from . import exchange
from .mesh import make_mesh


class MeshLoweringError(Exception):
    """Plan cannot lower to a single shard_map program (host syncs needed)."""


def _pad_page(page: Page, capacity: int) -> Page:
    if page.capacity == capacity:
        return page
    pad = capacity - page.capacity
    cols = tuple(
        Column(
            c.type,
            jnp.pad(c.data, [(0, pad)] + [(0, 0)] * (c.data.ndim - 1)),
            jnp.pad(c.valid, (0, pad)),
            c.dictionary,
        )
        for c in page.columns
    )
    return Page(cols, jnp.pad(page.active, (0, pad)))


@dataclass
class _ScanSpec:
    """One table scan's sharded input page + its fragment/scan identity."""

    fragment_id: int
    page: Page  # global page, device_put with P(axis) sharding
    symbols: Tuple[str, ...]


class _MeshFragmentExecutor(_TracedExecutor):
    """Executes one fragment per-shard inside shard_map. Scans read this
    shard's block of the sharded page; RemoteSources turn into collectives."""

    def __init__(
        self,
        plan,
        metadata,
        session,
        staged: Dict[int, Tuple[Page, Partitioning]],
        scan_pages: List[Page],
        frag_by_id: Dict[int, PlanFragment],
        num_partitions: int,
        axis_name: str,
        bucket_caps: Dict[int, int],
        join_capacity_factor: float,
    ):
        super().__init__(
            plan, metadata, session, dict(enumerate(scan_pages)),
            join_capacity_factor=join_capacity_factor,
        )
        self._staged = staged
        self._frag_by_id = frag_by_id
        self._n = num_partitions
        self._axis = axis_name
        self._bucket_caps = bucket_caps

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Relation:
        page, producer_part = self._staged[node.fragment_id]
        single_producer = producer_part in (
            Partitioning.SINGLE,
            Partitioning.COORDINATOR_ONLY,
        )
        if node.exchange_type == ExchangeType.REPARTITION_RANGE:
            o = node.orderings[0]
            key_idx = node.symbols.index(o.symbol)
            if single_producer:
                # replicated producer: each shard keeps its key range — same
                # sample-sort boundaries, no collective needed
                me = jax.lax.axis_index(self._axis).astype(jnp.int32)
                c = page.columns[key_idx]
                from ..ops import kernels as K

                # sorted dictionary codes are order keys (see
                # exchange.repartition_by_range)
                key = K.encode_sort_column(c.data, c.valid, o.ascending, o.nulls_first)
                skey = jnp.sort(jnp.where(page.active, key, jnp.int64(K.INT64_MAX)))
                cnt = jnp.sum(page.active.astype(jnp.int64))
                pos = (jnp.arange(1, self._n, dtype=jnp.int64) * cnt) // self._n
                bounds = skey[jnp.clip(pos, 0, page.capacity - 1)]
                target = jnp.sum(
                    (key[:, None] >= bounds[None, :]).astype(jnp.int32), axis=1
                )
                out = Page(page.columns, page.active & (target == me))
            else:
                bucket_cap = self._bucket_caps[node.fragment_id]
                out, overflow = exchange.repartition_by_range(
                    page, key_idx, o.ascending, o.nulls_first,
                    self._n, self._axis, bucket_cap=bucket_cap,
                )
                self.overflows.append(overflow)
            return Relation(out, node.symbols)
        if node.exchange_type == ExchangeType.REPARTITION:
            if single_producer:
                # replicated producer: repartitioning needs NO collective —
                # each shard keeps exactly the rows that hash to it
                keys = exchange.hash_key_columns(
                    [page.columns[node.symbols.index(k)] for k in node.partition_keys]
                )
                if keys:
                    target = exchange.partition_ids(keys, self._n)
                else:
                    target = jnp.zeros(page.capacity, dtype=jnp.int32)
                me = jax.lax.axis_index(self._axis).astype(jnp.int32)
                out = Page(page.columns, page.active & (target == me))
            else:
                key_idx = [node.symbols.index(k) for k in node.partition_keys]
                bucket_cap = self._bucket_caps[node.fragment_id]
                out, overflow = exchange.repartition_by_keys(
                    page, key_idx, self._n, self._axis, bucket_cap=bucket_cap
                )
                self.overflows.append(overflow)
            return Relation(out, node.symbols)
        # GATHER / BROADCAST: consumers need the complete producer output.
        # A replicated producer already satisfies that without a collective.
        if single_producer:
            return Relation(page, node.symbols)
        gathered = _all_gather_page(page, self._axis)
        return Relation(gathered, node.symbols)


def _all_gather_page(page: Page, axis_name: str) -> Page:
    cols = tuple(
        Column(
            c.type,
            jax.lax.all_gather(c.data, axis_name, axis=0, tiled=True),
            jax.lax.all_gather(c.valid, axis_name, axis=0, tiled=True),
            c.dictionary,
        )
        for c in page.columns
    )
    active = jax.lax.all_gather(page.active, axis_name, axis=0, tiled=True)
    return Page(cols, active)


class MeshQueryRunner:
    """SQL -> fragments -> ONE shard_map program over the device mesh.

    The planner-connected ICI execution path: the same SubPlan the DCN-tier
    DistributedQueryRunner schedules stage-by-stage compiles here into a single
    collective program (the intra-pod tier of SURVEY.md §5.8's two-level
    design). Plans with host-sync operators raise MeshLoweringError — callers
    (DistributedQueryRunner) fall back to the staged path.
    """

    def __init__(
        self,
        session: Optional[Session] = None,
        mesh=None,
        n_devices: Optional[int] = None,
        axis_name: str = "workers",
        catalogs: Optional[CatalogManager] = None,
        metadata: Optional[Metadata] = None,
    ):
        self.catalogs = catalogs or CatalogManager()
        self.metadata = metadata or Metadata(self.catalogs)
        self.session = session or Session()
        self.mesh = mesh if mesh is not None else make_mesh(
            n_devices or len(jax.devices())
        )
        self.axis = axis_name
        self.n = self.mesh.shape[axis_name]
        # compiled shard_map programs keyed by (plan structure, capacities) —
        # repeated queries reuse the XLA executable (the PageFunctionCompiler
        # cache discipline applied to whole multi-fragment programs)
        self._program_cache: Dict[tuple, object] = {}

    @staticmethod
    def tpch(scale: float = 0.01, n_devices: Optional[int] = None, **kw):
        from ..connectors.tpch import TpchConnector

        runner = MeshQueryRunner(
            Session(catalog="tpch", schema="sf" + f"{scale:g}".replace(".", "_")),
            n_devices=n_devices,
        )
        runner.catalogs.register("tpch", TpchConnector(scale=scale, **kw))
        return runner

    # ----------------------------------------------------------------- planning

    def plan_distributed(self, sql: str) -> SubPlan:
        stmt = parse_statement(sql)
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, self.metadata, self.session)
        return create_fragments(plan)

    # ---------------------------------------------------------------- execution

    def execute(self, sql: str) -> QueryResult:
        subplan = self.plan_distributed(sql)
        names, page = self.execute_subplan(subplan)
        return QueryResult(names, page.to_pylist())

    def execute_subplan(self, subplan: SubPlan) -> Tuple[List[str], Page]:
        self._check_lowerable(subplan)
        scan_specs, scan_counts = self._shard_scans(subplan)
        root = subplan.root_fragment.root
        assert isinstance(root, OutputNode)

        join_factor = float(self.session.get("mesh_join_capacity_factor") or 1.0)
        bucket_caps = self._initial_bucket_caps(subplan, scan_specs)
        flat_pages = [s.page for s in scan_specs]

        import time as _time

        from ..runtime import observability as obs

        collector = obs.current_collector()
        plan_key = repr(
            [(f.fragment_id, f.partitioning, f.root) for f in subplan.fragments]
        )
        for attempt in range(4):
            cache_key = (
                plan_key,
                tuple(p.capacity for p in flat_pages),
                tuple(sorted(bucket_caps.items())),
                join_factor,
            )
            program = self._program_cache.get(cache_key)
            cached = program is not None
            if program is None:
                program = self._build_program(
                    subplan, scan_counts, bucket_caps, join_factor
                )
                self._program_cache[cache_key] = program
            elif collector is not None:
                collector.add_count("compile_cache_hits")
            t0 = _time.perf_counter()
            with obs.RECORDER.span(
                "mesh_program", "mesh", attempt=attempt,
                join_factor=join_factor, cached=cached,
            ), obs.compile_window() as cw:
                out_page, overflow = program(*flat_pages)
                done = int(overflow) == 0
            if collector is not None:
                collector.add_time(
                    "device_busy_secs",
                    max(_time.perf_counter() - t0 - cw.seconds, 0.0),
                )
            if done:
                break
            # degrade to recompile, never to wrong answers
            if collector is not None:
                collector.add_count("overflow_retries")
            obs.RECORDER.instant(
                "mesh_overflow_retry", "mesh", attempt=attempt
            )
            join_factor *= 2.0
            bucket_caps = {k: v * 2 for k, v in bucket_caps.items()}
        else:
            raise MeshLoweringError("capacity retry limit exceeded")

        # out_specs P(axis) stacks each shard's (replicated) root block; the
        # root fragment is SINGLE so shard 0's block is the complete answer
        cap = out_page.capacity // self.n
        cols = tuple(
            Column(c.type, c.data[:cap], c.valid[:cap], c.dictionary)
            for c in out_page.columns
        )
        page = Page(cols, out_page.active[:cap])
        return list(root.column_names), page

    # ----------------------------------------------------------------- internals

    def _check_lowerable(self, subplan: SubPlan) -> None:
        """Reject plans whose SPMD execution would be wrong, not just slow.

        - cross / non-equi joins get NO exchange from the planner, so both
          sides land in one fragment: each shard would join only its own
          blocks, silently dropping cross-shard pairs.
        - a fragment whose partitioning is not SOURCE but which contains a
          table scan (e.g. scan UNION Values -> SINGLE) would be consumed as
          replicated while its scan rows are actually sharded.
        The staged (DCN-tier) runner handles these shapes correctly.
        """
        from ..planner.plan import JoinNode

        for frag in subplan.fragments:
            if not is_traceable(
                LogicalPlan(frag.root, subplan.types),
                allow_joins=True,
                extra_types=(RemoteSourceNode,),
            ):
                raise MeshLoweringError(
                    f"fragment {frag.fragment_id} contains host-sync operators"
                )
            scans = 0
            bad = []

            def check(n: PlanNode):
                nonlocal scans
                if isinstance(n, TableScanNode):
                    scans += 1
                if isinstance(n, JoinNode) and not n.criteria:
                    bad.append("cross or non-equi join (no exchange inserted)")

            visit_plan(frag.root, check)
            if bad:
                raise MeshLoweringError(bad[0])
            if scans > 1:
                raise MeshLoweringError(
                    "multiple scans in one fragment (no co-location exchange)"
                )
            if scans and frag.partitioning != Partitioning.SOURCE:
                raise MeshLoweringError(
                    f"scan in a {frag.partitioning.value} fragment would be "
                    "consumed as replicated"
                )

    def _shard_scans(self, subplan: SubPlan):
        """Load every fragment's scans as mesh-sharded global pages (splits ->
        shards), with per-column dictionaries unified BEFORE sharding so the
        static dictionary aux is identical on every shard."""
        scan_specs: List[_ScanSpec] = []
        scan_counts: Dict[int, int] = {}
        sharding = NamedSharding(self.mesh, P(self.axis))
        for frag in subplan.fragments:
            scans: List[TableScanNode] = []

            def collect(n: PlanNode):
                if isinstance(n, TableScanNode):
                    scans.append(n)

            visit_plan(frag.root, collect)
            scan_counts[frag.fragment_id] = len(scans)
            for node in scans:
                page = self._load_scan(node)
                per_shard = _round_capacity(
                    max(math.ceil(page.capacity / self.n), 1), base=8
                )
                padded = _pad_page(page, per_shard * self.n)
                sharded = jax.device_put(padded, sharding)
                symbols = tuple(s for s, _ in node.assignments)
                scan_specs.append(_ScanSpec(frag.fragment_id, sharded, symbols))
        return scan_specs, scan_counts

    def _load_scan(self, node: TableScanNode) -> Page:
        connector = self.metadata.connector_for(node.table)
        handle = node.table
        if node.constraint.domains:
            absorbed = self.metadata.apply_filter(handle, node.constraint)
            if absorbed is not None:
                handle = absorbed
        splits = connector.split_manager().get_splits(handle)
        meta = self.metadata.get_table_metadata(node.table)
        col_indexes = [meta.column_index(c) for _, c in node.assignments]
        provider = connector.page_source_provider()
        from ..runtime.executor import _load_splits

        pages = _load_splits(provider, splits, col_indexes, self.session)
        if not pages:
            # fully pruned scan: the staged (DCN) path handles it; keep the
            # mesh program's scan layout uniform instead of special-casing
            raise MeshLoweringError("empty scan (fully pruned) on mesh path")
        return _concat_pages(pages)

    def _initial_bucket_caps(self, subplan, scan_specs) -> Dict[int, int]:
        """bucket_cap per REPARTITION producer fragment: 2x the even share of
        the producer's (estimated) per-shard capacity, pow2-rounded. Overflow
        is detected and retried, so this is a bandwidth/memory tradeoff, not a
        correctness knob."""
        caps: Dict[int, int] = {}
        frag_caps: Dict[int, int] = {}
        for s in scan_specs:
            frag_caps[s.fragment_id] = max(
                frag_caps.get(s.fragment_id, 0), s.page.capacity // self.n
            )
        for frag in subplan.fragments:
            base = frag_caps.get(frag.fragment_id, 0)
            for fid in frag.input_fragments:
                base = max(base, frag_caps.get(fid, 0))
            frag_caps[frag.fragment_id] = max(base, 8)
            caps[frag.fragment_id] = _round_capacity(
                max(2 * frag_caps[frag.fragment_id] // self.n, 8), base=8
            )
        return caps

    def _build_program(self, subplan, scan_counts, bucket_caps, join_factor):
        frag_by_id = {f.fragment_id: f for f in subplan.fragments}
        root_id = subplan.root_fragment.fragment_id
        n, axis = self.n, self.axis

        def body(*flat_scan_pages: Page):
            staged: Dict[int, Tuple[Page, Partitioning]] = {}
            overflows: List[jnp.ndarray] = []
            it = iter(flat_scan_pages)
            for frag in subplan.fragments:
                frag_scans = [next(it) for _ in range(scan_counts[frag.fragment_id])]
                executor = _MeshFragmentExecutor(
                    LogicalPlan(frag.root, subplan.types),
                    self.metadata,
                    self.session,
                    staged,
                    frag_scans,
                    frag_by_id,
                    n,
                    axis,
                    bucket_caps,
                    join_factor,
                )
                if isinstance(frag.root, OutputNode):
                    rel = executor.eval(frag.root.source)
                    page = Page(
                        tuple(rel.column_for(s) for s in frag.root.symbols),
                        rel.page.active,
                    )
                else:
                    rel = executor.eval(frag.root)
                    page = Page(
                        tuple(
                            rel.column_for(s) for s in frag.root.output_symbols
                        ),
                        rel.page.active,
                    )
                staged[frag.fragment_id] = (page, frag.partitioning)
                overflows.extend(executor.overflows)
            root_page = staged[root_id][0]
            total = jnp.int64(0)
            for o in overflows:
                total = total + o.astype(jnp.int64)
            # psum makes the indicator globally visible (values already psum'd
            # just scale by n — the host only tests > 0)
            total = jax.lax.psum(total, axis)
            return root_page, total

        return kernelcost.jit(
            jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=tuple(P(axis) for _ in range(sum(scan_counts.values()))),
                out_specs=(P(axis), P()),
            )
        )
