"""Static-analysis plane: plan sanity checkers + the engine lint suite.

Three layers of coverage:

1. Checker mutation suite — every checker in planner/sanity.py is killed by
   at least one seeded plan corruption (dangling symbol, duplicate node id,
   dropped partition key, nondet-below-exchange, ...), and each corruption is
   caught by EXACTLY the checker that owns it (disjoint ownership is what
   makes a PlanSanityError actionable).
2. Whole-corpus validation — all 22 TPC-H queries (tests/tpch_corpus.py) and
   the TPC-DS conformance corpus (when the reference checkout is present)
   optimize + add_exchanges cleanly with validate_plan=true, i.e. the
   intermediate checks run after EVERY optimizer rule; repeated with
   history_based_stats=true over warm history (the stats overlay must keep
   estimates finite/non-negative).
3. Engine lint tier-1 gate — python -m tools.lint over trino_tpu/ reports
   zero non-baselined findings, and each lint rule is itself mutation-tested
   against a seeded bad snippet.
"""

import os

import pytest

from trino_tpu.metadata import Session
from trino_tpu.planner.plan import (
    Aggregation,
    AggregationNode,
    ExchangeNode,
    ExchangeScope,
    ExchangeType,
    FilterNode,
    LimitNode,
    LogicalPlan,
    Ordering,
    OutputNode,
    ProjectNode,
    SemiJoinNode,
    UnionNode,
    ValuesNode,
    VectorTopNNode,
    WindowFunction,
    WindowNode,
)
from trino_tpu.planner.sanity import (
    CHECKERS,
    PlanSanityError,
    SanityContext,
    checker_ids,
    run_checkers,
    validate_final,
    validate_intermediate,
)
from trino_tpu.planner.stats import PlanStats
from trino_tpu.spi.types import BIGINT, BOOLEAN, DOUBLE
from trino_tpu.sql.ir import Call, Constant, Reference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _leaf(symbols=("a", "b")):
    return ValuesNode(symbols=tuple(symbols), rows=((1, 2),))


def _types(**extra):
    out = {"a": BIGINT, "b": BIGINT}
    out.update(extra)
    return out


def _fired(root, types=None, session=None, estimator=None):
    ctx = SanityContext(types if types is not None else _types(),
                        session=session, estimator=estimator)
    return {v.checker for v in run_checkers(root, ctx)}


class TestCheckerMutations:
    """Each seeded corruption is caught by exactly the checker that owns it."""

    def test_checker_count(self):
        # the plane's floor: >= 8 composable checkers
        assert len(CHECKERS) >= 8
        assert len(set(checker_ids())) == len(CHECKERS)

    def test_valid_plan_is_clean(self):
        v = _leaf()
        root = OutputNode(
            source=ProjectNode(
                source=FilterNode(
                    source=v,
                    predicate=Call("$eq", (Reference("a", BIGINT),
                                           Constant(BIGINT, 1)), BOOLEAN),
                ),
                assignments=(("p", Reference("a", BIGINT)),),
            ),
            column_names=("p",), symbols=("p",),
        )
        assert _fired(root, _types(p=BIGINT)) == set()

    def test_dangling_symbol(self):
        root = ProjectNode(
            source=_leaf(), assignments=(("p", Reference("zz", BIGINT)),)
        )
        assert _fired(root, _types(p=BIGINT)) == {"symbol-dependencies"}

    def test_semijoin_key_dangling(self):
        root = SemiJoinNode(
            source=_leaf(), filtering_source=ValuesNode(symbols=("c",), rows=()),
            source_key="zz", filtering_key="c", output="m",
        )
        assert _fired(root, _types(c=BIGINT, m=BOOLEAN)) == {"symbol-dependencies"}

    def test_duplicate_node_id(self):
        v = _leaf(("a",))
        root = UnionNode(
            inputs=(v, v), symbols=("u",), symbol_mapping=(("a",), ("a",))
        )
        assert _fired(root, {"a": BIGINT, "u": BIGINT}) == {"no-duplicate-plan-node-ids"}

    def test_duplicate_output_symbols(self):
        root = ProjectNode(
            source=_leaf(),
            assignments=(("d", Reference("a", BIGINT)),
                         ("d", Reference("b", BIGINT))),
        )
        assert _fired(root, _types(d=BIGINT)) == {"unique-output-symbols"}

    def test_missing_symbol_type(self):
        root = ProjectNode(
            source=_leaf(), assignments=(("untyped", Reference("a", BIGINT)),)
        )
        assert _fired(root, _types()) == {"type-consistency"}

    def test_non_boolean_filter_predicate(self):
        root = FilterNode(source=_leaf(), predicate=Reference("a", BIGINT))
        assert _fired(root, _types()) == {"type-consistency"}

    def test_aggregation_arg_dangling(self):
        root = AggregationNode(
            source=_leaf(), group_keys=("a",),
            aggregations=(("s", Aggregation("sum", ("zz",), output_type=BIGINT)),),
        )
        assert _fired(root, _types(s=BIGINT)) == {"aggregation-validity"}

    def test_window_arg_dangling(self):
        root = WindowNode(
            source=_leaf(),
            functions=(("w", WindowFunction("sum", ("zz",), output_type=BIGINT)),),
        )
        assert _fired(root, _types(w=BIGINT)) == {"window-validity"}

    def test_dropped_partition_key(self):
        root = ExchangeNode(
            source=_leaf(), exchange_type=ExchangeType.REPARTITION,
            scope=ExchangeScope.REMOTE, partition_keys=("zz",),
        )
        assert _fired(root, _types()) == {"exchange-partitioning"}

    def test_repartition_without_keys(self):
        root = ExchangeNode(
            source=_leaf(), exchange_type=ExchangeType.REPARTITION,
            scope=ExchangeScope.REMOTE, partition_keys=(),
        )
        assert _fired(root, _types()) == {"exchange-partitioning"}

    def test_nondeterministic_below_retryable_exchange(self):
        root = ExchangeNode(
            source=ProjectNode(
                source=_leaf(),
                assignments=(("r", Call("random", (), DOUBLE)),),
            ),
            exchange_type=ExchangeType.GATHER, scope=ExchangeScope.REMOTE,
        )
        fte = Session(properties={"retry_policy": "TASK"})
        assert _fired(root, _types(r=DOUBLE), session=fte) == {"fte-determinism"}
        # without TASK retries the same plan is legal
        assert _fired(root, _types(r=DOUBLE), session=Session()) == set()

    def test_union_mapping_arity(self):
        root = UnionNode(
            inputs=(_leaf(("a",)), ValuesNode(symbols=("c",), rows=())),
            symbols=("u",), symbol_mapping=(("a",),),
        )
        assert _fired(root, {"a": BIGINT, "c": BIGINT, "u": BIGINT}) == {
            "union-consistency"
        }

    def test_negative_limit(self):
        root = LimitNode(source=_leaf(), count=-1)
        assert _fired(root, _types()) == {"limit-sanity"}

    def test_output_arity(self):
        root = OutputNode(source=_leaf(("a",)), column_names=("x", "y"),
                          symbols=("a",))
        assert _fired(root, {"a": BIGINT}) == {"output-arity"}

    def test_nan_estimate(self):
        class NanEstimator:
            def stats(self, node):
                return PlanStats(float("nan"), {})

        root = _leaf()
        assert _fired(root, _types(), estimator=NanEstimator()) == {
            "estimate-sanity"
        }

    def test_vector_dimension_mismatch(self):
        """Tensor plane: dot_product over mismatched VECTOR dimensions must
        fail plan validation naming type-consistency, never inside a
        kernel."""
        from trino_tpu.spi.types import vector_type

        expr = Call(
            "dot_product",
            (Reference("a", vector_type(3)), Reference("b", vector_type(4))),
            DOUBLE,
        )
        root = ProjectNode(source=_leaf(), assignments=(("p", expr),))
        assert _fired(root, _types(p=DOUBLE)) == {"type-consistency"}

    def test_vector_arg_not_a_vector(self):
        from trino_tpu.spi.types import vector_type

        expr = Call(
            "cosine_similarity",
            (Reference("a", vector_type(3)), Reference("b", BIGINT)),
            DOUBLE,
        )
        root = FilterNode(
            source=_leaf(),
            predicate=Call("$gt", (expr, Constant(DOUBLE, 0.5)), BOOLEAN),
        )
        assert _fired(root, _types()) == {"type-consistency"}

    def test_linear_model_arity_mismatch(self):
        from trino_tpu.spi.types import UNKNOWN

        spec = ((1.0, 2.0, 3.0), 0.0)  # 3 weights...
        expr = Call(
            "$linear_model",
            (Constant(UNKNOWN, spec), Reference("a", DOUBLE)),  # ...1 feature
            DOUBLE,
        )
        root = ProjectNode(source=_leaf(), assignments=(("p", expr),))
        assert _fired(root, _types(p=DOUBLE)) == {"type-consistency"}

    def test_gbdt_model_arity_mismatch(self):
        from trino_tpu.spi.types import UNKNOWN

        # one depth-1 tree splitting on feature index 2...
        spec = (0.0, (((2,), (0.5,), (-1.0, 1.0)),))
        expr = Call(
            "$gbdt_model",
            (Constant(UNKNOWN, spec), Reference("a", DOUBLE)),  # ...1 feature
            DOUBLE,
        )
        root = ProjectNode(source=_leaf(), assignments=(("p", expr),))
        assert _fired(root, _types(p=DOUBLE)) == {"type-consistency"}

    def test_fused_topn_unprojected_sort_key(self):
        root = VectorTopNNode(
            source=_leaf(),
            assignments=(("p", Reference("a", BIGINT)),),
            count=5,
            orderings=(Ordering("zz"),),
        )
        assert _fired(root, _types(p=BIGINT)) == {"symbol-dependencies"}

    def test_fused_topn_negative_count(self):
        root = VectorTopNNode(
            source=_leaf(),
            assignments=(("p", Reference("a", BIGINT)),),
            count=-2,
            orderings=(Ordering("p"),),
        )
        assert _fired(root, _types(p=BIGINT)) == {"limit-sanity"}

    def test_every_checker_killed(self):
        """The mutation suite above covers the full checker set."""
        killed = {
            "symbol-dependencies", "no-duplicate-plan-node-ids",
            "unique-output-symbols", "type-consistency",
            "aggregation-validity", "window-validity",
            "exchange-partitioning", "union-consistency", "limit-sanity",
            "output-arity", "fte-determinism", "estimate-sanity",
        }
        assert killed == set(checker_ids())


class TestSanityErrorReporting:
    def test_error_names_checker_path_and_rule(self):
        root = ProjectNode(
            source=_leaf(), assignments=(("p", Reference("zz", BIGINT)),)
        )
        with pytest.raises(PlanSanityError) as ei:
            validate_intermediate(root, _types(p=BIGINT), rule="bogus_rule")
        err = ei.value
        assert err.checker == "symbol-dependencies"
        assert err.rule == "bogus_rule"
        assert "Project" in err.node_path
        assert "zz" in str(err)

    def test_validate_final_raises_on_corrupt_plan(self):
        plan = LogicalPlan(LimitNode(source=_leaf(), count=-3), _types())
        with pytest.raises(PlanSanityError) as ei:
            validate_final(plan, stage="add_exchanges")
        assert ei.value.rule == "add_exchanges"

    def test_optimizer_reports_offending_rule(self, monkeypatch):
        """An optimizer rule that corrupts the plan is named by the error."""
        from trino_tpu.planner import optimizer as opt
        from trino_tpu.runtime.local import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.0005)
        runner.session.set("validate_plan", True)

        real = opt.optimizer_passes

        def sabotaged(metadata, types, session):
            passes = real(metadata, types, session)

            def corrupt(root):
                return LimitNode(source=root, count=-1)

            return passes[:3] + [("evil_rule", corrupt)] + passes[3:]

        monkeypatch.setattr(opt, "optimizer_passes", sabotaged)
        with pytest.raises(PlanSanityError) as ei:
            runner.plan_sql("SELECT count(*) FROM nation")
        assert ei.value.rule == "evil_rule"
        assert ei.value.checker == "limit-sanity"


SCALE = 0.0005


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


class TestTpchCorpusValidates:
    """Final + intermediate plan sanity across the full TPC-H corpus with
    validate_plan=true (the knob also defaults on under pytest, so every
    OTHER test in the suite exercises the checkers over its own queries —
    this class makes the 22-query contract explicit and adds the
    warm-history overlay)."""

    @pytest.mark.parametrize("name", sorted(__import__(
        "tests.tpch_corpus", fromlist=["TPCH_QUERIES"]).TPCH_QUERIES))
    def test_query_validates_through_exchanges(self, runner, name):
        from tests.tpch_corpus import TPCH_QUERIES
        from trino_tpu.planner.fragmenter import add_exchanges, create_fragments

        runner.session.set("validate_plan", True)
        plan = runner.plan_sql(TPCH_QUERIES[name])  # intermediate + final
        distributed = add_exchanges(plan, runner.metadata, runner.session)
        create_fragments(distributed)

    def test_corpus_validates_with_warm_history(self, runner):
        """history_based_stats=true over recorded actuals: the overlay
        changes estimates (possibly plans) but must keep every estimate
        finite/non-negative through every rule."""
        from tests.tpch_corpus import TPCH_QUERIES
        from trino_tpu.planner.fragmenter import add_exchanges

        runner.session.set("validate_plan", True)
        # warm the statistics-feedback history with real executions
        for name in ("q03", "q05", "q06"):
            runner.execute(TPCH_QUERIES[name])
        runner.session.set("history_based_stats", True)
        try:
            for name, sql in sorted(TPCH_QUERIES.items()):
                plan = runner.plan_sql(sql)
                add_exchanges(plan, runner.metadata, runner.session)
        finally:
            runner.session.properties.pop("history_based_stats", None)

    def test_fte_execution_validates(self):
        """The FTE tier (durable exchanges, retries, the adaptive join-mode
        flip below the plan layer) plans through the same validated
        optimize + add_exchanges path; the distributed smoke shape must
        stay bit-correct with validation explicitly on."""
        from trino_tpu.parallel.runner import DistributedQueryRunner

        r = DistributedQueryRunner.tpch(scale=0.001, n_workers=2)
        r.session.set("retry_policy", "TASK")
        r.session.set("validate_plan", True)
        r.session.set("join_distribution_type", "PARTITIONED")
        r.session.set("target_partition_rows", 500)
        rows = r.execute(
            "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"
        ).rows
        assert rows and rows[0][0] > 0


TPCDS_CANON = (
    "/root/reference/testing/trino-benchmark-queries/src/main/resources/sql/trino/tpcds"
)


@pytest.mark.skipif(not os.path.isdir(TPCDS_CANON),
                    reason="reference checkout not available")
class TestTpcdsCorpusValidates:
    """Every canonical TPC-DS query optimizes + places exchanges cleanly
    under intermediate + final sanity checks."""

    @pytest.fixture(scope="class")
    def ds_runner(self):
        from trino_tpu.connectors import tpcds as ds
        from trino_tpu.runtime import LocalQueryRunner

        r = LocalQueryRunner(Session(catalog="tpcds", schema="sf0_001"))
        r.register_catalog("tpcds", ds.TpcdsConnector(scale=0.001))
        r.session.set("validate_plan", True)
        return r

    def test_corpus_validates(self, ds_runner):
        import glob
        import sys

        from trino_tpu.planner.fragmenter import add_exchanges

        sys.setrecursionlimit(20000)  # q08-class IN-lists recurse in the parser
        failures = []
        for path in sorted(glob.glob(os.path.join(TPCDS_CANON, "q*.sql"))):
            sql = open(path).read().strip().rstrip(";")
            sql = sql.replace('"${database}"."${schema}".', "")
            sql = sql.replace("${database}.${schema}.", "")
            try:
                plan = ds_runner.plan_sql(sql)
                add_exchanges(plan, ds_runner.metadata, ds_runner.session)
            except PlanSanityError as e:
                failures.append((os.path.basename(path), str(e)[:120]))
            except Exception:
                # parse/plan gaps are the conformance suite's concern, not
                # the sanity plane's
                continue
        assert not failures, failures


class TestEngineLint:
    """Tier-1 gate: the lint suite over trino_tpu/ has zero non-baselined
    findings, and each rule is killed by a seeded bad snippet."""

    def test_lint_trino_tpu_clean(self):
        from tools.lint import run_lint

        result = run_lint()
        new = [f"{f.file}:{f.line} [{f.rule}] {f.message}"
               for f in result.findings]
        assert not new, new

    def test_rule_count(self):
        from tools.lint.rules import ALL_RULES

        assert len(ALL_RULES) >= 5
        assert len({r.id for r in ALL_RULES}) == len(ALL_RULES)

    # ---------------------------------------------------------- rule kills

    def _lint_snippet(self, tmp_path, relpath, source, rules=None):
        from tools.lint.engine import LintEngine
        from tools.lint.rules import ALL_RULES

        full = tmp_path / relpath
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_text(source)
        engine = LintEngine(list(rules or ALL_RULES), root=str(tmp_path))
        return engine.lint_file(str(full))

    def test_kill_blocking_call_under_lock(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import time\n"
            "def f(self):\n"
            "    with self._lock:\n"
            "        time.sleep(1)\n"
        ))
        assert [f.rule for f in findings] == ["blocking-call-under-lock"]

    def test_kill_nested_acquire_and_foreign_wait(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "def f(self):\n"
            "    with self._lock:\n"
            "        other.acquire()\n"
            "        self._other_cond.wait()\n"
            "    with self._cond:\n"
            "        self._cond.wait()\n"  # waiting on the held cond is fine
        ))
        assert [f.rule for f in findings] == ["blocking-call-under-lock"] * 2

    def test_io_lock_exemption(self, tmp_path):
        # the sanctioned dedicated-I/O-serialization-lock pattern
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "def f(self):\n"
            "    with self._io_lock:\n"
            "        with open('x', 'a') as fh:\n"
            "            fh.write('y')\n"
        ))
        assert findings == []

    def test_kill_unpaired_flight_span(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "def f():\n"
            "    sp = RECORDER.span('a', 'b')\n"
            "    with RECORDER.span('c', 'd'):\n"
            "        pass\n"
        ))
        assert [f.rule for f in findings] == ["unpaired-flight-span"]
        assert findings[0].line == 2

    def test_kill_metric_help_missing(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "REGISTRY.counter('x_total')\n"
            "REGISTRY.counter('y_total', help='')\n"
            "REGISTRY.counter('z_total', help='a real description')\n"
            "REGISTRY.counter('p_total', {'l': 'v'}, 'positional')\n"
            "REGISTRY.counter('q_total', {'l': 'v'}, '')\n"
        ))
        assert [f.rule for f in findings] == ["metric-help-missing"] * 3
        assert {f.line for f in findings} == {1, 2, 5}

    def test_kill_metric_name_conformance(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "REGISTRY.counter('trino_tpu_things', help='h')\n"        # bad
            "REGISTRY.counter('trino_tpu_things_total', help='h')\n"  # ok
            "_counter('trino_tpu_helper_things', 'h')\n"              # bad
            "_counter('trino_tpu_helper_things_total', 'h')\n"        # ok
            "REGISTRY.histogram('trino_tpu_lat_secs', help='h')\n"    # bad
            "REGISTRY.histogram('trino_tpu_lat_secs', help='h', "
            "buckets=[1, 2])\n"                                       # ok
        ))
        assert [f.rule for f in findings] == ["metric-name-conformance"] * 3
        assert {f.line for f in findings} == {1, 3, 5}

    def test_metric_name_rule_ignores_foreign_counters(self, tmp_path):
        # a non-registry call named counter() with a non-metric literal is
        # not a metric registration; gauges carry no _total requirement
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "collections.Counter('abc')\n"
            "words.counter('not_a_metric')\n"
            "REGISTRY.gauge('trino_tpu_queries_running', help='h')\n"
        ))
        assert findings == []

    def test_kill_env_read_outside_knobs(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import os\n"
            "E = 'TRINO_TPU_SOMETHING'\n"
            "a = os.environ.get('TRINO_TPU_FOO')\n"
            "b = os.environ['TRINO_TPU_BAR']\n"
            "c = os.environ.get(E)\n"
            "d = os.environ.get('NOT_OURS')\n"
            "e = os.environ[E]\n"
        ))
        assert [f.rule for f in findings] == ["env-read-outside-knobs"] * 4

    def test_env_rule_skips_knobs_module(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "trino_tpu/knobs.py", (
            "import os\n"
            "a = os.environ.get('TRINO_TPU_FOO')\n"
        ))
        assert findings == []

    def test_kill_bare_except_swallow(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/executor.py", (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except:\n"
            "        pass\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
        ))
        assert [f.rule for f in findings] == ["bare-except-swallow"] * 2

    def test_swallow_ok_outside_critical_paths(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "connectors/x.py", (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except ValueError:\n"
            "        pass\n"
        ))
        assert findings == []

    def test_kill_undeclared_session_property(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "def f(session):\n"
            "    session.get('definitely_not_a_knob')\n"
            "    session.get('validate_plan')\n"
        ))
        assert [f.rule for f in findings] == ["undeclared-session-property"]

    def test_kill_unnamed_thread(self, tmp_path):
        # thread names are the host-profile/cluster-trace lane identity:
        # every Thread construction spelling must pass name=
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import threading\n"
            "import threading as _th\n"
            "from threading import Thread\n"
            "a = threading.Thread(target=f)\n"
            "b = _th.Thread(target=f, daemon=True)\n"
            "c = Thread(target=f, args=(1,))\n"
        ))
        assert [f.rule for f in findings] == ["unnamed-thread"] * 3
        assert {f.line for f in findings} == {4, 5, 6}

    def test_unnamed_thread_ok_paths(self, tmp_path):
        # named construction, kwargs forwarding, and non-Thread callables
        ok = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import threading\n"
            "a = threading.Thread(target=f, name='worker-http-8080')\n"
            "b = threading.Thread(**kwargs)\n"
            "c = threading.Timer(1.0, f)\n"
        ))
        assert ok == []

    def test_unnamed_thread_baseline_empty(self):
        # the engine migration is total: no file carries a baselined
        # unnamed-thread finding
        import json

        from tools.lint.engine import BASELINE_PATH

        with open(BASELINE_PATH) as f:
            baseline = json.load(f)
        assert not [e for e in baseline if "unnamed-thread" in str(e)]

    def test_kill_pallas_call_outside_ops(self, tmp_path):
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "from jax.experimental import pallas as pl\n"
            "def f(k, xs):\n"
            "    return pl.pallas_call(k, out_shape=xs)\n"
        ))
        assert [f.rule for f in findings] == ["pallas-call-outside-ops"]
        # the ops/ kernel layer is the sanctioned launch site
        ok = self._lint_snippet(tmp_path, "ops/megakernels.py", (
            "from jax.experimental import pallas as pl\n"
            "def f(k, xs):\n"
            "    return pl.pallas_call(k, out_shape=xs)\n"
        ))
        assert ok == []

    def test_kill_jit_without_cost_hook(self, tmp_path):
        # every form a raw jax.jit takes in the engine: decorator,
        # partial-wrapped decorator, and plain call
        findings = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import jax\n"
            "from functools import partial\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x\n"
            "g = partial(jax.jit, static_argnums=(0,))(f)\n"
            "h = jax.jit(f, static_argnums=(0,))\n"
        ))
        assert [f.rule for f in findings] == ["jit-without-cost-hook"] * 3
        assert {f.line for f in findings} == {3, 6, 7}

    def test_jit_rule_ok_paths(self, tmp_path):
        # the wrapper itself and non-jit jax attributes stay clean
        ok = self._lint_snippet(tmp_path, "runtime/x.py", (
            "import jax\n"
            "from . import kernelcost\n"
            "@kernelcost.jit\n"
            "def f(x):\n"
            "    return jax.vmap(f)(x)\n"
        ))
        assert ok == []
        suppressed = self._lint_snippet(tmp_path, "runtime/y.py", (
            "import jax\n"
            "j = jax.jit(abs)  # lint: disable=jit-without-cost-hook -- tested reason\n"
        ))
        assert suppressed == []

    def test_jit_rule_baseline_empty(self):
        # the migration is total: no engine file carries a baselined raw
        # jax.jit (the one sanctioned site suppresses inline with a reason)
        import json

        from tools.lint.engine import BASELINE_PATH

        with open(BASELINE_PATH) as f:
            baseline = json.load(f)
        assert not [
            e for e in baseline if "jit-without-cost-hook" in str(e)
        ]

    def test_suppression_requires_reason(self, tmp_path):
        with_reason = self._lint_snippet(tmp_path, "runtime/executor.py", (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # lint: disable=bare-except-swallow -- tested reason\n"
            "        pass\n"
        ))
        assert with_reason == []
        without = self._lint_snippet(tmp_path, "runtime/fte_scheduler.py", (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # lint: disable=bare-except-swallow\n"
            "        pass\n"
        ))
        assert len(without) == 1 and "without a reason" in without[0].message

    def test_json_entry_point(self):
        import json
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--format", "json"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["new"] == []

    def test_shared_help_rule_runtime_half(self):
        from tools.lint.rules import registry_help_problems

        class FakeRegistry:
            def collect(self):
                return [
                    {"name": "good_total", "help": "fine"},
                    {"name": "bad_total", "help": ""},
                ]

        problems = registry_help_problems(FakeRegistry(), required=("missing_x",))
        assert any("bad_total" in p for p in problems)
        assert any("missing_x" in p for p in problems)


class TestKnobRegistry:
    """The central knob registry (satellite): every TRINO_TPU_* env var is
    declared, accessors enforce declaration, and the generated doc table in
    ARCHITECTURE.md matches the generator (no drift)."""

    def test_undeclared_env_knob_rejected(self):
        from trino_tpu import knobs

        with pytest.raises(KeyError):
            knobs.env_str("TRINO_TPU_NOT_DECLARED")

    def test_every_source_env_var_is_declared(self):
        """Grep the tree for TRINO_TPU_* literals; each must be a declared
        knob (docstrings and the knobs module itself included — an
        undeclared name anywhere is either a typo or undeclared config)."""
        import re

        from trino_tpu import knobs

        declared = {k.name for k in knobs.ENV_KNOBS}
        pat = re.compile(r"TRINO_TPU_[A-Z_]+")
        undeclared = {}
        root = os.path.join(REPO, "trino_tpu")
        for dirpath, _dirs, files in os.walk(root):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                for name in pat.findall(open(path).read()):
                    if name not in declared:
                        undeclared.setdefault(name, path)
        assert not undeclared, undeclared

    def test_session_defaults_built_from_registry(self):
        from trino_tpu import knobs

        assert set(Session.DEFAULTS) == set(knobs.session_property_names())
        # every declared property carries a non-empty description
        assert all(p.description for p in knobs.SESSION_PROPERTIES)

    def test_validate_plan_defaults_on_under_pytest(self):
        # PYTEST_CURRENT_TEST is set while this test runs
        assert Session().get("validate_plan") is True

    def test_validate_plan_env_override(self, monkeypatch):
        monkeypatch.setenv("TRINO_TPU_VALIDATE_PLAN", "0")
        assert Session().get("validate_plan") is False
        monkeypatch.setenv("TRINO_TPU_VALIDATE_PLAN", "1")
        assert Session().get("validate_plan") is True

    def test_architecture_knob_table_not_drifted(self):
        from trino_tpu import knobs

        doc = open(os.path.join(REPO, "ARCHITECTURE.md")).read()
        assert knobs.knob_table_markdown() in doc, (
            "ARCHITECTURE.md knob table drifted: run "
            "`python -m trino_tpu.knobs --write`"
        )
