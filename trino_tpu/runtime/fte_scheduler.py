"""Event-driven fault-tolerant task scheduler (the FTE control plane).

Reference blueprint: execution/scheduler/faulttolerant/
EventDrivenFaultTolerantQueryScheduler.java:209 — an event loop over task
lifecycle events rather than a sequential per-partition wait — together
with its satellites: TaskExecutionStats-driven speculation, per-query node
exclusion fed by HeartbeatFailureDetector, and ErrorType-classified retry
with capped exponential backoff (SURVEY.md §3.4/§5.3).

What the round-5 control plane got wrong (and this module fixes):

- a SEQUENTIAL per-partition loop: one task at a time, so a stage never
  ran at the cluster's width and one slow task serialized everything →
  all ready attempts of a stage dispatch CONCURRENTLY onto a bounded pool;
- blind ``except Exception`` retries: a CompileError re-ran a query that
  can never succeed → failures classify (runtime/failure.ErrorCategory);
  USER errors fail the query immediately and consume NO retry budget,
  INTERNAL/EXTERNAL re-attempt with capped exponential backoff + jitter;
- fixed-rotation worker choice: ``(fid*31+p+attempt) % len(urls)`` could
  re-pick the exact worker that just failed after ``live_urls`` pruning
  shifted the modulus → picks now exclude the failed attempt's worker
  explicitly and consult a per-query :class:`runtime.nodes.NodeBlacklist`
  (observed failures + heartbeat expiry, timed re-admission);
- an unbounded completion wait: a worker accepting the POST then hanging
  stalled the query forever → every REMOTE attempt carries a deadline
  (``task_completion_timeout``; local in-process attempts stay unbounded
  — the compute runs in this process either way, and a concurrent retry
  would only double device pressure), and stragglers past a percentile-based
  threshold get a SPECULATIVE second attempt on another worker — safe
  because the durable exchange dedups on first commit.

The scheduler also recovers from exchange data corruption: a consumer
failing on a committed-but-undecodable producer attempt triggers
quarantine of that attempt plus a producer re-run (new attempt number),
then the consumer retries — a consumer-only retry would re-read the same
corrupt bytes forever.

Every attempt emits a ``task_attempt`` flight-recorder span (attempt /
worker / outcome labels) and lands in a bounded process-wide attempt log
surfaced as ``system.runtime.task_attempts``.
"""

from __future__ import annotations

import heapq
import itertools
import math
import queue
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .failure import (
    ErrorCategory,
    FailureInjector,
    TaskDeadlineExceeded,
    chaos_fire,
    classify_error,
    retry_backoff,
)
from .nodes import NodeBlacklist
from .observability import RECORDER
from .tracing import TRACER

TaskKey = Tuple[int, int]  # (fragment_id, partition)

# process-wide bounded attempt log: system.runtime.task_attempts reads it
_ATTEMPT_LOG: deque = deque(maxlen=1024)
_ATTEMPT_LOG_LOCK = threading.Lock()

# live schedulers (weak: a finished query's scheduler falls out on its own)
# — the elastic scale controller admits/drains workers across ALL running
# FTE queries through this registry (runtime/ha.ScaleController)
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()


def active_schedulers() -> List["EventDrivenFteScheduler"]:
    return list(_ACTIVE)


def attempt_log() -> List[dict]:
    """Snapshot of recent task attempts (newest last)."""
    with _ATTEMPT_LOG_LOCK:
        return list(_ATTEMPT_LOG)


def _log_attempt(rec: dict) -> None:
    with _ATTEMPT_LOG_LOCK:
        _ATTEMPT_LOG.append(rec)


def _counter(name: str, help_: str):
    from .metrics import REGISTRY

    return REGISTRY.counter(name, help=help_)


@dataclass
class TaskSpec:
    """One schedulable task: a fragment x partition plus the closure that
    executes ONE attempt of it. ``run(attempt, worker, deadline)`` must
    raise on failure; ``worker`` is None for in-process execution."""

    fid: int
    partition: int
    run: Callable[[int, Optional[str], Optional[float]], None]


class _Attempt:
    __slots__ = ("key", "number", "worker", "started", "deadline",
                 "speculative", "abandoned", "released")

    def __init__(self, key: TaskKey, number: int, worker: Optional[str],
                 deadline: Optional[float], speculative: bool):
        self.key = key
        self.number = number
        self.worker = worker
        self.started = time.monotonic()
        self.deadline = deadline
        self.speculative = speculative
        self.abandoned = False
        self.released = False


class _TaskState:
    __slots__ = ("spec", "done", "failures", "next_attempt", "live", "speculated")

    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.done = False
        self.failures = 0       # non-speculative failures (the retry budget)
        self.next_attempt = 0   # monotonic: attempt numbers never reuse
        self.live: Dict[int, _Attempt] = {}
        self.speculated = False


class EventDrivenFteScheduler:
    """Drives one FTE query's task attempts. All state mutation happens on
    the event-loop thread (the caller of :meth:`run_stage`); attempt
    threads only execute the task closure and post completion events, so
    the scheduler itself needs no locks."""

    def __init__(
        self,
        workers: Sequence[str],
        session,
        query_id: str = "",
        blacklist: Optional[NodeBlacklist] = None,
        probe: Optional[Callable[[str], bool]] = None,
        node_manager=None,
    ):
        self.workers = [u.rstrip("/") for u in (workers or [])]
        self.query_id = query_id
        self.blacklist = blacklist or NodeBlacklist(
            ttl=float(session.get("fte_blacklist_ttl") or 60.0)
        )
        self._probe = probe
        self._node_manager = node_manager
        self.max_attempts = max(1, int(session.get("task_retry_attempts") or 2))
        timeout = float(session.get("task_completion_timeout") or 0)
        self.task_timeout = timeout if timeout > 0 else None
        self.concurrency = max(1, int(session.get("fte_task_concurrency") or 8))
        self.retry_initial = float(session.get("fte_retry_initial_delay") or 0.05)
        self.retry_cap = float(session.get("fte_retry_max_delay") or 2.0)
        self.speculation = bool(session.get("fte_speculation_enabled"))
        self.spec_min_secs = float(session.get("fte_speculation_min_secs") or 10.0)
        self.spec_quantile = float(session.get("fte_speculation_quantile") or 0.75)
        self.spec_multiplier = float(session.get("fte_speculation_multiplier") or 4.0)
        self._events: "queue.Queue" = queue.Queue()
        self._specs: Dict[TaskKey, TaskSpec] = {}
        self._states: Dict[TaskKey, _TaskState] = {}
        self._dir_fid: Dict[str, int] = {}
        self._followup: Dict[TaskKey, Set[TaskKey]] = {}
        self._inflight: Dict[str, int] = {u: 0 for u in self.workers}
        self._durations: List[float] = []  # completed attempt wall times
        self._ready: deque = deque()       # dispatches waiting for a slot
        self._retry_heap: List[tuple] = [] # (due, seq, key, exclude)
        self._seq = itertools.count()
        self._running = 0
        self._open: Set[TaskKey] = set()
        # the submitting thread's failure injector rides into attempt threads
        self._injector = FailureInjector.current()
        # observability for tests and EXPLAIN-level consumers
        self.stats = {
            "dispatched": 0, "retries": 0, "speculative": 0, "timeouts": 0,
            "corruption_recoveries": 0, "user_failures": 0,
        }
        # task -> attempt number whose completion won (the statistics
        # feedback plane folds ONLY this attempt's operator actuals into the
        # query-level rollup — losing/abandoned siblings must not double-count)
        self.winners: Dict[TaskKey, int] = {}
        # serving fabric plane (runtime/ha.py): the dispatch journal hook —
        # called with (key, attempt) on every winning commit; a raise is
        # FATAL for the query (a fenced old leader must stop scheduling)
        self.on_winner: Optional[Callable[[TaskKey, int], None]] = None
        # cluster observability plane: the leader epoch this query's
        # attempts dispatch under (set by the runner when cluster_obs + HA
        # are both on); task_attempt spans carry it so a merged post-
        # failover trace distinguishes both epochs. None = no extra arg.
        self.epoch: Optional[int] = None
        # elastic workers: draining urls take no new dispatch (live attempts
        # finish); SUSPECT urls (one missed heartbeat, runtime/nodes.py) are
        # steered around while any alternative exists — a GC pause must not
        # burn an FTE attempt the way a GONE hard-strike would
        self._draining: Set[str] = set()
        self._suspect: Set[str] = set()
        _ACTIVE.add(self)

    # ------------------------------------------------------------------ wiring

    def register_exchange(self, root: str, fid: int) -> None:
        """Exchange dir -> producer fragment (corruption attribution)."""
        self._dir_fid[root] = fid

    # --------------------------------------------------------------- elastic

    def admit_worker(self, url: str) -> bool:
        """Late-join a worker into this RUNNING query (elastic scale-up).
        Safe from any thread: _inflight gains the key BEFORE the url
        becomes pickable, and list/set mutation is atomic in CPython — the
        event loop only ever reads these structures."""
        u = (url or "").rstrip("/")
        if not u or u in self.workers:
            return False
        self._inflight.setdefault(u, 0)
        self._draining.discard(u)
        self.workers.append(u)
        return True

    def drain_worker(self, url: str) -> None:
        """Stop dispatching NEW attempts to ``url``; in-flight attempts
        finish normally (graceful scale-down)."""
        u = (url or "").rstrip("/")
        if u:
            self._draining.add(u)

    def worker_inflight(self, url: str) -> int:
        return self._inflight.get((url or "").rstrip("/"), 0)

    def set_suspects(self, urls) -> None:
        self._suspect = {(u or "").rstrip("/") for u in urls if u}

    # ------------------------------------------------------------------ driving

    def run_stage(self, specs: Sequence[TaskSpec]) -> None:
        """Dispatch every task of one stage concurrently; return when all
        committed. Raises the first fatal error (USER-category failure,
        exhausted retries, or no live workers)."""
        if not specs:
            return
        if self._node_manager is not None:
            fresh = self.blacklist.sync_nodes(self._node_manager)
            if fresh:
                _counter(
                    "trino_tpu_workers_blacklisted_total",
                    "workers blacklisted by the FTE scheduler",
                ).inc(fresh)
            # heartbeat-loss grace window: SUSPECT nodes (one missed
            # announcement) take no NEW dispatch but are never struck —
            # recovery is a fresh announcement, not a blacklist TTL
            from .nodes import suspect_uris

            self.set_suspects(suspect_uris(self._node_manager))
        for s in specs:
            key = (s.fid, s.partition)
            self._specs[key] = s
            state = self._states.get(key)
            if state is None or state.done:
                self._states[key] = _TaskState(s)
            self._open.add(key)
        fatal: Optional[BaseException] = None
        for s in specs:
            fatal = fatal or self._enqueue((s.fid, s.partition), exclude=())
        fatal = fatal if fatal is not None else self._drive()
        if fatal is not None:
            self._abandon_all()
            raise fatal

    def _drive(self) -> Optional[BaseException]:
        """Run the event loop until every open task committed or a fatal
        error surfaced."""
        fatal: Optional[BaseException] = None
        while self._open and fatal is None:
            fatal = self._pump_ready()
            try:
                ev = self._events.get(timeout=self._next_wait())
            except queue.Empty:
                ev = None
            if ev is not None:
                fatal = fatal or self._handle_event(ev)
                # drain whatever else arrived while we were handling
                while fatal is None:
                    try:
                        ev = self._events.get_nowait()
                    except queue.Empty:
                        break
                    fatal = self._handle_event(ev)
            now = time.monotonic()
            fatal = fatal or self._expire_deadlines(now)
            fatal = fatal or self._pump_retries(now)
            if fatal is None and self.speculation:
                self._maybe_speculate(now)
        return fatal

    # ------------------------------------------------------------------ dispatch

    def _enqueue(self, key: TaskKey, exclude: tuple,
                 speculative: bool = False) -> Optional[BaseException]:
        state = self._states.get(key)
        if state is None or state.done:
            # a followup re-dispatch can race a sibling's success (the
            # consumer finished while its producer re-ran): never launch
            # an attempt of a task that is already done
            return None
        if self._running >= self.concurrency:
            self._ready.append((key, exclude, speculative))
            return None
        return self._dispatch(key, exclude, speculative)

    def _pump_ready(self) -> Optional[BaseException]:
        while self._ready and self._running < self.concurrency:
            key, exclude, speculative = self._ready.popleft()
            state = self._states.get(key)
            if state is None or state.done:
                continue
            fatal = self._dispatch(key, exclude, speculative)
            if fatal is not None:
                return fatal
        return None

    def _dispatch(self, key: TaskKey, exclude: tuple,
                  speculative: bool = False) -> Optional[BaseException]:
        state = self._states[key]
        try:
            worker = self._pick_worker(exclude)
        except RuntimeError as e:
            return e
        number = state.next_attempt
        state.next_attempt += 1
        # the deadline bounds the REMOTE completion wait (a worker that
        # accepts the POST then hangs). A local in-process attempt is
        # compute in THIS process: abandoning it leaves the computation
        # running anyway while a concurrent retry doubles device pressure,
        # so local attempts stay unbounded (stragglers are speculation's
        # job, and a legitimately slow local task must be allowed to finish)
        deadline = (
            time.monotonic() + self.task_timeout
            if self.task_timeout and worker is not None
            else None
        )
        att = _Attempt(key, number, worker, deadline, speculative)
        state.live[number] = att
        self._running += 1
        if worker is not None:
            self._inflight[worker] = self._inflight.get(worker, 0) + 1
        self.stats["dispatched"] += 1
        _counter(
            "trino_tpu_task_attempts_total", "FTE task attempts dispatched"
        ).inc()
        spec = self._specs[key]
        # trace parentage captured HERE (the query thread runs the loop)
        run = TRACER.wrap(
            lambda: spec.run(att.number, att.worker, att.deadline)
        )
        thread = threading.Thread(
            target=self._attempt_main,
            args=(att, run),
            daemon=True,  # an abandoned/hung attempt must never pin shutdown
            name=f"fte-{self.query_id}-f{key[0]}p{key[1]}a{number}",
        )
        thread.start()
        return None

    def _attempt_main(self, att: _Attempt, run: Callable[[], None]) -> None:
        spec = self._specs[att.key]
        text = f"{self.query_id}_f{spec.fid}_p{spec.partition}_a{att.number}"
        with FailureInjector.activated(self._injector):
            act = chaos_fire("task_stall", text=text)
            if act is not None:
                time.sleep(float(act.get("delay", 1.0)))
            span_args = dict(
                task=text, fragment=spec.fid, partition=spec.partition,
                attempt=att.number, worker=att.worker or "local",
                speculative=att.speculative,
            )
            if self.epoch is not None:
                span_args["epoch"] = self.epoch
            try:
                with RECORDER.span(
                    "task_attempt", "fte", **span_args
                ) as end:
                    try:
                        run()
                    except BaseException:
                        end["outcome"] = "failed"
                        raise
                    end["outcome"] = "ok"
                self._events.put(("ok", att, None))
            except BaseException as e:  # noqa: BLE001 — loop classifies
                self._events.put(("err", att, e))

    def _pick_worker(self, exclude: tuple) -> Optional[str]:
        """Least-loaded live worker, never the excluded (just-failed) one
        when any alternative exists, steering around the blacklist. When
        every candidate is blacklisted, probe for survivors and re-admit
        them — survival beats purity; zero live workers is fatal."""
        if not self.workers:
            return None  # in-process execution
        candidates = [u for u in self.workers if u not in exclude]
        ok = self.blacklist.filter(candidates)
        # preference ladder: healthy > suspect (missed one heartbeat) —
        # draining workers are held out entirely while ANY alternative
        # exists (graceful scale-down = no new dispatch), and survival
        # still beats purity when everything else is exhausted
        healthy = [
            u for u in ok
            if u not in self._draining and u not in self._suspect
        ]
        not_draining = [u for u in ok if u not in self._draining]
        pool = healthy or not_draining or ok or candidates or list(self.workers)
        if not ok:
            # fell back past the blacklist: verify liveness before re-picking
            # a node we already saw die (satellite: the old fixed rotation
            # could hand a retry straight back to the dead worker)
            if self._probe is not None:
                alive = [u for u in pool if self._probe(u)]
                if not alive:
                    raise RuntimeError("no live workers for FTE retry")
                for u in alive:
                    self.blacklist.readmit(u)
                pool = alive
        return min(pool, key=lambda u: (self._inflight.get(u, 0), u))

    # ------------------------------------------------------------------ events

    def _release(self, att: _Attempt) -> None:
        if att.released:
            return
        att.released = True
        self._running = max(0, self._running - 1)
        if att.worker is not None:
            self._inflight[att.worker] = max(
                0, self._inflight.get(att.worker, 1) - 1
            )

    def _record(self, att: _Attempt, outcome: str, category: str = "") -> None:
        _log_attempt({
            "ts": time.time(),
            "query_id": self.query_id,
            "fragment": att.key[0],
            "partition": att.key[1],
            "attempt": att.number,
            "worker": att.worker or "local",
            "outcome": outcome,
            "category": category,
            "speculative": att.speculative,
            "elapsed_ms": int((time.monotonic() - att.started) * 1000),
        })

    def _handle_event(self, ev: tuple) -> Optional[BaseException]:
        kind, att, exc = ev
        self._release(att)
        state = self._states.get(att.key)
        if kind == "ok":
            if not att.abandoned:
                # a deadline-abandoned attempt's late success would feed
                # its hang time into the straggler percentile and silently
                # disable speculation for the rest of the query
                self._durations.append(time.monotonic() - att.started)
            self._record(att, "ok")
            if state is None:
                return None
            state.live.pop(att.number, None)
            if state.done:
                return None  # late success of an abandoned/sibling attempt
            return self._complete(att.key, state, winner=att.number)
        # failure
        stale = att.abandoned or state is None or state.done
        category = classify_error(exc)
        self._record(att, "stale" if stale else "failed", category.value)
        if stale:
            return None
        state.live.pop(att.number, None)
        return self._handle_failure(att, exc, category)

    def _complete(
        self, key: TaskKey, state: _TaskState, winner: int = -1
    ) -> Optional[BaseException]:
        """First committed attempt wins: the task is done, siblings are
        abandoned (their commits dedup away), blocked consumers re-dispatch."""
        state.done = True
        fenced: Optional[BaseException] = None
        if winner >= 0:
            self.winners[key] = winner
            if self.on_winner is not None:
                try:
                    self.on_winner(key, winner)
                except BaseException as e:  # noqa: BLE001 — fencing is fatal
                    # the dispatch journal refused the write (superseded
                    # epoch): this coordinator lost leadership — finish the
                    # sibling cleanup, then stop scheduling rather than
                    # race the new leader
                    fenced = e
        for sibling in state.live.values():
            sibling.abandoned = True
            # free the loser's concurrency slot NOW: once the task left
            # _open, deadline expiry can never release it, and a hung
            # sibling with no deadline would pin the slot forever
            self._release(sibling)
        state.live.clear()
        self._open.discard(key)
        fatal = fenced
        for consumer in sorted(self._followup.pop(key, ())):
            fatal = fatal or self._enqueue(consumer, exclude=())
        return fatal

    def _handle_failure(
        self, att: _Attempt, exc: BaseException, category: ErrorCategory
    ) -> Optional[BaseException]:
        state = self._states[att.key]
        corruption = self._corruption_info(exc)
        if corruption is not None:
            handled = self._recover_corruption(
                att.key, state, corruption, speculative=att.speculative
            )
            if handled is not True:
                return handled if handled is not None else exc
            return None
        if category is ErrorCategory.USER:
            # the query can never succeed: fail NOW, burn zero retries
            self.stats["user_failures"] += 1
            _counter(
                "trino_tpu_fte_user_failures_total",
                "FTE tasks failed with USER-category errors (never retried)",
            ).inc()
            return exc
        if att.worker is not None:
            # EXTERNAL = the node itself failed us (transport/deadline):
            # blacklist immediately; INTERNAL task errors accumulate strikes
            if self.blacklist.strike(
                att.worker, reason=f"{type(exc).__name__}",
                hard=category is ErrorCategory.EXTERNAL,
            ):
                _counter(
                    "trino_tpu_workers_blacklisted_total",
                    "workers blacklisted by the FTE scheduler",
                ).inc()
        if att.speculative and state.live:
            return None  # the primary is still running; its outcome decides
        if not att.speculative:
            # speculative failures NEVER consume the retry budget: when the
            # primary failed first (deferring to the live speculative
            # sibling), the sibling's later failure must still leave the
            # primary's remaining retries dispatchable
            state.failures += 1
        if state.live:
            # a sibling attempt is still live — let it decide before
            # spending more budget
            return None
        if state.failures >= self.max_attempts:
            return exc
        self.stats["retries"] += 1
        _counter(
            "trino_tpu_task_retries_total",
            "FTE task retries after classified retryable failures",
        ).inc()
        delay = retry_backoff(state.failures, self.retry_initial, self.retry_cap)
        exclude = (att.worker,) if att.worker is not None else ()
        heapq.heappush(
            self._retry_heap,
            (time.monotonic() + delay, next(self._seq), att.key, exclude),
        )
        return None

    # ------------------------------------------------------ corruption recovery

    def _corruption_info(self, exc: BaseException) -> Optional[dict]:
        from .exchange_spi import ExchangeDataCorruption, parse_corruption

        if isinstance(exc, ExchangeDataCorruption):
            return {
                "dir": exc.root, "partition": exc.partition,
                "attempt": exc.attempt,
            }
        text = getattr(exc, "error_text", None)
        return parse_corruption(text) if text else None

    def _producer_key(self, info: Optional[dict]) -> Optional[TaskKey]:
        """Corruption info -> the producer task that must re-run, or None
        when the exchange dir / fragment is unknown to this scheduler."""
        if info is None:
            return None
        pfid = self._dir_fid.get(info["dir"])
        if pfid is None:
            return None
        pkey = (pfid, info["partition"])
        return pkey if pkey in self._specs else None

    def _quarantine_and_rerun_producer(
        self, pkey: TaskKey, info: dict, rerun: bool = True
    ) -> Optional[BaseException]:
        """Shared core of both corruption paths: count the recovery, hide
        the corrupt committed attempt from selection, and give its producer
        a fresh attempt (attempt numbers stay monotonic when the producer's
        state survives; a producer already re-running is left alone)."""
        from .exchange_spi import exchange_for

        self.stats["corruption_recoveries"] += 1
        _counter(
            "trino_tpu_exchange_corruption_recoveries_total",
            "corrupt committed attempts quarantined and re-produced",
        ).inc()
        exchange_for(info["dir"]).quarantine_attempt(
            info["partition"], info.get("attempt")
        )
        if not rerun:
            return None
        pstate = self._states.get(pkey)
        if pstate is not None and not pstate.done:
            return None  # already re-running (a sibling consumer's recovery)
        if pstate is None:
            self._states[pkey] = pstate = _TaskState(self._specs[pkey])
        pstate.done = False
        self._open.add(pkey)
        return self._enqueue(pkey, exclude=())

    def _recover_corruption(self, key: TaskKey, state: _TaskState, info: dict,
                            speculative: bool = False):
        """Quarantine the corrupt committed attempt, re-run its PRODUCER,
        then retry the consumer once the fresh attempt is committed.
        Returns True when recovery is underway, an exception when the
        consumer's budget is exhausted, None when unattributable."""
        pkey = self._producer_key(info)
        if pkey is None:
            return None
        if key in self._followup.get(pkey, set()):
            # recovery already underway for this consumer — its SIBLING hit
            # the same corrupt attempt first. Don't double-count budget or
            # metrics; the followup re-dispatch covers this failure too.
            return True
        if not speculative:
            # same contract as _handle_failure: speculative failures never
            # consume the consumer's retry budget
            state.failures += 1
            if state.failures >= self.max_attempts:
                # still quarantine (the corrupt bytes must never be
                # re-served) but don't waste a producer re-run: the query
                # is failing
                self._quarantine_and_rerun_producer(pkey, info, rerun=False)
                return RuntimeError(
                    f"task f{key[0]}/p{key[1]} exhausted attempts on "
                    f"exchange corruption in {info['dir']} "
                    f"p{info['partition']}"
                )
        self.stats["retries"] += 1
        _counter(
            "trino_tpu_task_retries_total",
            "FTE task retries after classified retryable failures",
        ).inc()
        # the consumer re-dispatches when the producer's fresh attempt lands
        self._followup.setdefault(pkey, set()).add(key)
        fatal = self._quarantine_and_rerun_producer(pkey, info)
        if fatal is not None:
            return fatal
        return True

    def recover_exchange_corruption(self, exc: BaseException) -> None:
        """Coordinator-side twin of :meth:`_recover_corruption` for
        corruption detected OUTSIDE any task attempt: the ROOT fragment's
        gathered output and REPARTITION_RANGE edges are read by the
        coordinator itself, so no consumer task exists whose failure would
        trigger recovery. Quarantines the corrupt committed attempt and
        re-runs its producer to a fresh durable commit (blocks until
        committed); re-raises ``exc`` when the producer is unknown."""
        info = self._corruption_info(exc)
        pkey = self._producer_key(info)
        if pkey is None:
            raise exc  # unattributable: nothing to re-run
        fatal = self._quarantine_and_rerun_producer(pkey, info)
        fatal = fatal if fatal is not None else self._drive()
        if fatal is not None:
            self._abandon_all()
            raise fatal

    # ------------------------------------------------------------------ timers

    def _next_wait(self) -> float:
        now = time.monotonic()
        horizon = now + 0.25
        if self._retry_heap:
            horizon = min(horizon, self._retry_heap[0][0])
        for state in self._states.values():
            for att in state.live.values():
                if att.deadline is not None and not att.abandoned:
                    horizon = min(horizon, att.deadline)
        if self.speculation and self._durations and self._running:
            # wake exactly when the oldest sole-live attempt could cross
            # the straggler threshold — not a fixed 20 Hz poll
            threshold = self._straggler_threshold()
            if threshold is not None:
                for state in self._states.values():
                    if state.done or state.speculated:
                        continue
                    live = [
                        a for a in state.live.values() if not a.abandoned
                    ]
                    if len(live) == 1 and not live[0].speculative:
                        horizon = min(horizon, live[0].started + threshold)
        return min(0.5, max(0.01, horizon - now))

    def _expire_deadlines(self, now: float) -> Optional[BaseException]:
        fatal = None
        for key in list(self._open):
            state = self._states.get(key)
            if state is None or state.done:
                continue
            for number, att in list(state.live.items()):
                if att.deadline is None or att.abandoned or now < att.deadline:
                    continue
                # the attempt is HUNG: abandon it (its thread keeps running;
                # a late commit just dedups away) and treat as EXTERNAL
                att.abandoned = True
                state.live.pop(number, None)
                self._release(att)
                self.stats["timeouts"] += 1
                self._record(att, "timeout", ErrorCategory.EXTERNAL.value)
                exc = TaskDeadlineExceeded(
                    f"task f{key[0]}/p{key[1]} attempt {number} exceeded "
                    f"task_completion_timeout on {att.worker or 'local'}"
                )
                fatal = fatal or self._handle_failure(
                    att, exc, ErrorCategory.EXTERNAL
                )
        return fatal

    def _pump_retries(self, now: float) -> Optional[BaseException]:
        fatal = None
        while self._retry_heap and self._retry_heap[0][0] <= now:
            _, _, key, exclude = heapq.heappop(self._retry_heap)
            state = self._states.get(key)
            if state is None or state.done:
                continue
            fatal = fatal or self._enqueue(key, exclude)
        return fatal

    # -------------------------------------------------------------- speculation

    def _straggler_threshold(self) -> Optional[float]:
        if not self._durations:
            return None
        ordered = sorted(self._durations)
        # nearest-rank P-quantile: ceil(q*n)-1. int(q*n) is one rank too
        # high whenever q*n is integral (4 samples at q=0.75 would pick
        # the MAX, silently inflating the speculation threshold)
        idx = min(
            len(ordered) - 1,
            max(0, math.ceil(len(ordered) * self.spec_quantile) - 1),
        )
        return max(self.spec_min_secs, ordered[idx] * self.spec_multiplier)

    def _maybe_speculate(self, now: float) -> None:
        """A task whose sole attempt has run past the percentile-derived
        straggler threshold gets ONE speculative sibling on a different
        worker (ref: the scheduler's speculative execution over
        TaskExecutionStats). First commit wins; the loser dedups away."""
        threshold = self._straggler_threshold()
        if threshold is None:
            return
        for key in list(self._open):
            state = self._states.get(key)
            if state is None or state.done or state.speculated:
                continue
            live = [a for a in state.live.values() if not a.abandoned]
            if len(live) != 1 or live[0].speculative:
                continue
            primary = live[0]
            if now - primary.started < threshold:
                continue
            if self._running >= self.concurrency:
                return
            exclude = (primary.worker,) if primary.worker is not None else ()
            if self.workers and not self.blacklist.filter(
                [u for u in self.workers if u not in exclude]
            ):
                # every candidate sibling target is blacklisted: skip this
                # tick WITHOUT falling through to _pick_worker's blocking
                # liveness probes (speculation is an optimization — probing
                # dead nodes from the event loop every tick would stall
                # deadline/completion handling for the whole query);
                # `speculated` stays unset so ttl re-admission re-enables it
                continue
            if self._dispatch(key, exclude, speculative=True) is not None:
                # no dispatchable worker RIGHT NOW: NOT fatal (the primary
                # is still running) and `speculated` stays unset so the
                # straggler can still get its sibling once workers re-admit
                continue
            state.speculated = True
            self.stats["speculative"] += 1
            _counter(
                "trino_tpu_speculative_attempts_total",
                "speculative FTE task attempts launched for stragglers",
            ).inc()
            RECORDER.instant(
                "speculative_attempt", "fte",
                fragment=key[0], partition=key[1],
                straggler_secs=round(now - primary.started, 3),
            )

    # ------------------------------------------------------------------ cleanup

    def _abandon_all(self) -> None:
        for state in self._states.values():
            for att in state.live.values():
                att.abandoned = True
                self._release(att)
            state.live.clear()
        self._ready.clear()
        self._retry_heap.clear()
        self._open.clear()
