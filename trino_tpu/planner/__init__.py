from .plan import LogicalPlan, format_plan
from .logical_planner import LogicalPlanner, SemanticError
from .optimizer import optimize
