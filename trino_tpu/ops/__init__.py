from . import kernels
from .compiler import compile_expression, ColumnLayout, CVal, CompileError
