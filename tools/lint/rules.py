"""Engine-specific lint rules over the trino_tpu AST.

Each rule encodes an invariant one of the concurrency/observability planes
depends on; ids are stable (they appear in baselines and suppressions):

- ``blocking-call-under-lock``   no sleep / foreign Condition.wait / file or
                                 HTTP I/O / nested lock acquire while holding
                                 a lock (the FTE event loop and the memory
                                 arbiter both assume lock-brief sections)
- ``unpaired-flight-span``       ``RECORDER.span(...)`` must be entered as a
                                 ``with`` context manager so the B always
                                 gets its E (the obs_smoke pairing contract,
                                 enforced at the source instead of per-trace)
- ``metric-help-missing``        REGISTRY.counter/gauge/histogram call sites
                                 always pass a non-empty ``help`` kwarg (the
                                 HELP-registered-family contract; the runtime
                                 half is registry_help_problems below)
- ``env-read-outside-knobs``     ``TRINO_TPU_*`` environment reads go through
                                 the central knob registry (trino_tpu/knobs.py)
- ``bare-except-swallow``        no bare ``except:`` anywhere, and no
                                 ``except ...: pass`` swallow in scheduler/
                                 executor paths (a swallowed failure there
                                 becomes a hang or a wrong answer)
- ``undeclared-session-property`` literal ``session.get("...")`` names must
                                 be declared in the knob registry (catches
                                 typo'd knobs that silently KeyError at
                                 runtime)
- ``unnamed-thread``             every ``threading.Thread(...)`` constructed
                                 in the engine passes ``name=`` — thread
                                 names are the host-profile/cluster-trace
                                 lane identity (clusterobs canonical tids
                                 sort by name; hostprof collapses stacks per
                                 name), so a ``Thread-12`` default makes the
                                 lane unattributable
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .engine import Finding

# --------------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------------- #


def _attr_chain(node: ast.AST) -> str:
    """Dotted name for Name/Attribute chains ('self._lock', 'time.sleep')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = _attr_chain(node.func)
        parts.append(f"{inner}()")
    return ".".join(reversed(parts))


def _base_obj(chain: str) -> str:
    """'self._cond.wait' -> 'self._cond'; 'pool.acquire' -> 'pool'."""
    return chain.rsplit(".", 1)[0] if "." in chain else chain


def _looks_like_lock(chain: str) -> bool:
    last = chain.rsplit(".", 1)[-1].lower()
    if "io_lock" in last or "iolock" in last:
        # the sanctioned dedicated-I/O-serialization-lock pattern (cachestore
        # persistence, event-listener appends): blocking under it is its ONLY
        # job and no shared state may hide behind it — reviewed by name
        return False
    return "lock" in last or "mutex" in last


def rule(id_: str, description: str):
    def deco(fn):
        fn.id = id_
        fn.description = description
        return fn
    return deco


# --------------------------------------------------------------------------- #
# blocking-call-under-lock
# --------------------------------------------------------------------------- #

_SLEEPS = {"time.sleep", "sleep"}
_IO_CALLS = {
    "open", "urlopen", "urllib.request.urlopen", "requests.get",
    "requests.post", "requests.request",
}
_IO_METHOD_SUFFIXES = ("getresponse", "urlopen")


@rule(
    "blocking-call-under-lock",
    "sleep / foreign Condition.wait / file or HTTP I/O / nested lock acquire "
    "while holding a lock",
)
def blocking_call_under_lock(tree: ast.AST, source_lines: Sequence[str],
                             path: str) -> List[Finding]:
    findings: List[Finding] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            # stack of lock object chains currently held via `with`
            self.held: List[str] = []

        def visit_With(self, node: ast.With):
            locks = []
            for item in node.items:
                ctx = item.context_expr
                chain = _attr_chain(ctx.func) if isinstance(ctx, ast.Call) else _attr_chain(ctx)
                # `with lock:` / `with self._lock:` / `with cond:` — treat
                # Condition objects as locks too (entering one acquires it)
                if _looks_like_lock(chain) or "cond" in chain.rsplit(".", 1)[-1].lower():
                    locks.append(chain)
            self.held.extend(locks)
            self.generic_visit(node)
            for _ in locks:
                self.held.pop()

        # a nested def/lambda runs later, not under the lock
        def visit_FunctionDef(self, node):
            saved, self.held = self.held, []
            self.generic_visit(node)
            self.held = saved

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            saved, self.held = self.held, []
            self.generic_visit(node)
            self.held = saved

        def visit_Call(self, node: ast.Call):
            if self.held:
                chain = _attr_chain(node.func)
                leaf = chain.rsplit(".", 1)[-1]
                base = _base_obj(chain)
                problem: Optional[str] = None
                if chain in _SLEEPS:
                    problem = f"sleep under lock {self.held[-1]!r}"
                elif leaf == "wait" and base not in self.held:
                    # cond.wait() inside `with cond:` releases that lock —
                    # fine; waiting on a DIFFERENT condition while holding
                    # this lock blocks everyone behind it
                    problem = (
                        f"wait on {base!r} while holding {self.held[-1]!r}"
                    )
                elif leaf == "acquire" and base not in self.held:
                    problem = (
                        f"nested acquire of {base!r} while holding "
                        f"{self.held[-1]!r}"
                    )
                elif chain in _IO_CALLS or leaf in _IO_METHOD_SUFFIXES:
                    problem = (
                        f"{chain or leaf}() I/O under lock {self.held[-1]!r}"
                    )
                if problem:
                    findings.append(Finding(
                        path, node.lineno, blocking_call_under_lock.id, problem
                    ))
            self.generic_visit(node)

    V().visit(tree)
    return findings


# --------------------------------------------------------------------------- #
# unpaired-flight-span
# --------------------------------------------------------------------------- #

_SPAN_OWNERS = {"RECORDER", "TRACER"}


@rule(
    "unpaired-flight-span",
    "flight-recorder/tracer span calls must be entered as `with` context "
    "managers so every B event gets its E on all code paths",
)
def unpaired_flight_span(tree: ast.AST, source_lines: Sequence[str],
                         path: str) -> List[Finding]:
    findings: List[Finding] = []
    with_items = set()
    returns = set()

    class Collect(ast.NodeVisitor):
        def visit_With(self, node: ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    with_items.add(id(item.context_expr))
            self.generic_visit(node)

        def visit_Return(self, node: ast.Return):
            if isinstance(node.value, ast.Call):
                returns.add(id(node.value))
            self.generic_visit(node)

    Collect().visit(tree)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "span"):
            continue
        owner = _attr_chain(func.value)
        leaf_owner = owner.rsplit(".", 1)[-1]
        if leaf_owner not in _SPAN_OWNERS:
            continue
        if id(node) in with_items:
            continue
        if id(node) in returns:
            # a helper returning the context manager for its caller to
            # `with` — pairing is the caller's job; flag it so the author
            # must either suppress with a reason or restructure
            findings.append(Finding(
                path, node.lineno, unpaired_flight_span.id,
                f"{owner}.span(...) returned instead of entered — pairing "
                "depends on every caller using `with`",
            ))
        else:
            findings.append(Finding(
                path, node.lineno, unpaired_flight_span.id,
                f"{owner}.span(...) not entered via `with` — the B/E pair "
                "is not guaranteed on all code paths",
            ))
    return findings


# --------------------------------------------------------------------------- #
# metric-help-missing (AST half of the HELP lint; runtime half below)
# --------------------------------------------------------------------------- #

_METRIC_CTORS = {"counter", "gauge", "histogram"}


@rule(
    "metric-help-missing",
    "REGISTRY.counter/gauge/histogram call sites must pass a non-empty help "
    "kwarg (every series exported with HELP text)",
)
def metric_help_missing(tree: ast.AST, source_lines: Sequence[str],
                        path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and func.attr in _METRIC_CTORS
            and isinstance(func.value, ast.Name)
            and func.value.id in ("REGISTRY", "registry", "reg")
        ):
            continue
        help_kw = next((k for k in node.keywords if k.arg == "help"), None)
        if help_kw is None:
            # positional help (counter(name, labels, help)): the LAST string
            # constant after the name plays the help role in the registry
            # signature — single-word help is fine, empty is not
            positional = [
                a for a in node.args[1:]
                if isinstance(a, ast.Constant) and isinstance(a.value, str)
            ]
            if positional:
                if not positional[-1].value:
                    findings.append(Finding(
                        path, node.lineno, metric_help_missing.id,
                        f"{func.value.id}.{func.attr}(...) with an EMPTY "
                        "positional help string",
                    ))
                continue
            findings.append(Finding(
                path, node.lineno, metric_help_missing.id,
                f"{func.value.id}.{func.attr}(...) without a help kwarg",
            ))
        elif isinstance(help_kw.value, ast.Constant) and not help_kw.value.value:
            findings.append(Finding(
                path, node.lineno, metric_help_missing.id,
                f"{func.value.id}.{func.attr}(...) with an EMPTY help string",
            ))
    return findings


def registry_help_problems(registry=None, required: Sequence[str] = ()) -> List[str]:
    """Runtime half of the HELP lint (the registry contract): every collected
    series carries HELP text, and every ``required`` family is registered.
    Shared by tools/obs_smoke.py and tests — the single implementation the
    old per-plane copies collapsed into."""
    if registry is None:
        from trino_tpu.runtime.metrics import REGISTRY as registry  # noqa: N813
    problems: List[str] = []
    by_name = {}
    for m in registry.collect():
        by_name.setdefault(m["name"], m)
        if not m["help"]:
            problems.append(f"metric {m['name']} missing HELP text")
    for name in required:
        if name not in by_name:
            problems.append(f"metric {name} not registered")
    return sorted(set(problems))


# --------------------------------------------------------------------------- #
# metric-name-conformance
# --------------------------------------------------------------------------- #


@rule(
    "metric-name-conformance",
    "counter names must end in _total (Prometheus convention) and "
    "histogram registrations must declare their bucket bounds explicitly",
)
def metric_name_conformance(tree: ast.AST, source_lines: Sequence[str],
                            path: str) -> List[Finding]:
    """Two conformance halves of the federated-metrics contract:

    - every COUNTER whose name is a literal ends in ``_total`` — the
      cluster exposition merges per-node series by name, and scrape-side
      rate() math assumes the convention;
    - every ``REGISTRY.histogram(...)`` call declares ``buckets=``
      explicitly — cross-node histogram merging requires agreeing bounds,
      and an implicit default at one call site drifts silently when the
      default changes.

    Counter detection covers both the registry surface (``REGISTRY.counter``)
    and the per-module ``_counter("trino_tpu_...", help)`` wrappers: any
    call whose callee name is/ends with ``counter`` with a literal first
    argument starting ``trino_tpu_`` is a metric registration."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        leaf = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else ""
        )
        registry_owner = (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("REGISTRY", "registry", "reg")
        )
        name = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
        if leaf.endswith("counter") and leaf not in ("_get",):
            is_metric = registry_owner or (
                name is not None and name.startswith("trino_tpu_")
            )
            if is_metric and name is not None and not name.endswith("_total"):
                findings.append(Finding(
                    path, node.lineno, metric_name_conformance.id,
                    f"counter {name!r} does not end in _total",
                ))
        elif leaf == "histogram" and registry_owner:
            has_buckets = any(k.arg == "buckets" for k in node.keywords) \
                or len(node.args) >= 4
            if not has_buckets:
                findings.append(Finding(
                    path, node.lineno, metric_name_conformance.id,
                    f"histogram {name or '<dynamic>'!r} does not declare "
                    "buckets= explicitly",
                ))
    return findings


# --------------------------------------------------------------------------- #
# env-read-outside-knobs
# --------------------------------------------------------------------------- #


@rule(
    "env-read-outside-knobs",
    "TRINO_TPU_* environment reads must go through the central knob "
    "registry (trino_tpu/knobs.py)",
)
def env_read_outside_knobs(tree: ast.AST, source_lines: Sequence[str],
                           path: str) -> List[Finding]:
    if path.replace("\\", "/").endswith("trino_tpu/knobs.py"):
        return []
    findings: List[Finding] = []

    def is_environ(node: ast.AST) -> bool:
        chain = _attr_chain(node)
        return chain in ("os.environ", "environ")

    def tpu_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value.startswith("TRINO_TPU_"):
                return node.value
        return None

    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Subscript) and is_environ(node.value):
            name = tpu_name(node.slice)
            # os.environ[SOME_ENV_CONST]: same module-constant resolution
            # as the .get(...) form below
            if name is None and isinstance(node.slice, ast.Name):
                name = _module_env_const(tree, node.slice.id)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
                name = tpu_name(node.args[0]) if node.args else None
                # os.environ.get(SOME_ENV_CONST): resolve simple Name args
                # against module-level "X = 'TRINO_TPU_...'" assignments
                if name is None and node.args and isinstance(node.args[0], ast.Name):
                    name = _module_env_const(tree, node.args[0].id)
        if name:
            findings.append(Finding(
                path, node.lineno, env_read_outside_knobs.id,
                f"direct environment read of {name} — use trino_tpu.knobs",
            ))
    return findings


def _module_env_const(tree: ast.AST, ident: str) -> Optional[str]:
    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if (isinstance(tgt, ast.Name) and tgt.id == ident
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)
                        and stmt.value.value.startswith("TRINO_TPU_")):
                    return stmt.value.value
    return None


# --------------------------------------------------------------------------- #
# bare-except-swallow
# --------------------------------------------------------------------------- #

# scheduler/executor paths where a swallowed exception becomes a hang or a
# wrong answer instead of a logged anomaly
_CRITICAL_PATH_PARTS = (
    "runtime/fte_scheduler.py", "runtime/executor.py",
    "runtime/query_manager.py", "parallel/runner.py", "server/worker.py",
    "runtime/fte_plane.py",
)


@rule(
    "bare-except-swallow",
    "no bare `except:`; no `except ...: pass` swallow in scheduler/executor "
    "paths",
)
def bare_except_swallow(tree: ast.AST, source_lines: Sequence[str],
                        path: str) -> List[Finding]:
    findings: List[Finding] = []
    norm = path.replace("\\", "/")
    critical = any(norm.endswith(p) for p in _CRITICAL_PATH_PARTS)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                path, node.lineno, bare_except_swallow.id,
                "bare `except:` catches KeyboardInterrupt/SystemExit too",
            ))
            continue
        if not critical:
            continue
        body = node.body
        swallows = all(
            isinstance(s, ast.Pass)
            or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
            for s in body
        )
        if swallows:
            exc = _attr_chain(node.type) if not isinstance(node.type, ast.Tuple) else "(...)"
            findings.append(Finding(
                path, node.lineno, bare_except_swallow.id,
                f"except {exc}: pass swallows failures on a scheduler/"
                "executor path",
            ))
    return findings


# --------------------------------------------------------------------------- #
# undeclared-session-property
# --------------------------------------------------------------------------- #


@rule(
    "undeclared-session-property",
    "literal session.get()/set() property names must be declared in "
    "trino_tpu.knobs.SESSION_PROPERTIES",
)
def undeclared_session_property(tree: ast.AST, source_lines: Sequence[str],
                                path: str) -> List[Finding]:
    from trino_tpu import knobs

    declared = knobs.session_property_names()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr in ("get", "set")):
            continue
        owner = _attr_chain(func.value)
        if not owner.endswith("session"):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in declared:
                findings.append(Finding(
                    path, node.lineno, undeclared_session_property.id,
                    f"session property {arg.value!r} is not declared in the "
                    "knob registry",
                ))
    return findings


# --------------------------------------------------------------------------- #
# unnamed-thread
# --------------------------------------------------------------------------- #


@rule(
    "unnamed-thread",
    "threading.Thread construction must pass name= — thread names are the "
    "host-profile and cluster-trace lane identity",
)
def unnamed_thread(tree: ast.AST, source_lines: Sequence[str],
                   path: str) -> List[Finding]:
    """The host-path observability plane keys everything on thread names:
    hostprof collapses sampled stacks per ``threading.Thread.name``, and
    clusterobs assigns canonical trace tids by sorted (name, first-activity).
    A default ``Thread-12`` name is nondeterministic across runs and says
    nothing about the lane, so every ``Thread(...)`` / ``threading.Thread``
    / ``_th.Thread`` construction in the engine must pass ``name=``."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not (chain == "Thread" or chain.endswith(".Thread")):
            continue
        if any(k.arg == "name" for k in node.keywords):
            continue
        if any(k.arg is None for k in node.keywords):
            # Thread(**kwargs) forwarding — the name may ride the dict;
            # resolving that statically is out of scope, don't flag
            continue
        findings.append(Finding(
            path, node.lineno, unnamed_thread.id,
            f"{chain}(...) without name= — unnamed threads are invisible "
            "to the host-profile/cluster-trace lane contract",
        ))
    return findings


# --------------------------------------------------------------------------- #
# pallas-call-outside-ops
# --------------------------------------------------------------------------- #


@rule(
    "pallas-call-outside-ops",
    "direct pl.pallas_call launches belong in trino_tpu/ops/ — runtime code "
    "goes through the megakernel/compiler layer so pallas_compile/"
    "pallas_launch spans and fallback accounting cannot be skipped",
)
def pallas_call_outside_ops(tree: ast.AST, source_lines: Sequence[str],
                            path: str) -> List[Finding]:
    """Every kernel launch must route through the ops/ kernel layer
    (ops/pallas_kernels.py, ops/megakernels.py): that layer owns the paired
    flight spans, the launch/fallback counters, and the interpret-mode
    bit-identity contract. A ``pl.pallas_call`` (or
    ``pallas.pallas_call`` / bare ``pallas_call``) anywhere else in the
    engine dodges all three."""
    norm = path.replace("\\", "/")
    if "/ops/" in norm or norm.startswith("ops/"):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain == "pallas_call" or chain.endswith(".pallas_call"):
            findings.append(Finding(
                path, node.lineno, pallas_call_outside_ops.id,
                "direct pl.pallas_call outside trino_tpu/ops/ — launch "
                "through the megakernel/compiler layer",
            ))
    return findings


# --------------------------------------------------------------------------- #
# jit-without-cost-hook
# --------------------------------------------------------------------------- #


@rule(
    "jit-without-cost-hook",
    "raw jax.jit call sites bypass the kernel cost plane — use "
    "runtime/kernelcost.jit (same signature) so the program's XLA cost "
    "analysis attributes to the launching plan node",
)
def jit_without_cost_hook(tree: ast.AST, source_lines: Sequence[str],
                          path: str) -> List[Finding]:
    """Every jitted engine program must compile through the
    ``kernelcost.jit`` wrapper: it is a transparent pass-through until a
    recording scope is active, and it is the ONLY place the engine can
    observe a program's FLOPs / HBM bytes / peak device memory. A raw
    ``jax.jit`` — as a call, a decorator, or a ``partial(jax.jit, ...)``
    argument — produces a program the cost plane can never attribute. The
    one sanctioned site is inside kernelcost.CostJit itself (inline
    suppression with reason)."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and _attr_chain(node) == "jax.jit":
            findings.append(Finding(
                path, node.lineno, jit_without_cost_hook.id,
                "raw jax.jit bypasses the cost-recording wrapper — use "
                "trino_tpu.runtime.kernelcost.jit",
            ))
    return findings


ALL_RULES = (
    blocking_call_under_lock,
    unpaired_flight_span,
    metric_help_missing,
    metric_name_conformance,
    env_read_outside_knobs,
    bare_except_swallow,
    undeclared_session_property,
    unnamed_thread,
    pallas_call_outside_ops,
    jit_without_cost_hook,
)
