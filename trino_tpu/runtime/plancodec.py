"""Schema'd plan/IR codec for the worker control plane.

Reference blueprint: Trino ships plan fragments between coordinator and
workers as JSON (PlanFragment and every PlanNode/Expression are
Jackson-annotated, server/remotetask/HttpRemoteTask.java:743) — NEVER as
executable serialization. This codec does the same for the TPU engine:
frozen-dataclass plan nodes, IR expressions, types, and predicate domains
encode to tagged JSON; decoding instantiates only classes from the fixed
registry below, so a hostile payload cannot execute code (the pickle codec it
replaces was remote code execution for anyone who could reach a worker port).
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
import json
from typing import Any, Dict

import numpy as np


def _registry() -> Dict[str, type]:
    from ..planner import fragmenter as frag_mod
    from ..planner import plan as plan_mod
    from ..spi import connector as conn_mod
    from ..spi import predicate as pred_mod
    from ..spi import types as types_mod
    from ..sql import ir as ir_mod

    reg: Dict[str, type] = {}
    for mod in (plan_mod, frag_mod, ir_mod, types_mod, pred_mod, conn_mod):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and (
                dataclasses.is_dataclass(obj) or issubclass(obj, enum.Enum)
            ):
                key = f"{obj.__module__.rsplit('.', 1)[-1]}.{obj.__name__}"
                reg[key] = obj
    return reg


_REG: Dict[str, type] = {}


def _reg() -> Dict[str, type]:
    global _REG
    if not _REG:
        _REG = _registry()
    return _REG


def _key_of(cls: type) -> str:
    return f"{cls.__module__.rsplit('.', 1)[-1]}.{cls.__name__}"


def encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj
    if isinstance(obj, enum.Enum):
        return {"@e": _key_of(type(obj)), "v": obj.name}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        key = _key_of(type(obj))
        if key not in _reg():
            raise TypeError(f"unregistered dataclass {key}")
        fields = {
            f.name: encode(getattr(obj, f.name)) for f in dataclasses.fields(obj)
        }
        return {"@t": key, "f": fields}
    if isinstance(obj, tuple):
        return {"@u": [encode(x) for x in obj]}
    if isinstance(obj, list):
        return [encode(x) for x in obj]
    if isinstance(obj, dict):
        return {"@m": [[encode(k), encode(v)] for k, v in obj.items()]}
    if isinstance(obj, np.ndarray):
        return {"@np": obj.dtype.str, "v": obj.tolist()}
    if isinstance(obj, np.generic):
        return encode(obj.item())
    if isinstance(obj, datetime.datetime):
        return {"@ts": obj.isoformat()}
    if isinstance(obj, datetime.date):
        return {"@dt": obj.isoformat()}
    raise TypeError(f"cannot encode {type(obj).__name__}")


def decode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode(x) for x in obj]
    if isinstance(obj, dict):
        if "@t" in obj:
            cls = _reg().get(obj["@t"])
            if cls is None:
                raise ValueError(f"unknown plan class {obj['@t']!r}")
            return cls(**{k: decode(v) for k, v in obj["f"].items()})
        if "@e" in obj:
            cls = _reg().get(obj["@e"])
            if cls is None:
                raise ValueError(f"unknown enum {obj['@e']!r}")
            return cls[obj["v"]]
        if "@u" in obj:
            return tuple(decode(x) for x in obj["@u"])
        if "@m" in obj:
            return {decode(k): decode(v) for k, v in obj["@m"]}
        if "@np" in obj:
            return np.asarray(obj["v"], dtype=np.dtype(obj["@np"]))
        if "@ts" in obj:
            return datetime.datetime.fromisoformat(obj["@ts"])
        if "@dt" in obj:
            return datetime.date.fromisoformat(obj["@dt"])
        raise ValueError(f"untagged object {list(obj)[:3]}")
    raise ValueError(f"cannot decode {type(obj).__name__}")


def dumps(obj: Any) -> bytes:
    return json.dumps(encode(obj), separators=(",", ":")).encode()


def fingerprint(obj: Any) -> str:
    """Structural content hash of anything the codec can encode (plan
    subtrees, fragments). THE fingerprint function of the engine: capstore
    keys capacity vectors on it and the statistics feedback plane
    (runtime/statstore.py) keys estimate-vs-actual history on it, so both
    stores agree on what "the same plan shape" means. Empty string when the
    object holds types outside the registry — no key, no persistence."""
    import hashlib

    try:
        blob = dumps(obj)
    except Exception:  # noqa: BLE001 — a fingerprint failure must only ever
        # mean "no persistence": encode recurses through arbitrary node
        # fields (RecursionError on 1000-conjunct chains, AttributeError
        # from a property), and both capstore and statstore callers sit on
        # query paths that must not fail for a missing cache key
        return ""
    return hashlib.sha256(blob).hexdigest()


def loads(data: bytes) -> Any:
    return decode(json.loads(data))
