"""Out-of-core execution for ARBITRARY fragment trees — joins included.

Round-4 verdict: `runtime/streaming.py` streams exactly one plan shape
(scan -> filter/project -> one aggregation), so no join had ever executed
above SF1. The reference streams *any* operator pipeline over
larger-than-memory data (operator/Driver.java:372 page pull;
operator/join/spilling/HashBuilderOperator.java:68 partitioned spill state
machine; SpillableHashAggregationBuilder). This module is the TPU-first
generalization: the distributed fragmenter's stage cut IS the out-of-core
execution plan, run on ONE chip with a disk-spillable host bucket store as
the exchange:

- `add_exchanges` + `create_fragments` (planner/fragmenter.py) already cut
  the plan at repartition boundaries and split aggregations into
  partial/final — exactly the decomposition grace hash join / partitioned
  aggregation needs. Nothing is re-derived here.
- A producer fragment never materializes its output: each execution unit's
  output page is fetched, hash-bucketed on host (the SAME value-stable rule
  the DCN exchange uses, parallel/runner.host_partition_targets), and
  appended to a `BucketStore` that overflows to disk beyond a byte budget.
- SOURCE fragments iterate scan splits in BATCHES of K splits per device
  dispatch (round-4's 985 s Q1-SF100 combine loop was one dispatch per
  split; batching amortizes dispatch + program constant costs). Broadcast
  build sides (CBO-chosen small relations) materialize once per batch from
  the store.
- FIXED_HASH fragments run bucket-at-a-time: every input edge of bucket b
  is co-partitioned by construction, so join build+probe and final
  aggregation see complete key groups. Device memory is bounded by the
  largest single bucket, not the table (SF100 lineitem / 64 buckets ≈
  hundreds of MB vs ~17 GB > HBM).
- SINGLE fragments (query tails: final TopN/sort/output) gather the tiny
  upstream results and run once.

Static-shape discipline: executor programs are compiled per capacity bucket
(power-of-two, runtime/executor._round_capacity), so 64 buckets share a
handful of compiled programs regardless of row-count variation.

Unsupported (falls back to in-core or partitioned-spill paths):
REPARTITION_RANGE (out-of-core distributed sort), cross joins (two scans in
one fragment), nested-lane columns crossing an exchange.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import replace as _dc_replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metadata import Metadata, Session
from ..planner.fragmenter import (
    Partitioning,
    PlanFragment,
    RemoteSourceNode,
    SubPlan,
    add_exchanges,
    create_fragments,
)
import jax

from ..planner.plan import (
    ExchangeType,
    LogicalPlan,
    OutputNode,
    PlanNode,
    TableScanNode,
    visit_plan,
)
from ..spi.page import Page
from ..parallel.runner import (
    _FragmentExecutor,
    _page_from_host_chunks,
    _page_to_host,
    empty_page_for,
    host_partition_targets,
    run_fragment_partition,
    scan_sources,
)
from .executor import ExecutionError, Relation, _concat_pages, _round_capacity
from .traced import _TracedExecutor, is_traceable

HostChunk = List[Tuple]  # [(type, data, valid, dictionary), ...] per column


class OutOfCoreUnsupported(ExecutionError):
    pass


def _chunk_bytes(cols: HostChunk) -> int:
    return sum(d.nbytes + v.nbytes for _, d, v, _ in cols)


class _DiskChunk:
    """One spilled chunk: data/valid arrays in an .npz, types + dictionaries
    (tiny, code-table objects) retained in memory."""

    __slots__ = ("path", "types", "dicts", "nbytes", "rows")

    def __init__(self, path: str, cols: HostChunk):
        self.path = path
        self.types = [c[0] for c in cols]
        self.dicts = [c[3] for c in cols]
        self.nbytes = _chunk_bytes(cols)
        self.rows = len(cols[0][1]) if cols else 0
        np.savez(
            path,
            **{f"d{i}": c[1] for i, c in enumerate(cols)},
            **{f"v{i}": c[2] for i, c in enumerate(cols)},
        )

    def load(self) -> HostChunk:
        with np.load(self.path) as z:
            return [
                (tp, z[f"d{i}"], z[f"v{i}"], dc)
                for i, (tp, dc) in enumerate(zip(self.types, self.dicts))
            ]


class BucketStore:
    """P-bucket columnar chunk store for one exchange edge: memory-first,
    newest chunks spill to disk once the in-memory byte budget is exceeded
    (the reference's FileSystemExchangeSink role, played by local disk;
    plugin/trino-exchange-filesystem/.../FileSystemExchangeSink.java)."""

    def __init__(self, n_buckets: int, budget_bytes: int, spool_dir: str, tag: str):
        self.n_buckets = n_buckets
        self.budget_bytes = budget_bytes
        self.spool_dir = spool_dir
        self.tag = tag
        self.chunks: List[List[object]] = [[] for _ in range(n_buckets)]
        self.mem_bytes = 0
        self.spilled_bytes = 0
        self._seq = 0

    def append(self, bucket: int, cols: HostChunk) -> None:
        if not cols or len(cols[0][1]) == 0:
            return
        size = _chunk_bytes(cols)
        if self.mem_bytes + size > self.budget_bytes:
            path = os.path.join(self.spool_dir, f"{self.tag}-{bucket}-{self._seq}.npz")
            self._seq += 1
            self.chunks[bucket].append(_DiskChunk(path, cols))
            self.spilled_bytes += size
        else:
            self.chunks[bucket].append(cols)
            self.mem_bytes += size

    def rows_of(self, bucket: int) -> int:
        total = 0
        for c in self.chunks[bucket]:
            total += c.rows if isinstance(c, _DiskChunk) else len(c[0][1])
        return total

    def read(self, bucket: int) -> List[HostChunk]:
        return [
            c.load() if isinstance(c, _DiskChunk) else c for c in self.chunks[bucket]
        ]

    def read_all(self) -> List[HostChunk]:
        out: List[HostChunk] = []
        for b in range(self.n_buckets):
            out.extend(self.read(b))
        return out

    def drop(self) -> None:
        for lst in self.chunks:
            for c in lst:
                if isinstance(c, _DiskChunk):
                    try:
                        os.unlink(c.path)
                    except OSError:
                        pass
        self.chunks = [[] for _ in range(self.n_buckets)]
        self.mem_bytes = 0


def _split_chunk_by_targets(
    cols: HostChunk, targets: np.ndarray, n: int
) -> List[Optional[HostChunk]]:
    """One stable argsort + slicing instead of n boolean scans."""
    order = np.argsort(targets, kind="stable")
    sorted_t = targets[order]
    bounds = np.searchsorted(sorted_t, np.arange(n + 1))
    gathered = [(tp, d[order], v[order], dc) for tp, d, v, dc in cols]
    out: List[Optional[HostChunk]] = []
    for b in range(n):
        lo, hi = bounds[b], bounds[b + 1]
        if lo == hi:
            out.append(None)
            continue
        out.append([(tp, d[lo:hi], v[lo:hi], dc) for tp, d, v, dc in gathered])
    return out


_empty_page = empty_page_for


class _OOCFragmentExecutor(_FragmentExecutor):
    """Fragment executor whose table scans read a pre-assembled split-batch
    page instead of loading the whole table."""

    def __init__(self, plan, metadata, session, staged, scan_pages: Dict[int, Page]):
        super().__init__(plan, metadata, session, staged, partition=0, n_workers=1)
        self._scan_pages = scan_pages

    def _exec_TableScanNode(self, node: TableScanNode) -> Relation:
        page = self._scan_pages.get(id(node))
        if page is None:
            return super()._exec_TableScanNode(node)
        symbols = tuple(s for s, _ in node.assignments)
        return Relation(page, symbols)


class _TracedUnitExecutor(_TracedExecutor):
    """Traced executor for ONE fragment execution unit: scans AND remote
    sources fed as page arguments, joins at static capacities with overflow
    accounting. The whole unit is one XLA program — one device dispatch per
    split batch / bucket, which is what makes the out-of-core tier viable
    through a remote-TPU tunnel (per-operator dispatch pays a tunnel
    round-trip per op; round 3 measured 15.8 s wallclock Q3 that way)."""

    def __init__(self, plan, metadata, session, scan_pages, remote_pages, factor):
        super().__init__(plan, metadata, session, scan_pages, factor)
        self._remote_pages = remote_pages

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Relation:
        return Relation(self._remote_pages[node.fragment_id], node.symbols)


class OutOfCoreRunner:
    """Drives one query's fragment tree out-of-core on a single chip."""

    def __init__(
        self,
        plan: LogicalPlan,
        metadata: Metadata,
        session: Session,
        n_buckets: int = 64,
        split_batch: int = 8,
        mem_budget_bytes: int = 2 << 30,
        spool_dir: Optional[str] = None,
    ):
        self.metadata = metadata
        self.session = session
        self.n_buckets = n_buckets
        self.split_batch = max(1, split_batch)
        self.mem_budget = mem_budget_bytes
        # distributed sort would need REPARTITION_RANGE (global quantiles over
        # a stream); query tails sort SINGLE instead
        session_ooc = _dc_replace(
            session, properties={**session.properties, "distributed_sort": False}
        )
        distributed = add_exchanges(plan, metadata, session_ooc)
        self.subplan: SubPlan = create_fragments(distributed)
        self.types = self.subplan.types
        self._consumer_edge: Dict[int, RemoteSourceNode] = {}
        for frag in self.subplan.fragments:
            visit_plan(
                frag.root,
                lambda n: self._consumer_edge.__setitem__(n.fragment_id, n)
                if isinstance(n, RemoteSourceNode)
                else None,
            )
        self._validate()  # before mkdtemp: a rejected plan must not leak a dir
        self._own_spool = spool_dir is None
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="trino-tpu-ooc-")
        self.stores: Dict[int, BucketStore] = {}
        self.stats: Dict[str, object] = {"fragments": len(self.subplan.fragments)}
        self._unit_fns: Dict[Tuple[int, float], object] = {}
        self._unit_factor: Dict[int, float] = {}
        self._traceable: Dict[int, bool] = {}

    # ------------------------------------------------------------ validation

    def _validate(self) -> None:
        for frag in self.subplan.fragments:
            scans: List[TableScanNode] = []
            visit_plan(
                frag.root,
                lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
            )
            if len(scans) > 1:
                raise OutOfCoreUnsupported(
                    "fragment with multiple scans (cross join?) cannot stream"
                )
            edge = self._consumer_edge.get(frag.fragment_id)
            if edge is not None and edge.exchange_type == ExchangeType.REPARTITION_RANGE:
                raise OutOfCoreUnsupported(
                    "REPARTITION_RANGE (distributed sort) not supported out-of-core"
                )

    # ------------------------------------------------------------- plumbing

    def _edge_buckets(self, fid: int) -> int:
        edge = self._consumer_edge.get(fid)
        if edge is not None and edge.exchange_type == ExchangeType.REPARTITION:
            return self.n_buckets
        return 1

    def _emit(self, frag: PlanFragment, page: Page) -> None:
        """Bucket one execution unit's output into the fragment's store."""
        store = self.stores[frag.fragment_id]
        cols = _page_to_host(page)
        if not cols:
            return
        edge = self._consumer_edge.get(frag.fragment_id)
        if edge is None or edge.exchange_type != ExchangeType.REPARTITION or store.n_buckets == 1:
            store.append(0, cols)
            return
        out_symbols = list(frag.root.output_symbols)
        key_idx = [out_symbols.index(k) for k in edge.partition_keys]
        targets = host_partition_targets(cols, key_idx, store.n_buckets)
        for b, chunk in enumerate(
            _split_chunk_by_targets(cols, targets, store.n_buckets)
        ):
            if chunk is not None:
                store.append(b, chunk)

    def _input_page(self, rs: RemoteSourceNode, bucket: Optional[int]) -> Page:
        """Assemble one remote source's input page for one execution unit."""
        store = self.stores[rs.fragment_id]
        if rs.exchange_type == ExchangeType.REPARTITION and bucket is not None:
            chunks = store.read(bucket)
        else:  # GATHER / BROADCAST: complete producer output
            chunks = store.read_all()
        if not chunks:
            return _empty_page(rs.symbols, self.types)
        rows = sum(len(c[0][1]) for c in chunks)
        # power-of-two padding: varying bucket sizes share compiled programs
        return _page_from_host_chunks(chunks, capacity=_round_capacity(max(rows, 1)))

    def _remotes_of(self, frag: PlanFragment) -> List[RemoteSourceNode]:
        from ..planner.fragmenter import remote_sources

        return remote_sources(frag.root)

    def _fragment_traceable(self, frag: PlanFragment) -> bool:
        flag = self._traceable.get(frag.fragment_id)
        if flag is None:
            flag = is_traceable(
                LogicalPlan(frag.root, self.types),
                allow_joins=True,
                extra_types=(RemoteSourceNode,),
            )
            self._traceable[frag.fragment_id] = flag
        return flag

    def _unit_fn(self, frag: PlanFragment, factor: float):
        """One jitted program per (fragment, join-capacity factor); jax's own
        cache handles the handful of power-of-two input shapes."""
        key = (frag.fragment_id, factor)
        fn = self._unit_fns.get(key)
        if fn is not None:
            return fn
        plan = LogicalPlan(frag.root, self.types)
        remote_fids = [rs.fragment_id for rs in self._remotes_of(frag)]
        root = frag.root

        def run(scan_page: Optional[Page], remote_pages: Tuple[Page, ...]):
            import jax.numpy as jnp

            scans = {} if scan_page is None else {0: scan_page}
            executor = _TracedUnitExecutor(
                plan, self.metadata, self.session, scans,
                dict(zip(remote_fids, remote_pages)), factor,
            )
            if isinstance(root, OutputNode):
                rel = executor.eval(root.source)
                symbols = root.symbols
            else:
                rel = executor.eval(root)
                symbols = root.output_symbols
            page = Page(
                tuple(rel.column_for(s) for s in symbols), rel.page.active
            )
            overflow = jnp.int64(0)
            for o in executor.overflows:
                overflow = overflow + o.astype(jnp.int64)
            return page, overflow

        fn = jax.jit(run)
        self._unit_fns[key] = fn
        return fn

    def _run_unit(
        self,
        frag: PlanFragment,
        staged: Dict[int, List[Page]],
        scan_pages: Dict[int, Page],
    ) -> Page:
        if self._fragment_traceable(frag):
            scan_page = next(iter(scan_pages.values())) if scan_pages else None
            remote_fids = [rs.fragment_id for rs in self._remotes_of(frag)]
            remote_pages = tuple(staged[fid][0] for fid in remote_fids)
            factor = self._unit_factor.get(frag.fragment_id, 1.0)
            while True:
                page, overflow = self._unit_fn(frag, factor)(
                    scan_page, remote_pages
                )
                if int(np.asarray(overflow)) == 0:
                    self._unit_factor[frag.fragment_id] = factor
                    return page
                factor *= 2.0  # join output exceeded capacity: retry larger
                if factor > 1024:
                    raise ExecutionError("join capacity runaway in OOC unit")
        plan = LogicalPlan(frag.root, self.types)
        ex = _OOCFragmentExecutor(plan, self.metadata, self.session, staged, scan_pages)
        return run_fragment_partition(ex, frag.root)

    # ------------------------------------------------------------- stages

    def _execute_source(self, frag: PlanFragment) -> None:
        scan: List[TableScanNode] = []
        visit_plan(
            frag.root,
            lambda n: scan.append(n) if isinstance(n, TableScanNode) else None,
        )
        node = scan[0]
        splits, col_indexes, provider = scan_sources(self.metadata, node)

        # non-repartition inputs (broadcast builds, gathered subquery results)
        staged = {
            rs.fragment_id: [self._input_page(rs, None)]
            for rs in self._remotes_of(frag)
        }
        units = 0
        for i in range(0, max(len(splits), 1), self.split_batch):
            batch = splits[i : i + self.split_batch]
            if batch:
                pages = [provider.create_page_source(sp, col_indexes) for sp in batch]
                page = pages[0] if len(pages) == 1 else _concat_pages(pages)
            else:  # empty table still needs one unit (partial global aggs)
                page = _empty_page(tuple(s for s, _ in node.assignments), self.types)
            out = self._run_unit(frag, staged, {id(node): page})
            self._emit(frag, out)
            units += 1
        self.stats[f"f{frag.fragment_id}_units"] = units

    def _execute_buckets(self, frag: PlanFragment) -> None:
        remotes = self._remotes_of(frag)
        hash_edges = [
            rs for rs in remotes if rs.exchange_type == ExchangeType.REPARTITION
        ]
        if not hash_edges:
            # no co-partitioned inputs (all broadcast/gather): one unit
            self._emit(frag, self._execute_single(frag))
            self.stats[f"f{frag.fragment_id}_units"] = 1
            return
        shared = {
            rs.fragment_id: [self._input_page(rs, None)]
            for rs in remotes
            if rs.exchange_type != ExchangeType.REPARTITION
        }
        units = 0
        for b in range(self.n_buckets):
            if all(self.stores[rs.fragment_id].rows_of(b) == 0 for rs in hash_edges):
                continue  # empty bucket emits nothing for every operator
            staged = dict(shared)
            for rs in hash_edges:
                staged[rs.fragment_id] = [self._input_page(rs, b)]
            out = self._run_unit(frag, staged, {})
            self._emit(frag, out)
            units += 1
        self.stats[f"f{frag.fragment_id}_units"] = units

    def _execute_single(self, frag: PlanFragment) -> Page:
        staged = {
            rs.fragment_id: [self._input_page(rs, None)]
            for rs in self._remotes_of(frag)
        }
        return self._run_unit(frag, staged, {})

    # ------------------------------------------------------------- driver

    def execute(self) -> Tuple[List[str], Page]:
        try:
            final_page: Optional[Page] = None
            root_id = self.subplan.root_fragment.fragment_id
            for frag in self.subplan.fragments:
                has_scan: List[TableScanNode] = []
                visit_plan(
                    frag.root,
                    lambda n: has_scan.append(n)
                    if isinstance(n, TableScanNode)
                    else None,
                )
                if frag.fragment_id == root_id:
                    final_page = self._execute_single(frag)
                    break
                self.stores[frag.fragment_id] = BucketStore(
                    self._edge_buckets(frag.fragment_id),
                    self.mem_budget,
                    self.spool_dir,
                    f"f{frag.fragment_id}",
                )
                if has_scan:
                    self._execute_source(frag)
                elif frag.partitioning in (
                    Partitioning.FIXED_HASH,
                    Partitioning.FIXED_ARBITRARY,
                ):
                    self._execute_buckets(frag)
                else:
                    self._emit(frag, self._execute_single(frag))
                # every fragment has exactly ONE consumer (each REMOTE
                # exchange cuts its own fragment), so its producers' stores
                # are dead as soon as it finishes: free host memory + spool
                # eagerly — peak usage is bounded by adjacent stages, not the
                # whole fragment tree
                for fid in frag.input_fragments:
                    store = self.stores.get(fid)
                    if store is not None:
                        store.drop()  # spilled_bytes counter survives drop
            assert final_page is not None
            root = self.subplan.root_fragment.root
            assert isinstance(root, OutputNode)
            self.stats["spilled_bytes"] = sum(
                s.spilled_bytes for s in self.stores.values()
            )
            return list(root.column_names), final_page
        finally:
            for s in self.stores.values():
                s.drop()
            if self._own_spool:
                try:
                    os.rmdir(self.spool_dir)
                except OSError:
                    pass


def execute_out_of_core(
    plan: LogicalPlan,
    metadata: Metadata,
    session: Session,
    n_buckets: int = 64,
    split_batch: int = 8,
    mem_budget_bytes: int = 2 << 30,
) -> Tuple[List[str], Page]:
    runner = OutOfCoreRunner(
        plan,
        metadata,
        session,
        n_buckets=n_buckets,
        split_batch=split_batch,
        mem_budget_bytes=mem_budget_bytes,
    )
    return runner.execute()
