"""Test configuration: force a hermetic 8-device virtual CPU "cluster".

Mirrors the reference's DistributedQueryRunner idea (testing/trino-testing/.../
DistributedQueryRunner.java:108 — a multi-node cluster in one process): we get a
multi-"chip" TPU topology in one process via XLA's host-platform device count, so
sharding/collective paths are exercised without TPU hardware.

Must run before jax is imported anywhere.
"""

import os
import pathlib

# NOTE: in this environment the axon TPU plugin ignores the JAX_PLATFORMS env
# var — only jax.config / JAX_PLATFORM_NAME reliably force the CPU backend.
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: XLA CPU compile time scales with array size for
# sort/scan ops, so caching compiled operator programs across test runs matters.
# The XLA:CPU AOT sub-cache is DISABLED: its entries pin host machine features
# and loading them on a host without (e.g.) +prefer-no-gather segfaults mid-
# suite (observed: reproducible SIGSEGV in backend_compile_and_load at ~94%);
# jax's own executable cache is feature-safe and keeps most of the win.
_CACHE_DIR = pathlib.Path(__file__).parent / ".jax_cache"
jax.config.update("jax_compilation_cache_dir", str(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
try:
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
except Exception:  # older jax without the knob: drop the cache entirely
    jax.config.update("jax_compilation_cache_dir", "")

import pytest  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def _fresh_jit_caches_per_module():
    """XLA:CPU reproducibly SEGFAULTS in backend_compile_and_load after
    roughly ~600 in-process compiles (observed at different suite positions
    as tests were added — the trigger tracks the CUMULATIVE compile count,
    not any specific program; every module passes standalone). Dropping the
    accumulated executables at each module boundary keeps the compiler
    inside its working envelope; module-internal caching still amortizes
    the hot fixtures."""
    import jax

    jax.clear_caches()


@pytest.fixture(scope="session")
def tpch_tiny():
    """Tiny deterministic TPC-H runner shared across the test session."""
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def pytest_configure(config):
    # "slow" excludes a test from the tier-1 sweep (`-m 'not slow'`):
    # currently the full 22-query megakernel corpus A/B, whose tier-1 slice
    # runs the join-heaviest four queries instead
    config.addinivalue_line("markers", "slow: excluded from the tier-1 run")
