"""Distributed query steps: sharded scan -> local partial ops -> ICI exchange ->
final ops, composed under shard_map over a Mesh.

Reference blueprint: a Trino stage tree with REMOTE REPARTITION exchanges
(SURVEY.md §2.11 parallelism inventory): source-partitioned scans (splits ->
devices), partial aggregation below the exchange (PushPartialAggregationThrough-
Exchange), hash repartition, final aggregation. Here the whole multi-stage plan
for one pod compiles into ONE XLA program with all_to_all/psum collectives where
Trino would run HTTP shuffles.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import kernels as K
from ..spi.page import Column, Page
from . import exchange


def shard_pages(pages: Sequence[Page], mesh: Mesh, axis_name: str = "workers") -> Page:
    """Concatenate per-split pages and lay them out shard-per-device."""
    n = mesh.shape[axis_name]
    assert len(pages) % n == 0 or len(pages) == 1, (
        f"{len(pages)} splits not divisible across {n} devices"
    )
    cols = []
    for i in range(pages[0].num_columns):
        data = jnp.concatenate([p.columns[i].data for p in pages])
        valid = jnp.concatenate([p.columns[i].valid for p in pages])
        c0 = pages[0].columns[i]
        cols.append(Column(c0.type, data, valid, c0.dictionary))
    active = jnp.concatenate([p.active for p in pages])
    page = Page(tuple(cols), active)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.device_put(page, sharding)


def distributed_groupby_sum(
    mesh: Mesh,
    page: Page,
    key_index: int,
    value_index: int,
    axis_name: str = "workers",
) -> Tuple[Page, jnp.ndarray]:
    """Full distributed group-by: per-shard partial agg -> all_to_all hash
    repartition of partials -> final agg; plus a psum'd global row count.

    The canonical "distributed training step" of this engine — the shape the
    fragmenter lowers AggregationNode(PARTIAL) / ExchangeNode(REPARTITION) /
    AggregationNode(FINAL) stage chains into.
    """
    n = mesh.shape[axis_name]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis_name),),
        out_specs=(P(axis_name), P()),
    )
    def step(p: Page):
        key_col = p.columns[key_index]
        val_col = p.columns[value_index]
        cap = key_col.data.shape[0]
        active = p.active
        # ---- partial aggregation (local) ----
        perm, gid, new_group, num_groups = K.group_ids(
            [(key_col.data, key_col.valid)], active
        )
        key_s = key_col.data[perm]
        val_s = val_col.data[perm].astype(jnp.int64)
        w = active[perm] & val_col.valid[perm]
        part_keys = K.scatter_first(key_s, new_group, gid, cap)
        part_sums = K.segment_reduce(val_s, w, gid, cap, "sum")
        part_counts = K.segment_reduce(w.astype(jnp.int64), w, gid, cap, "count")
        part_active = jnp.arange(cap) < num_groups
        partial_page = Page(
            (
                Column(key_col.type, part_keys, part_active),
                Column(val_col.type, part_sums, part_active),
                Column(val_col.type, part_counts, part_active),
            ),
            part_active,
        )
        # ---- REMOTE REPARTITION over ICI ----
        # bucket_cap == cap can never overflow; overflow stays for the contract
        shuffled, _overflow = exchange.repartition_by_keys(
            partial_page, [0], n, axis_name, bucket_cap=cap
        )
        # ---- final aggregation (local, keys now co-located) ----
        scap = shuffled.capacity
        kcol = shuffled.columns[0]
        perm2, gid2, new2, ng2 = K.group_ids(
            [(kcol.data, kcol.valid)], shuffled.active
        )
        w2 = shuffled.active[perm2]
        fkeys = K.scatter_first(kcol.data[perm2], new2, gid2, scap)
        fsums = K.segment_reduce(
            shuffled.columns[1].data[perm2].astype(jnp.int64), w2, gid2, scap, "sum"
        )
        fcounts = K.segment_reduce(
            shuffled.columns[2].data[perm2].astype(jnp.int64), w2, gid2, scap, "sum"
        )
        factive = jnp.arange(scap) < ng2
        out = Page(
            (
                Column(key_col.type, fkeys, factive),
                Column(val_col.type, fsums, factive),
                Column(val_col.type, fcounts, factive),
            ),
            factive,
        )
        # global row count over ICI (psum collective)
        total_rows = jax.lax.psum(jnp.sum(active.astype(jnp.int64)), axis_name)
        return out, total_rows

    return step(page)


def distributed_filter_sum(
    mesh: Mesh,
    page: Page,
    predicate_fn,
    value_index: int,
    axis_name: str = "workers",
) -> jnp.ndarray:
    """Distributed Q6 shape: sharded scan -> local filter+multiply -> psum."""

    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axis_name),), out_specs=P())
    def step(p: Page):
        keep = predicate_fn(p) & p.active
        val = p.columns[value_index]
        local = jnp.sum(jnp.where(keep & val.valid, val.data.astype(jnp.int64), 0))
        return jax.lax.psum(local, axis_name)

    return step(page)
