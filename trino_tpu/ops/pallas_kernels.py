"""Pallas TPU kernels for hot operator pipelines.

Reference blueprint: the role of gen/columnar (compiled columnar filters,
SURVEY.md §2.4) taken below XLA: a fused scan→filter→aggregate pass written
against the TPU VPU directly. XLA's own fusion already reaches the HBM roofline
for Q6-shaped pipelines (BASELINE.md), so the value here is (a) proving the
Pallas path end-to-end for round-2 kernels (join build/probe, grouped
aggregation) where XLA's lowering is weaker, and (b) exact integer accumulation
without int64 emulation.

Exactness trick: the VPU has no int64, so block sums of int32 products are
accumulated as two int32 lanes — sum(x & 0xFFFF) and sum(x >> 16) — recombined
as int64 on the host side (low + (high << 16)). Each lane stays well inside
int32 for blocks up to 8 sublanes x 1024 lanes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

LANES = 1024          # block width  (multiple of 128)
SUBLANES = 8          # block height (multiple of 8)
BLOCK = LANES * SUBLANES


def _q6_kernel(shipdate_ref, discount_ref, quantity_ref, price_ref, mask_ref, out_ref,
               *, lo_date, hi_date, lo_disc, hi_disc, hi_qty):
    sd = shipdate_ref[:]
    disc = discount_ref[:]
    qty = quantity_ref[:]
    price = price_ref[:]
    mask = mask_ref[:]
    keep = (
        (sd >= lo_date)
        & (sd < hi_date)
        & (disc >= lo_disc)
        & (disc <= hi_disc)
        & (qty < hi_qty)
        & (mask != 0)
    )
    product = jnp.where(keep, price * disc, 0)
    # dtype pinned to int32: under jax_enable_x64, sum() would promote to int64,
    # which the Pallas TPU lowering rejects
    low = jnp.sum(product & 0xFFFF, dtype=jnp.int32)
    high = jnp.sum(product >> 16, dtype=jnp.int32)
    # output blocks must be (8, 128)-tiled; scatter is not lowerable on TPU,
    # so place the two partials via iota masks (lanes [0,0] and [0,1])
    rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    first_row = rows == 0
    out = jnp.where(first_row & (cols == 0), low, 0) + jnp.where(
        first_row & (cols == 1), high, 0
    )
    out_ref[0] = out


def q6_fused(
    shipdate: jnp.ndarray,
    discount: jnp.ndarray,
    quantity: jnp.ndarray,
    extendedprice: jnp.ndarray,
    mask: jnp.ndarray,
    lo_date: int,
    hi_date: int,
    lo_disc: int,
    hi_disc: int,
    hi_qty: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused Q6: sum(price * discount) over the predicate; exact int64 result.

    Inputs are int32 1-D arrays (dates as days, decimals as cents) plus an
    int32 0/1 mask (active & validity). Length is padded to a whole number of
    (8, 1024) blocks; padding rides in with mask=0.
    """
    n = shipdate.shape[0]
    padded = ((n + BLOCK - 1) // BLOCK) * BLOCK

    def prep(x, fill=0):
        x = x.astype(jnp.int32)
        if padded != n:
            x = jnp.pad(x, (0, padded - n), constant_values=fill)
        return x.reshape(padded // LANES, LANES)

    sd = prep(shipdate)
    disc = prep(discount)
    qty = prep(quantity)
    price = prep(extendedprice)
    msk = prep(mask)

    rows = padded // LANES
    grid = rows // SUBLANES
    kernel = partial(
        _q6_kernel,
        lo_date=lo_date,
        hi_date=hi_date,
        lo_disc=lo_disc,
        hi_disc=hi_disc,
        hi_qty=hi_qty,
    )
    block_in = pl.BlockSpec((SUBLANES, LANES), lambda i: (i, 0))
    # the engine runs with jax_enable_x64; inside the kernel trace x64 weak-type
    # promotion produces int64 convert_element_type ops that the Mosaic TPU
    # lowering cannot handle (it recurses) — trace the kernel in x32 scope
    with jax.enable_x64(False):
        partials = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((grid, 8, 128), jnp.int32),
            grid=(grid,),
            in_specs=[block_in] * 5,
            out_specs=pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0)),
            interpret=interpret,
        )(sd, disc, qty, price, msk)
    low = partials[:, 0, 0].astype(jnp.int64)
    high = partials[:, 0, 1].astype(jnp.int64)
    return jnp.sum(low) + (jnp.sum(high) << 16)


def q6_reference(shipdate, discount, quantity, extendedprice, mask,
                 lo_date, hi_date, lo_disc, hi_disc, hi_qty) -> jnp.ndarray:
    """XLA formulation of the same computation (the engine's compiled path)."""
    keep = (
        (shipdate >= lo_date)
        & (shipdate < hi_date)
        & (discount >= lo_disc)
        & (discount <= hi_disc)
        & (quantity < hi_qty)
        & (mask != 0)
    )
    return jnp.sum(
        jnp.where(keep, extendedprice.astype(jnp.int64) * discount.astype(jnp.int64), 0)
    )
