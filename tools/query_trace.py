#!/usr/bin/env python
"""Run one SQL query with the pipeline flight recorder on; export the trace.

The observability plane's export tool: enables the process flight recorder
(runtime/observability.RECORDER), runs the query through the embedded engine
(in-core, or the out-of-core tier with --ooc), and writes the recorded
pipeline events — operator spans, bucket units, prefetch issue/complete,
host->device transfers, XLA compiles, spill writes/reads, exchange
push/pull — as Chrome trace-event JSON loadable in ui.perfetto.dev or
chrome://tracing. A stats summary (device/host/compile attribution +
counters) prints to stderr.

    python tools/query_trace.py --sql "SELECT ..." --scale 0.01 --out t.json
    python tools/query_trace.py --q q3 --ooc --validate

Exports are DETERMINISTIC: tids derive from sorted (thread-name, first
activity) instead of thread-arrival order (runtime/clusterobs.
canonicalize_trace), so repeated exports of the same ring are byte-
identical.

Cluster mode (the cluster observability plane) pulls the MERGED cross-node
timeline from a coordinator — every node's flight-recorder segment,
skew-aligned by announced clock offsets, one process lane per node:

    python tools/query_trace.py --cluster http://coord:8080 \\
        --query-id q_ab12... --out cluster.json --validate

The same module backs the observability smoke check (tools/obs_smoke.py):
``run_query_trace`` returns the trace dict + stats snapshot, and
``validate`` applies the minimal schema the smoke check enforces.

Host-path plane (runtime/hostprof.py): ``--speedscope host.json`` runs the
wall-clock sampling profiler alongside the flight recorder and writes the
collapsed host stacks as a speedscope document (drop on speedscope.app),
schema-checked by hostprof.validate_speedscope when --validate is on:

    python tools/query_trace.py --q q6 --speedscope host.json --validate
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

# runnable from anywhere: the repo root (trino_tpu's parent) joins sys.path
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# canned TPC-H queries for --q (kept tiny; bench.py owns the full ladder)
QUERIES = {
    "q6": """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
""",
    "q3": """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
""",
}


def run_query_trace(
    sql: str,
    scale: float = 0.01,
    ooc: bool = False,
    sync_stats: bool = True,
    runner=None,
    profile: bool = False,
) -> Tuple[dict, dict, int]:
    """Execute ``sql`` with the flight recorder on.

    Returns (chrome_trace_dict, query_stats_snapshot, result_rows). The
    recorder is cleared first so the export covers exactly this query, and
    disabled after (tool semantics; the server endpoint manages its own
    lifecycle). ``profile=True`` additionally runs the host sampling
    profiler (runtime/hostprof.PROFILER) for the query's duration — read
    ``PROFILER.speedscope()`` / ``PROFILER.collapsed()`` afterwards.
    """
    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.runtime.observability import RECORDER

    if runner is None:
        runner = LocalQueryRunner.tpch(scale=scale)
    RECORDER.clear()
    RECORDER.enable()
    profiler = None
    if profile:
        from trino_tpu.runtime.hostprof import PROFILER as profiler

        profiler.clear()
        profiler.acquire()
    try:
        if ooc:
            from trino_tpu.runtime import observability as obs
            from trino_tpu.runtime.ooc import OutOfCoreRunner

            plan = runner.plan_sql(sql)
            runner_ooc = OutOfCoreRunner(
                plan, runner.metadata, runner.session, n_buckets=8,
                split_batch=4,
            )
            _, page = runner_ooc.execute()
            import numpy as np

            rows = int(np.asarray(page.active).sum())
            stats = runner_ooc.collector.snapshot()
        else:
            if sync_stats:
                runner.session.set("query_stats_sync", True)
            res = runner.execute(sql)
            rows = len(res.rows)
            stats = res.query_stats or {}
    finally:
        RECORDER.disable()
        if profiler is not None:
            profiler.release()
            profiler.join()
    from trino_tpu.runtime.clusterobs import canonicalize_trace

    # deterministic tids: repeated exports of the same ring byte-identical
    return canonicalize_trace(RECORDER.chrome_trace()), stats, rows


def validate(trace: dict) -> List[str]:
    """Minimal Perfetto-schema validation (see observability.
    validate_chrome_trace): monotonic per-track timestamps, paired B/E
    events, declared pids/tids. Returns problems; [] means valid."""
    from trino_tpu.runtime.observability import validate_chrome_trace

    return validate_chrome_trace(trace)


def fetch_cluster_trace(
    coordinator_url: str, query_id: str, user: str = "tools",
    timeout: float = 30.0,
) -> dict:
    """The coordinator's merged cross-node timeline for ``query_id``
    (``GET /v1/query/{id}/trace?cluster=1`` — requires the coordinator to
    run with $TRINO_TPU_CLUSTER_OBS on)."""
    import urllib.request

    url = (
        f"{coordinator_url.rstrip('/')}/v1/query/{query_id}/trace?cluster=1"
    )
    req = urllib.request.Request(url, method="GET")
    req.add_header("X-Trino-User", user)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sql", help="SQL text to run")
    ap.add_argument("--q", choices=sorted(QUERIES), help="canned TPC-H query")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--ooc", action="store_true", help="out-of-core tier")
    ap.add_argument("--out", default="query_trace.json")
    ap.add_argument("--validate", action="store_true")
    ap.add_argument(
        "--speedscope", metavar="PATH",
        help="also run the host sampling profiler (runtime/hostprof.py) "
             "and write its collapsed stacks as a speedscope document",
    )
    ap.add_argument(
        "--cluster", metavar="COORDINATOR_URL",
        help="pull the merged cross-node timeline from this coordinator "
             "instead of executing locally (needs --query-id)",
    )
    ap.add_argument("--query-id", help="query id for --cluster mode")
    args = ap.parse_args(argv)
    if args.cluster:
        if not args.query_id:
            ap.error("--cluster requires --query-id")
        if args.speedscope:
            ap.error("--speedscope profiles a local execution, not --cluster")
        trace = fetch_cluster_trace(args.cluster, args.query_id)
        stats, rows = {}, None
    else:
        sql = args.sql or (QUERIES[args.q] if args.q else None)
        if not sql:
            ap.error("one of --sql / --q is required")
        trace, stats, rows = run_query_trace(
            sql, scale=args.scale, ooc=args.ooc,
            profile=bool(args.speedscope),
        )
    if args.speedscope:
        from trino_tpu.runtime.hostprof import PROFILER, validate_speedscope

        doc = PROFILER.speedscope(name=os.path.basename(args.speedscope))
        with open(args.speedscope, "w") as f:
            json.dump(doc, f)
        print(
            f"wrote {args.speedscope}: {len(doc['profiles'])} thread "
            f"profile(s), {len(doc['shared']['frames'])} frames "
            f"({PROFILER.tick_count} sampler ticks)",
            file=sys.stderr,
        )
        if args.validate:
            problems = validate_speedscope(doc)
            if problems:
                for p in problems:
                    print(f"INVALID speedscope: {p}", file=sys.stderr)
                return 1
            print("speedscope valid", file=sys.stderr)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    n_events = len(trace.get("traceEvents", []))
    lanes = trace.get("nodes")
    extra = f", node lanes: {lanes}" if lanes else f", {rows} result rows"
    print(f"wrote {args.out}: {n_events} events{extra}", file=sys.stderr)
    print(json.dumps(stats, indent=2), file=sys.stderr)
    if args.validate:
        problems = validate(trace)
        if problems:
            for p in problems:
                print(f"INVALID: {p}", file=sys.stderr)
            return 1
        print("trace valid", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
