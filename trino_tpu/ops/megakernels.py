"""Fragment-fused Pallas megakernels: hash join + partial agg + repartition.

Reference blueprint: "Query Processing on Tensor Computation Runtimes"
(arXiv:2203.01877) and "Accelerating Presto with GPUs" (PAPERS.md) both put
the dominant win in eliminating per-operator dispatch and the HBM round-trips
between operators. The device-batching plane (round 13) amortizes *launches*
across queries; each launched program is still a chain of discrete XLA ops.
This module fuses the hot fragment shapes into Pallas kernel launches:

- **hash join** — SplitMix64 bucketing + in-kernel probe, replacing the
  full-cosort internals of ops/kernels.join_match. The sort-based join exists
  because XLA TPU *scatters serialize*; inside a Pallas kernel scatter no
  longer serializes the program (stores into VMEM scratch are the intended
  build-side formulation, pallas_guide.md "Dynamic Indexing"), so the
  classic build/probe shape becomes expressible: a sequential build loop
  inserts active build rows into a bucketed slot table, and the probe side
  resolves matches with vectorized gathers — no multi-pass cosort, no
  rank-space merge sort.
- **join -> partial-agg fusion** — when the join feeds a direct-indexed
  aggregation (small static key domains: dictionary codes / booleans), the
  group-accumulate stage runs on the expanded rows inside the same kernel;
  the join output never materializes to HBM between operators.
- **repartition epilogue** — when the fragment output feeds a hash exchange
  (executor.repartition_hint), the engine-wide partition hash runs as the
  kernel's output stage and rides out as a ``dest`` lane attached to the
  page; ops/repartition consumes it instead of dispatching the standalone
  hash program. ``fused_epilogue`` additionally runs the full
  hash -> stable-cosort -> offsets epilogue as one kernel (the TPU-tier
  formulation, bit-identical to ops/repartition._repartition_epilogue).

Bit-identity contract (tier-1, interpret mode): every kernel runs under
``pl.pallas_call(..., interpret=True)`` on CPU, and the fused results are
bit-identical to the serial op-chain oracle BY CONSTRUCTION:

- slot assignment reuses kernels.expand_probe_slots — the same math the
  sort-based expansion uses, so probe row i's outputs land at the same slots;
- within equal keys, bucket insertion order is ascending original build index
  (the sequential build loop), exactly the stable sort order of the serial
  path's perm_b — so the d-th match of every probe row is the same build row;
- the fused aggregation re-traces executor._direct_aggregate_impl — the
  serial formulas, inside the kernel;
- the fused dest re-traces repartition._partition_dest.

Hardware status: the interpret path IS the contract tier-1 enforces; the
Mosaic lowering of the build loop (SMEM scalar stores) and the probe gathers
belongs to the ROADMAP item-2 hardware-verified ladder, like every BENCH
number since round 5 (CPU-labeled). Unsupported shapes (nested layouts,
non-equi residuals, FULL joins, multi-lane keys, sort-path aggregations)
fall back to the op-chain path per-fragment with a labeled
``trino_tpu_pallas_fallbacks_total`` tick — see ARCHITECTURE.md "Megakernel
plane" for the full fallback matrix.

Shape-class discipline: bucket counts key on capstore.capacity_class of the
build capacity and bucket slot widths on 4x-spaced classes (base 8), so the
kernel compile cache collapses varying fragment sizes into a handful of
classes — the same contract the OOC bucket loops and the device-batching
keys rely on.
"""

from __future__ import annotations

from dataclasses import replace
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl

from . import kernels as K
from ..runtime import kernelcost
from ..spi.page import Column, Page

# initial per-bucket slot width; retried at the 4x-spaced class of the
# observed max bucket population when a bucket overflows (duplicate-heavy
# build keys), then gives up at the table entry limit below
DEFAULT_BUCKET_CAP = 32
# (B+1) * C entries beyond this mean pathological key skew (one key owning a
# capacity-class worth of duplicates): the quadratic probe-compare block
# would dwarf the fused win, so the fragment falls back to the sort path
TABLE_ENTRY_LIMIT = 1 << 22

# fused-op labels carried on flight spans and the bench per-fragment reports
OP_JOIN = "hash_join"
OP_AGG = "partial_agg"
OP_REPART = "repartition"


# --------------------------------------------------------------------------- #
# observability: launch/fallback counters + paired compile/launch spans
# --------------------------------------------------------------------------- #


def _launch_counter():
    from ..runtime.metrics import REGISTRY

    return REGISTRY.counter(
        "trino_tpu_pallas_launches_total",
        help="fused Pallas megakernel launches (one per pl.pallas_call "
        "dispatch: probe/expand phases and standalone epilogues)",
    )


def _fallback_counter(reason: str):
    from ..runtime.metrics import REGISTRY

    return REGISTRY.counter(
        "trino_tpu_pallas_fallbacks_total",
        {"reason": reason},
        help="fragments that fell back from the fused megakernel path to "
        "the serial op-chain, by reason",
    )


def on_pallas_launch(n: int = 1) -> None:
    _launch_counter().inc(n)


def on_pallas_fallback(reason: str) -> None:
    """One fragment declined the fused path; ``reason`` is a short stable
    label (shape, bucket_skew, kernel_error, ...) — the fallback matrix in
    ARCHITECTURE.md enumerates them."""
    _fallback_counter(reason).inc()
    from ..runtime.observability import RECORDER

    RECORDER.instant("pallas_fallback", "pallas", reason=reason)


def pallas_launches() -> float:
    return _launch_counter().value


def pallas_fallbacks(reason: str) -> float:
    return _fallback_counter(reason).value


# signatures whose first trace already happened — the driver wraps the first
# call of each in a pallas_compile span (shape class + fused ops on E-args)
_COMPILED: set = set()


def _spanned_call(phase: str, fused_ops: str, shape_class: str, sig, call):
    from ..runtime.observability import RECORDER

    def _launch():
        with RECORDER.span("pallas_launch", "pallas", phase=phase) as end:
            out = call()
            end["shape_class"] = shape_class
            end["fused_ops"] = fused_ops
        on_pallas_launch()
        return out

    if sig not in _COMPILED:
        _COMPILED.add(sig)
        with RECORDER.span("pallas_compile", "pallas", phase=phase) as end:
            out = _launch()
            end["shape_class"] = shape_class
            end["fused_ops"] = fused_ops
        return out
    return _launch()


# --------------------------------------------------------------------------- #
# the megakernel harness: one traced body -> ONE pl.pallas_call
# --------------------------------------------------------------------------- #


def _mega_call(fn, tree, interpret: bool):
    """Run ``fn(tree) -> out_tree`` as ONE pallas kernel over full-array refs.

    The body is traced once (jax.eval_shape derives the output refs), then
    every input leaf becomes an input ref and every output leaf an output
    ref of a single ``pl.pallas_call`` — the whole fused fragment is one
    kernel launch. Grid-free full-block processing: fragment pages arrive in
    canonical capacity classes, so block tiling happens at the class level,
    not inside the kernel."""
    flat, treedef = jax.tree_util.tree_flatten(tree)

    def fn_flat(*xs):
        return fn(jax.tree_util.tree_unflatten(treedef, list(xs)))

    # trace the fused body once; jaxpr constants (e.g. jnp.array([n])
    # literals folded during tracing) become explicit kernel operands — a
    # pallas kernel cannot capture constants
    closed, out_shape = jax.make_jaxpr(fn_flat, return_shape=True)(*flat)
    consts = [jnp.asarray(c) for c in closed.consts]
    flat_out, out_tree = jax.tree_util.tree_flatten(out_shape)
    n_args = len(flat)
    n_consts = len(consts)

    def kernel(*refs):
        cs = [r[...] for r in refs[:n_consts]]
        ins = [r[...] for r in refs[n_consts:n_consts + n_args]]
        res = jax.core.eval_jaxpr(closed.jaxpr, cs, *ins)
        for r, v in zip(refs[n_consts + n_args:], res):
            r[...] = v

    out = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct(s.shape, s.dtype) for s in flat_out],
        interpret=interpret,
    )(*consts, *flat)
    return jax.tree_util.tree_unflatten(out_tree, out)


def _capacity_class(n: int, base: int = 1024) -> int:
    from ..runtime.capstore import capacity_class

    return capacity_class(n, base)


# --------------------------------------------------------------------------- #
# key normalization + bucket hashing (shared by both phases)
# --------------------------------------------------------------------------- #


def _normalized_keys(key_cols, luts):
    """(data, valid) pairs -> (normalized int64 keys, all-columns-valid).

    Mirrors the serial path's semantics exactly: dictionary-coded probe keys
    translate through the build dictionary's LUT (absent values become
    invalid — a real value that simply never matches), every column equality
    happens on kernels.order_key bits (floats via the sign-magnitude unfold,
    the engine-wide join equality)."""
    keys: List[jnp.ndarray] = []
    ok = None
    for (d, v), lut in zip(key_cols, luts):
        if lut is not None:
            d = lut[jnp.clip(d, 0, lut.shape[0] - 1)]
            v = v & (d >= 0)
        keys.append(K.order_key(d))
        ok = v if ok is None else (ok & v)
    return keys, ok


def _bucket_of(keys: Sequence[jnp.ndarray], n_buckets: int) -> jnp.ndarray:
    """SplitMix64 bucketing over the normalized key tuple. Internal layout
    only — never part of the bit-identity surface, so the fold is free to be
    a plain chained finalizer."""
    h = None
    for k in keys:
        h = K.splitmix64(k if h is None else h + k)
    return (h & jnp.int64(n_buckets - 1)).astype(jnp.int32)


def _bucket_match(table, counts, bucket, pk, pk_ok, bk, C: int):
    """Probe rows against their bucket's slots: ``eq[i, c]`` == slot c of
    row i's bucket holds a build row whose key tuple equals row i's.
    Returns (eq, rows) where ``rows[i, c]`` is the build row index in slot c
    (clipped; only meaningful where the slot is occupied)."""
    rows = table[bucket]  # [N, C] original build indices, insertion order
    m = bk[0].shape[0]
    rows_c = jnp.clip(rows, 0, m - 1)
    occ = (
        jax.lax.broadcasted_iota(jnp.int32, rows.shape, 1)
        < counts[bucket][:, None]
    )
    eq = occ & pk_ok[:, None]
    for p, b in zip(pk, bk):
        eq = eq & (b[rows_c] == p[:, None])
    return eq, rows_c


# --------------------------------------------------------------------------- #
# phase 1: build the bucket table + per-probe match counts (one kernel)
# --------------------------------------------------------------------------- #


def _probe_phase_body(B: int, C: int, left_outer: bool, tree):
    pkeys, bkeys, luts, probe_active, build_active = tree
    pk, pv = _normalized_keys(pkeys, luts)
    bk, bv = _normalized_keys(bkeys, (None,) * len(bkeys))
    pa = probe_active & pv
    ba = build_active & bv
    bucket_b = _bucket_of(bk, B)
    bucket_p = _bucket_of(pk, B)
    m = ba.shape[0]

    # build stage: sequential insertion keeps ascending original index
    # within each bucket — within equal keys this IS the serial path's
    # stable-sort order, the property the bit-identity proof leans on.
    # Inactive/NULL-key rows insert into the trash bucket B.
    def body(j, carry):
        table, counts = carry
        b = jnp.where(ba[j], bucket_b[j], jnp.int32(B))
        c = counts[b]
        table = table.at[b, jnp.minimum(c, C - 1)].set(jnp.int32(j))
        return table, counts.at[b].add(1)

    table, counts = jax.lax.fori_loop(
        0,
        m,
        body,
        (
            jnp.zeros((B + 1, C), jnp.int32),
            jnp.zeros((B + 1,), jnp.int32),
        ),
    )
    max_count = jnp.max(counts[:B])

    # probe stage: vectorized bucket-compare, no sorts, no merge
    eq, _ = _bucket_match(table, counts, bucket_p, pk, pa, bk, C)
    count = jnp.sum(eq, axis=1, dtype=jnp.int32)
    if left_outer:
        emit = jnp.where(probe_active, jnp.maximum(count, 1), 0)
    else:
        emit = count
    return table, counts, bucket_p, count, emit, max_count


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3))
def _jit_probe_phase(B, C, left_outer, interpret, tree):
    return _mega_call(
        partial(_probe_phase_body, B, C, left_outer), tree, interpret
    )


def probe_phase(
    pkeys,
    bkeys,
    luts,
    probe_active,
    build_active,
    left_outer: bool,
    interpret: bool,
) -> Optional[Dict[str, object]]:
    """Launch the build+count megakernel (retrying once at a larger bucket
    class when duplicate-heavy keys overflow the default slot width).

    Returns the phase-2 inputs plus ``emit`` (the array the executor sizes
    the output capacity from — the same host sync the serial join performs),
    or None after an ``on_pallas_fallback`` tick when the key distribution
    is too skewed for a bounded table."""
    B = _capacity_class(int(build_active.shape[0]))
    C = DEFAULT_BUCKET_CAP
    shape_class = f"p{probe_active.shape[0]}/b{build_active.shape[0]}/B{B}"
    tree = (tuple(pkeys), tuple(bkeys), tuple(luts), probe_active, build_active)
    for _attempt in range(2):
        sig = ("probe", B, C, left_outer, _tree_sig(tree))
        table, counts, bucket_p, count, emit, max_count = _spanned_call(
            "probe", OP_JOIN, f"{shape_class}/C{C}", sig,
            lambda: _jit_probe_phase(B, C, left_outer, interpret, tree),
        )
        need = int(max_count)
        if need <= C:
            return {
                "table": table, "counts": counts, "bucket_p": bucket_p,
                "count": count, "emit": emit, "B": B, "C": C,
                "shape_class": shape_class,
            }
        C = _capacity_class(need, base=8)
        if (B + 1) * C > TABLE_ENTRY_LIMIT:
            on_pallas_fallback("bucket_skew")
            return None
    on_pallas_fallback("bucket_skew")
    return None


def _tree_sig(tree) -> Tuple:
    return tuple(
        (tuple(x.shape), str(x.dtype))
        for x in jax.tree_util.tree_leaves(tree)
    )


# --------------------------------------------------------------------------- #
# phase 2: expand + (partial agg) + (repartition dest) (one kernel)
# --------------------------------------------------------------------------- #


def _expand_phase_body(out_capacity: int, C: int, symbols, proj_spec,
                       agg_spec, epi_spec, tree):
    (
        pkeys, bkeys, luts, probe_page, build_page,
        table, counts, bucket_p, count, emit,
    ) = tree
    from ..runtime.executor import (
        _cval_of,
        _direct_aggregate_impl,
        _group_sort_impl,
        _permute_column,
        _project_impl,
    )

    pk, pv = _normalized_keys(pkeys, luts)
    bk, _ = _normalized_keys(bkeys, (None,) * len(bkeys))
    pa = probe_page.active & pv

    # slot assignment: the EXACT math of the serial expansion — probe row i's
    # output rows occupy the same slots on both paths
    probe_idx, d, out_active, _total = K.expand_probe_slots(emit, out_capacity)
    matched = d < count[probe_idx]

    # d-th match of each output slot's probe row: within the bucket, the
    # (d+1)-th slot whose key equals the probe key — ascending original
    # build index, identical to perm_b[lo + d] on the serial path
    pk_sel = [k[probe_idx] for k in pk]
    eq, rows = _bucket_match(
        table, counts, bucket_p[probe_idx], pk_sel, pa[probe_idx], bk, C
    )
    cum = jnp.cumsum(eq.astype(jnp.int32), axis=1)
    sel = eq & (cum == (d + 1).astype(jnp.int32)[:, None])
    slot = jnp.argmax(sel, axis=1)
    bpos = jnp.take_along_axis(rows, slot[:, None].astype(jnp.int32), axis=1)[:, 0]

    cols: List[Column] = []
    for c in probe_page.columns:
        cols.append(_permute_column(c, probe_idx))
    for c in build_page.columns:
        pc = _permute_column(c, bpos)
        cols.append(replace(pc, valid=pc.valid & matched))
    out = Page(tuple(cols), out_active)

    if proj_spec is not None:
        # the ProjectNode between join and aggregation, traced in-kernel:
        # the serial _project_impl body over the expanded env (projections
        # are row-preserving, so everything downstream sees the same rows)
        compiled, _proj_symbols = proj_spec
        env = {s: _cval_of(c) for s, c in zip(symbols, out.columns)}
        out = _project_impl(compiled, env, out)
    if agg_spec is not None:
        mode, payload = agg_spec
        if mode == "direct":
            group_keys, aggregations, domains, agg_symbols = payload
            out = _direct_aggregate_impl(
                group_keys, aggregations, domains, agg_symbols, out, "off"
            )
        elif mode == "sort":
            # sort-path grouping: co-sort + boundary detection in-kernel;
            # the reduction stage runs as aggregate_phase after the host
            # reads num_groups (the same sync the serial path performs)
            group_keys, needed, agg_symbols = payload
            return _group_sort_impl(group_keys, needed, agg_symbols, out)
        else:  # "presorted": the self-verifying in-place grouping the
            # serial path takes when the input is ordered on the first
            # group key; the joined page rides out too so a detected
            # violation can re-group through group_sort_phase (the same
            # fallback decision the serial path host-syncs)
            from ..runtime.executor import _presorted_group_impl

            group_keys, needed, agg_symbols = payload
            p, ng, n_grp, viol = _presorted_group_impl(
                group_keys, needed, agg_symbols, out
            )
            return out, p, ng, n_grp, viol
    if epi_spec is not None:
        from .repartition import _partition_dest

        key_idx, n_parts = epi_spec
        dest = _partition_dest(n_parts, key_idx, out)
        return out, dest
    return out, None


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _jit_expand_phase(out_capacity, C, symbols, proj_spec, agg_spec,
                      epi_spec, interpret, tree):
    return _mega_call(
        partial(_expand_phase_body, out_capacity, C, symbols, proj_spec,
                agg_spec, epi_spec),
        tree,
        interpret,
    )


def expand_phase(
    probe_result: Dict[str, object],
    pkeys,
    bkeys,
    luts,
    probe_page: Page,
    build_page: Page,
    out_capacity: int,
    symbols,
    proj_spec,
    agg_spec,
    epi_spec,
    interpret: bool,
):
    """Launch the expand(+project)(+agg)(+repartition) megakernel.

    Returns ``(page, dest)`` — the fused output page plus, when
    ``epi_spec`` is set, the per-row exchange destination computed as the
    kernel's output stage (attach with ``attach_epilogue`` so
    ops/repartition skips its standalone program). For the sort-path
    aggregation (``agg_spec = ("sort", ...)``) it instead returns
    ``(sorted_page, new_group, num_groups)`` — feed those to
    :func:`aggregate_phase` after host-reading num_groups."""
    C = probe_result["C"]
    fused = [OP_JOIN]
    if proj_spec is not None:
        fused.append("project")
    if agg_spec is not None:
        fused.append(OP_AGG)
    if epi_spec is not None:
        fused.append(OP_REPART)
    tree = (
        tuple(pkeys), tuple(bkeys), tuple(luts), probe_page, build_page,
        probe_result["table"], probe_result["counts"],
        probe_result["bucket_p"], probe_result["count"], probe_result["emit"],
    )
    sig = (
        "expand", out_capacity, C, symbols, proj_spec, agg_spec, epi_spec,
        _tree_sig(tree),
    )
    return _spanned_call(
        "expand",
        "+".join(fused),
        f"{probe_result['shape_class']}/out{out_capacity}",
        sig,
        lambda: _jit_expand_phase(
            out_capacity, C, symbols, proj_spec, agg_spec, epi_spec,
            interpret, tree
        ),
    )


def _group_sort_body(group_keys, needed, symbols, page):
    from ..runtime.executor import _group_sort_impl

    return _group_sort_impl(group_keys, needed, symbols, page)


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3))
def _jit_group_sort_phase(group_keys, needed, symbols, interpret, page):
    return _mega_call(
        partial(_group_sort_body, group_keys, needed, symbols), page, interpret
    )


def group_sort_phase(group_keys, needed, symbols, page: Page, interpret: bool):
    """Standalone group-sort kernel: the rare re-group after the presorted
    fast path detected a sortedness violation on the joined page (the same
    one-extra-pass cost the serial path pays for a wrong or stale
    sortedness declaration)."""
    sig = ("group_sort", group_keys, needed, symbols, _tree_sig((page,)))
    return _spanned_call(
        "group_sort", OP_AGG, f"cap{page.capacity}", sig,
        lambda: _jit_group_sort_phase(group_keys, needed, symbols, interpret,
                                      page),
    )


def _agg_phase_body(group_keys, aggregations, needed, out_cap, epi_spec, tree):
    sorted_page, new_group, num_groups = tree
    from ..runtime.executor import _aggregate_impl

    out = _aggregate_impl(
        group_keys, aggregations, needed, out_cap, 0,
        sorted_page, new_group, num_groups,
    )
    if epi_spec is not None:
        from .repartition import _partition_dest

        key_idx, n_parts = epi_spec
        return out, _partition_dest(n_parts, key_idx, out)
    return out, None


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _jit_agg_phase(group_keys, aggregations, needed, out_cap, epi_spec,
                   interpret, tree):
    return _mega_call(
        partial(_agg_phase_body, group_keys, aggregations, needed, out_cap,
                epi_spec),
        tree,
        interpret,
    )


def aggregate_phase(
    group_keys, aggregations, needed, out_cap: int,
    sorted_page: Page, new_group, num_groups, epi_spec, interpret: bool,
) -> Tuple[Page, Optional[jnp.ndarray]]:
    """The sort-path reduction stage as ONE kernel: the serial
    _aggregate_impl body (cumsum-at-boundaries segment sums et al) over the
    group-sorted page the expand phase produced, plus the optional fused
    repartition dest. Lane-valued aggregates (array_agg & co) never reach
    here — their static lane width needs its own host sync, so the executor
    keeps them on the serial path."""
    tree = (sorted_page, new_group, num_groups)
    sig = (
        "aggregate", group_keys, aggregations, needed, out_cap, epi_spec,
        _tree_sig(tree),
    )
    fused = OP_AGG if epi_spec is None else f"{OP_AGG}+{OP_REPART}"
    return _spanned_call(
        "aggregate", fused, f"out{out_cap}", sig,
        lambda: _jit_agg_phase(
            group_keys, aggregations, needed, out_cap, epi_spec, interpret,
            tree
        ),
    )


# --------------------------------------------------------------------------- #
# standalone fused repartition epilogue (the TPU-tier output stage)
# --------------------------------------------------------------------------- #


def fused_epilogue(page: Page, key_idx: Sequence[int], n_parts: int,
                   interpret: bool = True):
    """hash -> stable cosort -> offsets as ONE kernel: the full device
    epilogue of ops/repartition run as a megakernel output stage, returning
    (sorted_page, offsets, counts) bit-identical to
    repartition._repartition_epilogue (it re-traces the same body).

    Status: the TPU-tier formulation staged for the ROADMAP item-2
    hardware ladder — the live CPU exchange path consumes the cheaper
    fused ``dest`` lane instead (repartition_to_host's host grouping needs
    no device cosort), so today's only caller is the tier-1 bit-identity
    test. Wire this into repartition_to_host's TPU branch when the Mosaic
    lowering lands; keeping it under the interpret contract is what stops
    that wiring from regressing in the meantime."""
    key_idx = tuple(key_idx)
    sig = ("epilogue", n_parts, key_idx, _tree_sig((page,)))
    return _spanned_call(
        "epilogue", OP_REPART, f"cap{page.capacity}/n{n_parts}", sig,
        lambda: _jit_fused_epilogue(n_parts, key_idx, interpret, page),
    )


@partial(kernelcost.jit, static_argnums=(0, 1, 2))
def _jit_fused_epilogue(n_parts, key_idx, interpret, page):
    from .repartition import _repartition_epilogue

    return _mega_call(
        lambda p: _repartition_epilogue(n_parts, key_idx, p), page, interpret
    )


def attach_epilogue(page: Page, dest, key_idx: Sequence[int], n_parts: int,
                    keys: Sequence[str] = ()) -> None:
    """Ride the fused per-row destination on the page object; consumed once
    by ops/repartition._take_fused_dest for the matching exchange spec.
    ``keys`` (symbol names) let :func:`reattach_epilogue` carry the payload
    across column-reordering page rewraps at fragment boundaries."""
    page._megakernel_epilogue = {
        "dest": dest, "key_idx": tuple(key_idx), "n_parts": int(n_parts),
        "keys": tuple(keys),
    }


def reattach_epilogue(src_page: Page, dst_page: Page,
                      dst_symbols: Sequence[str]) -> None:
    """Fragment roots rewrap their relation into an output-symbol-ordered
    Page (parallel/runner.run_fragment_partition); the fused dest survives
    the rewrap by re-deriving key_idx against the new column order. The
    dest VALUES stay valid — they are a function of key values, and rewraps
    reorder columns without touching rows."""
    payload = src_page.__dict__.pop("_megakernel_epilogue", None)
    if not payload:
        return
    keys = payload.get("keys")
    dst_symbols = tuple(dst_symbols)
    if not keys or any(k not in dst_symbols for k in keys):
        return
    dst_page._megakernel_epilogue = {
        "dest": payload["dest"], "n_parts": payload["n_parts"],
        "keys": keys,
        "key_idx": tuple(dst_symbols.index(k) for k in keys),
    }
