"""Iceberg-lite: snapshot-versioned tables, time travel, optimistic commits.

ref: plugin/trino-iceberg IcebergMetadata.java (snapshot log + manifest
scans + optimistic metadata commit). The round-5 "done" bar from the
verdict: CTAS -> two inserts -> read at each snapshot; concurrent-commit
conflict detected.
"""

import pytest

from trino_tpu.connectors.iceberg_lite import CommitConflict, IcebergLiteConnector
from trino_tpu.fs import FileSystemManager, LocalFileSystem
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.spi.connector import SchemaTableName


@pytest.fixture()
def berg_runner(tmp_path):
    fsm = FileSystemManager()
    fsm.register("local", lambda: LocalFileSystem(str(tmp_path)))
    berg = IcebergLiteConnector(fsm, "local://warehouse")
    r = LocalQueryRunner.tpch(scale=0.001)
    r.register_catalog("berg", berg)
    return r, berg


class TestSnapshots:
    def test_ctas_then_inserts_snapshot_per_commit(self, berg_runner):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey, n_name FROM nation WHERE n_nationkey < 5"
        )
        assert berg.snapshots("default", "nat") == [1]
        r.execute(
            "INSERT INTO berg.default.nat "
            "SELECT n_nationkey, n_name FROM nation "
            "WHERE n_nationkey BETWEEN 5 AND 9"
        )
        r.execute(
            "INSERT INTO berg.default.nat "
            "SELECT n_nationkey, n_name FROM nation "
            "WHERE n_nationkey BETWEEN 10 AND 14"
        )
        assert berg.snapshots("default", "nat") == [1, 2, 3]
        # current read sees all three commits
        ((n,),) = r.execute("SELECT count(*) FROM berg.default.nat").rows
        assert n == 15

    def test_time_travel_reads_each_snapshot(self, berg_runner):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
        )
        r.execute(
            "INSERT INTO berg.default.nat SELECT n_nationkey FROM nation "
            "WHERE n_nationkey BETWEEN 5 AND 9"
        )
        counts = {
            v: r.execute(
                f"SELECT count(*) FROM berg.default.nat FOR VERSION AS OF {v}"
            ).rows[0][0]
            for v in (1, 2)
        }
        assert counts == {1: 5, 2: 10}
        # snapshot 1's ROWS, not just counts
        rows = r.execute(
            "SELECT n_nationkey FROM berg.default.nat FOR VERSION AS OF 1 "
            "ORDER BY 1"
        ).rows
        assert [x[0] for x in rows] == [0, 1, 2, 3, 4]

    def test_missing_snapshot_errors(self, berg_runner):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS SELECT n_nationkey FROM nation"
        )
        with pytest.raises(Exception) as e:
            r.execute("SELECT * FROM berg.default.nat FOR VERSION AS OF 99")
        assert "99" in str(e.value)

    def test_non_versioned_connector_rejects_time_travel(self, berg_runner):
        r, _ = berg_runner
        with pytest.raises(Exception) as e:
            r.execute("SELECT * FROM nation FOR VERSION AS OF 1")
        assert "VERSION" in str(e.value).upper()


class TestOptimisticCommit:
    def test_concurrent_commit_conflict_detected(self, berg_runner):
        r, berg = berg_runner
        r.execute(
            "CREATE TABLE berg.default.nat AS SELECT n_nationkey FROM nation"
        )
        parent = berg.current_snapshot_id("default", "nat")
        # writer A commits parent+1 first
        berg._commit_snapshot("default", "nat", parent, [], "append")
        # writer B raced on the SAME parent: must conflict, not overwrite
        with pytest.raises(CommitConflict):
            berg._commit_snapshot("default", "nat", parent, [], "append")

    def test_loser_files_stay_invisible(self, berg_runner):
        r, berg = berg_runner
        name = SchemaTableName("default", "nat")
        r.execute(
            "CREATE TABLE berg.default.nat AS "
            "SELECT n_nationkey FROM nation WHERE n_nationkey < 5"
        )
        stale = berg.current_snapshot_id("default", "nat")
        # a racing writer commits INSIDE this insert's read->commit window:
        # pin the stale parent the insert resolved, then land the racer
        berg._commit_snapshot(
            "default", "nat", stale,
            berg.read_snapshot("default", "nat", stale)["files"], "append",
        )
        orig = berg.current_snapshot_id
        berg.current_snapshot_id = lambda s, t: stale  # the stale read
        try:
            with pytest.raises(CommitConflict):
                r.execute(
                    "INSERT INTO berg.default.nat SELECT n_nationkey FROM nation "
                    "WHERE n_nationkey >= 5"
                )
        finally:
            berg.current_snapshot_id = orig
        # the loser's data objects were written but are referenced by NO
        # snapshot: readers still see only committed data
        ((n,),) = r.execute("SELECT count(*) FROM berg.default.nat").rows
        assert n == 5
