"""IVF vector index connector: the ANN serving tier's storage substrate.

An inverted-file (IVF) index persisted on the ``fs.py`` object-store
abstraction: deterministic k-means centroids in ``meta.json`` plus one
``cluster_<i>.json`` row file per cluster.  Each cluster IS a split, so the
planner's centroid pre-pass (``fuse_vector_topn`` in ``ann_mode=approx``)
prunes splits exactly the way partition pruning does — the executor never
learns a new protocol, it just sees fewer splits.

Determinism contract (the tier-1 bit-identity tests lean on every clause):

- k-means is plain numpy with evenly-spaced init over the input row order and
  a fixed iteration count — no RNG, so rebuilding from the same rows yields
  the same centroids, assignments, and files.
- NULL vectors are excluded from centroid math (they would poison means) and
  assigned to cluster 0; empty clusters keep their previous centroid (never
  NaN).
- ``get_splits`` returns cluster ids in ASCENDING order both with and without
  a probe, so ``nprobe == n_clusters`` reads the exact scan's split sequence
  and the merged page is bitwise identical to exact mode.

Reference blueprint: plugin/trino-memory for the connector skeleton,
plugin/trino-iceberg's JSON-metadata-on-TrinoFileSystem idiom for persistence.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..fs import FileSystemManager, Location
from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Column, Page
from ..spi.types import is_string, is_vector, parse_type

KMEANS_ITERS = 10

# the similarity functions the probe pre-pass understands; scores are
# "higher is better" after the l2 negation below
PROBE_METRICS = ("dot_product", "cosine_similarity", "l2_distance")


def _kmeans(vecs: np.ndarray, k: int, iters: int = KMEANS_ITERS):
    """Deterministic k-means: evenly-spaced init over input order, fixed
    iteration count, empty clusters keep their previous centroid."""
    m = len(vecs)
    k = max(1, min(int(k), m))
    init = np.round(np.linspace(0, m - 1, k)).astype(int)
    centroids = vecs[init].astype(np.float64).copy()
    assign = np.zeros(m, dtype=np.int64)
    for _ in range(iters):
        d2 = ((vecs[:, None, :] - centroids[None, :, :]) ** 2).sum(axis=2)
        assign = d2.argmin(axis=1)
        for c in range(k):
            members = vecs[assign == c]
            if len(members):
                centroids[c] = members.mean(axis=0)
    return centroids, assign


def _centroid_scores(centroids: np.ndarray, q: np.ndarray, metric: str) -> np.ndarray:
    """Per-centroid probe score, higher = probe first (l2 is negated)."""
    c = np.asarray(centroids, dtype=np.float64)
    qv = np.asarray(q, dtype=np.float64)
    if metric == "l2_distance":
        return -((c - qv) ** 2).sum(axis=1)
    dots = c @ qv
    if metric == "cosine_similarity":
        norms = np.sqrt((c * c).sum(axis=1)) * np.sqrt(float(qv @ qv))
        safe = norms > 0.0
        dots = np.where(safe, dots / np.where(safe, norms, 1.0), -np.inf)
    return dots


def _json_value(type_, v):
    if v is None:
        return None
    if is_vector(type_):
        return [float(x) for x in v]
    if is_string(type_):
        return str(v)
    if isinstance(v, np.generic):
        return v.item()
    return v


class IvfVectorConnector(Connector):
    """IVF index tables persisted as JSON objects on a TrinoFileSystem."""

    name = "vector_index"

    def __init__(self, fs_manager: FileSystemManager, base_uri: str):
        self._fsm = fs_manager
        self._root = Location.parse(base_uri)
        self._lock = threading.RLock()
        self._meta = _IvfMetadata(self)
        self._splits = _IvfSplitManager(self)
        self._pages = _IvfPageSourceProvider(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    # --------------------------------------------------------------- storage

    def _fs(self):
        return self._fsm.for_location(self._root)

    def _table_loc(self, name: SchemaTableName) -> Location:
        return self._root.child(name.schema, name.table)

    def _load_meta(self, name: SchemaTableName) -> Optional[dict]:
        """Read ``meta.json`` fresh from the filesystem every time: split
        re-reads after spill/FTE restarts must observe the same on-store
        state, never an in-process cache that a rebuild already advanced."""
        fs = self._fs()
        loc = self._table_loc(name).child("meta.json")
        if not fs.exists(loc):
            return None
        return json.loads(fs.read(loc))

    def _load_cluster(self, name: SchemaTableName, cluster: int) -> List[list]:
        fs = self._fs()
        loc = self._table_loc(name).child(f"cluster_{cluster}.json")
        return json.loads(fs.read(loc))["rows"]

    # ------------------------------------------------------------------- DDL

    def build_index(
        self,
        name: SchemaTableName,
        columns: Sequence[ColumnMetadata],
        rows: Sequence[tuple],
        vector_column: str,
        n_clusters: int,
    ) -> dict:
        """(Re)build the IVF index for ``rows`` and persist it. Returns the
        written ``meta.json`` dict (tests inspect centroids/sizes)."""
        columns = tuple(columns)
        try:
            vec_idx = next(
                i for i, c in enumerate(columns) if c.name == vector_column
            )
        except StopIteration:
            raise ValueError(f"no such column: {vector_column}")
        vtype = columns[vec_idx].type
        if not is_vector(vtype):
            raise ValueError(f"not a vector column: {vector_column}")
        dim = vtype.dimension

        rows = [tuple(r) for r in rows]
        present = [
            (pos, np.asarray(r[vec_idx], dtype=np.float64))
            for pos, r in enumerate(rows)
            if r[vec_idx] is not None
        ]
        if present:
            vecs = np.stack([v for _, v in present])
            centroids, assign = _kmeans(vecs, n_clusters)
        else:
            # all-NULL (or empty) input: one zero centroid, everything in
            # cluster 0 — the index stays well-formed, never NaN
            centroids = np.zeros((1, dim), dtype=np.float64)
            assign = np.zeros(0, dtype=np.int64)
        k = len(centroids)

        cluster_of = {pos: int(c) for (pos, _), c in zip(present, assign)}
        buckets: List[List[list]] = [[] for _ in range(k)]
        for pos, r in enumerate(rows):
            # NULL vectors land in cluster 0 (excluded from centroid math)
            buckets[cluster_of.get(pos, 0)].append(
                [_json_value(c.type, v) for c, v in zip(columns, r)]
            )

        with self._lock:
            prev = self._load_meta(name)
            version = int(prev["version"]) + 1 if prev else 1
            fs = self._fs()
            loc = self._table_loc(name)
            for i, bucket in enumerate(buckets):
                fs.write(
                    loc.child(f"cluster_{i}.json"),
                    json.dumps({"rows": bucket}).encode(),
                )
            meta = {
                "columns": [[c.name, c.type.display()] for c in columns],
                "vector_column": vector_column,
                "dim": dim,
                "n_clusters": k,
                "cluster_sizes": [len(b) for b in buckets],
                "centroids": [[float(x) for x in c] for c in centroids],
                "version": version,
                # fresh per build: equal ids <=> same build <=> same bytes,
                # across connector instances and processes (cache tokens)
                "index_id": uuid.uuid4().hex[:12],
            }
            # meta lands last: readers keep resolving the previous complete
            # build until the new one is fully on store. This is the
            # marker-last publication rule the object-store substrate
            # requires (runtime/objectstore.py): cluster objects without
            # their meta marker are invisible, a torn build can never be
            # selected, and per-key meta reads are strongly consistent —
            # only DISCOVERY of brand-new tables (_list_indexes, a prefix
            # LIST) is exposed to list-after-write lag, never reads of an
            # already-resolved table
            fs.write(loc.child("meta.json"), json.dumps(meta, indent=1).encode())
        return meta

    def drop_index(self, name: SchemaTableName, if_exists: bool = False) -> None:
        with self._lock:
            fs = self._fs()
            loc = self._table_loc(name)
            entries = list(fs.list_files(loc))
            if not entries:
                if if_exists:
                    return
                raise ValueError(f"index not found: {name}")
            for e in entries:
                fs.delete(e.location)

    # ------------------------------------------------------- warm-path cache

    def cache_table_version(self, schema: str, table: str):
        """Warm-path cache plane hook (runtime/cachestore.py): the build-time
        ``index_id`` is drawn fresh per build, so equal tokens imply the same
        persisted bytes — across connector instances AND processes (unlike
        the memory connector, whose nonce is per instance)."""
        meta = self._load_meta(SchemaTableName(schema, table))
        if meta is None:
            return None
        return f"ivf{meta['index_id']}-{meta['version']}"

    # ------------------------------------------------------------- ANN probe

    def ann_probe_handle(
        self,
        handle: TableHandle,
        column_name: str,
        q: Sequence[float],
        nprobe: int,
        metric: str,
    ) -> Optional[TableHandle]:
        """Attach a centroid-probe spec to the scan handle, or None when this
        index cannot serve the probe (wrong column/dim/metric) — the planner
        then keeps the exact scan. Duck-typed: the optimizer looks this
        method up with getattr, connectors without it never probe."""
        import dataclasses

        meta = self._load_meta(handle.schema_table)
        if meta is None or metric not in PROBE_METRICS:
            return None
        if meta["vector_column"] != column_name or len(q) != int(meta["dim"]):
            return None
        ch = dict(handle.connector_handle or {})
        ch["ann_probe"] = {
            "q": tuple(float(x) for x in q),
            "nprobe": max(1, int(nprobe)),
            "metric": metric,
        }
        return dataclasses.replace(handle, connector_handle=ch)


class _IvfMetadata(ConnectorMetadata):
    def __init__(self, connector: IvfVectorConnector):
        self.connector = connector

    def _list_indexes(self) -> List[SchemaTableName]:
        fs = self.connector._fs()
        prefix = self.connector._root.uri().rstrip("/") + "/"
        out = set()
        for entry in fs.list_files(self.connector._root):
            uri = entry.location.uri()
            if not uri.endswith("/meta.json") or not uri.startswith(prefix):
                continue
            parts = uri[len(prefix):].split("/")
            if len(parts) == 3:
                out.add(SchemaTableName(parts[0], parts[1]))
        return sorted(out, key=str)

    def list_schemas(self):
        return sorted({n.schema for n in self._list_indexes()} | {"default"})

    def list_tables(self, schema: Optional[str] = None):
        return [
            n for n in self._list_indexes() if schema is None or n.schema == schema
        ]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        meta = self.connector._load_meta(name)
        if meta is None:
            return None
        cols = tuple(
            ColumnMetadata(cname, parse_type(ts)) for cname, ts in meta["columns"]
        )
        return TableMetadata(name, cols)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        meta = self.connector._load_meta(handle.schema_table)
        if meta is None:
            return TableStatistics(row_count=0.0)
        return TableStatistics(row_count=float(sum(meta["cluster_sizes"])))


class _IvfSplitManager(ConnectorSplitManager):
    def __init__(self, connector: IvfVectorConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        from ..ops import tensor as T

        meta = self.connector._load_meta(handle.schema_table)
        if meta is None:
            return []
        n = int(meta["n_clusters"])
        centroids = meta["centroids"]
        ch = handle.connector_handle
        probe = ch.get("ann_probe") if isinstance(ch, dict) else None
        selected = list(range(n))
        if probe is not None and n:
            nprobe = min(max(1, int(probe["nprobe"])), n)
            with T.ann_probe_span(n, nprobe):
                scores = _centroid_scores(
                    np.asarray(centroids, dtype=np.float64),
                    np.asarray(probe["q"], dtype=np.float64),
                    probe["metric"],
                )
                order = np.argsort(-scores, kind="stable")
                # ascending cluster-id order: nprobe == n_clusters replays
                # the exact scan's split sequence bit-for-bit
                selected = sorted(int(i) for i in order[:nprobe])
            T.on_ann_pruned(n - len(selected))
        return [
            Split(
                handle,
                cid,
                len(selected),
                info={
                    "cluster": cid,
                    "total_clusters": n,
                    "centroid": centroids[cid],
                },
            )
            for cid in selected
        ]


class _IvfPageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, connector: IvfVectorConnector):
        self.connector = connector

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        name = split.table.schema_table
        meta = self.connector._load_meta(name)
        if meta is None:
            raise ValueError(f"index not found: {name}")
        cols_meta = [(cn, parse_type(ts)) for cn, ts in meta["columns"]]
        rows = self.connector._load_cluster(name, split.split_id)
        if not rows:
            from ..spi.host_pages import empty_page_for

            names = [cols_meta[i][0] for i in column_indexes]
            types = {cols_meta[i][0]: cols_meta[i][1] for i in column_indexes}
            return empty_page_for(names, types)
        n = len(rows)
        out = []
        for i in column_indexes:
            _, t = cols_meta[i]
            vals = [r[i] for r in rows]
            valid = np.array([v is not None for v in vals], dtype=np.bool_)
            if is_vector(t):
                arr = np.zeros((n, t.dimension), dtype=np.float64)
                for j, v in enumerate(vals):
                    if v is not None:
                        arr[j] = np.asarray(v, dtype=np.float64)
                out.append(Column.from_numpy(t, arr, valid))
            elif is_string(t):
                out.append(Column.from_strings(vals, t))
            else:
                arr = np.array([0 if v is None else v for v in vals])
                out.append(Column.from_numpy(t, arr, valid))
        return Page(tuple(out), jnp.ones(n, dtype=jnp.bool_))
