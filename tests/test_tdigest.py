"""t-digest quantile sketches (ref: operator/aggregation/
TDigestAggregationFunction.java:33 + type/TDigestType).

TPU-native formulation: fixed-K centroid lanes built by one group-sort +
per-lane segment sums, k1 (arcsine) scale for tail resolution; queries walk
the cumulative weights vectorized over rows and centroids.
"""

import numpy as np
import pytest

from trino_tpu.runtime import LocalQueryRunner

SCALE = 0.002


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestTDigest:
    def test_median_close_to_exact(self, runner):
        got = runner.execute(
            "SELECT value_at_quantile(tdigest_agg(l_quantity), 0.5), "
            "approx_percentile(l_quantity, 0.5) FROM lineitem"
        ).rows[0]
        sketch, exact = got
        assert abs(sketch - exact) <= 2.0  # quantity domain 1..50

    def test_tail_quantiles_grouped(self, runner):
        rows = runner.execute(
            "SELECT l_returnflag, "
            "value_at_quantile(tdigest_agg(l_extendedprice), 0.99), "
            "approx_percentile(l_extendedprice, 0.99) "
            "FROM lineitem GROUP BY 1 ORDER BY 1"
        ).rows
        assert len(rows) == 3
        for _, sketch, exact in rows:
            assert abs(sketch - exact) / exact < 0.05  # tails get k1 resolution

    def test_monotone_in_q(self, runner):
        rows = runner.execute(
            "SELECT value_at_quantile(tdigest_agg(l_extendedprice), 0.1), "
            "value_at_quantile(tdigest_agg(l_extendedprice), 0.5), "
            "value_at_quantile(tdigest_agg(l_extendedprice), 0.9) FROM lineitem"
        ).rows[0]
        assert rows[0] <= rows[1] <= rows[2]

    def test_empty_group_is_null(self, runner):
        rows = runner.execute(
            "SELECT value_at_quantile(tdigest_agg(l_quantity), 0.5) "
            "FROM lineitem WHERE l_quantity < 0"
        ).rows
        assert rows == [(None,)]

    def test_digest_value_roundtrips_through_select(self, runner):
        # the digest is a first-class VALUE: it can pass through a subquery
        # before being queried (the reference's qdigest/tdigest column flow)
        rows = runner.execute(
            "SELECT value_at_quantile(d, 0.5) FROM "
            "(SELECT tdigest_agg(l_quantity) d FROM lineitem)"
        ).rows
        assert rows[0][0] is not None


class TestQDigest:
    """qdigest(T) — the typed sibling (QuantileDigestAggregationFunction):
    same centroid lanes, value_at_quantile returns the element type."""

    def test_small_groups_exact(self, runner):
        rows = runner.execute(
            "SELECT k, value_at_quantile(qdigest_agg(v), 0.5) "
            "FROM (VALUES (1,10),(1,20),(1,30),(2,5)) t(k,v) "
            "GROUP BY k ORDER BY k"
        ).rows
        assert rows == [(1, 20), (2, 5)]

    def test_returns_element_type(self, runner):
        got = runner.execute(
            "SELECT value_at_quantile(qdigest_agg(l_orderkey), 0.5) FROM lineitem"
        ).rows[0][0]
        assert isinstance(got, int)

    def test_tracks_exact_percentile(self, runner):
        sketch, exact = runner.execute(
            "SELECT value_at_quantile(qdigest_agg(l_orderkey), 0.9), "
            "approx_percentile(l_orderkey, 0.9) FROM lineitem"
        ).rows[0]
        assert abs(sketch - exact) / max(exact, 1) < 0.1
