"""Worker control plane: async tasks, pull/ack buffers, HMAC auth, recovery.

ref: server/TaskResource.java:93/230/334 (create, status long-poll, results
pull + ack), execution/buffer/PartitionedOutputBuffer.java, server/
InternalAuthenticationManager (shared-secret internal auth), SURVEY.md §3.3.
The plan travels in the schema'd JSON codec — no pickle anywhere on the wire.
"""

import json
import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.metadata import CatalogManager, Session
from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.server.worker import SIGNATURE_HEADER, WorkerServer, sign

SCALE = 0.0005
SECRET = "test-cluster-secret"


def _worker_catalogs():
    c = CatalogManager()
    c.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return c


@pytest.fixture(scope="module")
def workers():
    ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
    yield ws
    for w in ws:
        w.stop()


def _make_dist(workers, n_workers=4):
    dist = DistributedQueryRunner(
        Session(catalog="tpch", schema="sf0_0005"),
        n_workers=n_workers,
        worker_urls=[f"http://{w.address}" for w in workers],
        secret=SECRET,
    )
    dist.catalogs.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return dist


@pytest.fixture(scope="module")
def remote_dist(workers):
    return _make_dist(workers)


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestRemoteWorkers:
    QUERIES = [
        "SELECT count(*), sum(l_quantity) FROM lineitem",
        "SELECT l_returnflag, count(*) c, avg(l_quantity) a FROM lineitem GROUP BY 1 ORDER BY 1",
        "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey WHERE l_quantity < 10",
        "SELECT c_mktsegment, count(*) FROM customer JOIN nation ON c_nationkey = n_nationkey GROUP BY 1 ORDER BY 1",
    ]

    @pytest.mark.parametrize("sql", QUERIES)
    def test_parity_with_local(self, remote_dist, local, sql):
        a = remote_dist.execute(sql).rows
        b = local.execute(sql).rows
        assert len(a) == len(b)
        for ra, rb in zip(a, b):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    assert abs(va - vb) <= 1e-9 * max(1.0, abs(vb))
                else:
                    assert va == vb

    def test_bad_task_body_rejected(self, workers):
        body = b"not json"
        req = urllib.request.Request(
            f"http://{workers[0].address}/v1/task/bogus", data=body, method="POST"
        )
        req.add_header(SIGNATURE_HEADER, sign(SECRET, "POST", "/v1/task/bogus", body))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400

    def test_signature_binds_method_and_path(self, workers):
        # a GET signature must not authorize a DELETE of the same path
        rel = "/v1/task/sometask"
        get_sig = sign(SECRET, "GET", rel)
        req = urllib.request.Request(
            f"http://{workers[0].address}{rel}", method="DELETE"
        )
        req.add_header(SIGNATURE_HEADER, get_sig)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 401
        # nor a POST under a different task id
        body = b"{}"
        sig_a = sign(SECRET, "POST", "/v1/task/a", body)
        req2 = urllib.request.Request(
            f"http://{workers[0].address}/v1/task/b", data=body, method="POST"
        )
        req2.add_header(SIGNATURE_HEADER, sig_a)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req2)
        assert e.value.code == 401

    def test_unsigned_request_rejected(self, workers):
        req = urllib.request.Request(
            f"http://{workers[0].address}/v1/task/bogus", data=b"{}", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 401

    def test_no_pickle_on_the_wire(self):
        import inspect

        import trino_tpu.server.worker as w

        assert "pickle" not in inspect.getsource(w)

    def test_status_longpoll_and_results(self, workers, remote_dist):
        # run a query, then poke the status API of a fresh synthetic task
        remote_dist.execute("SELECT count(*) FROM nation")
        rel = "/v1/task/nonexistent"
        req = urllib.request.Request(
            f"http://{workers[0].address}{rel}?maxWait=0", method="GET"
        )
        req.add_header(SIGNATURE_HEADER, sign(SECRET, "GET", rel))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 404


class TestFailureRecovery:
    def test_worker_death_recovers_with_query_retry(self, local):
        w1 = WorkerServer(_worker_catalogs(), secret=SECRET).start()
        w2 = WorkerServer(_worker_catalogs(), secret=SECRET).start()
        dist = _make_dist([w1, w2])
        dist.session.set("retry_policy", "QUERY")
        sql = "SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1 ORDER BY 1"
        assert dist.execute(sql).rows == local.execute(sql).rows
        # kill one worker; the next execution must fail over to the survivor
        w2.stop()
        try:
            assert dist.execute(sql).rows == local.execute(sql).rows
        finally:
            w1.stop()

    def test_task_failure_propagates_without_retry(self, workers):
        dist = _make_dist(workers)
        dist.session.set("retry_policy", "NONE")
        # a query against a catalog the workers don't mount -> deterministic
        # task failure: surfaces as a plain error, NOT retryable
        dist.catalogs.register(
            "tpch2", TpchConnector(scale=SCALE, split_target_rows=512)
        )
        with pytest.raises(RuntimeError) as e:
            dist.execute("SELECT count(*) FROM tpch2.sf0_0005.nation")
        from trino_tpu.runtime.failure import RetryableQueryError

        assert not isinstance(e.value, RetryableQueryError)


class TestWorkerConcurrency:
    """Round-3 verdict weakness 9: nothing drove many concurrent queries
    through one worker under memory pressure. One WorkerServer takes every
    task of 8 concurrent multi-stage queries with a per-query device-memory
    cap; all results must be exact (ref: TimeSharingTaskExecutor's fairness
    concern — here the property under test is correctness + completion
    under concurrent load, the part a single-device engine must guarantee)."""

    def test_concurrent_queries_one_worker_memory_capped(self, local):
        import threading

        w = WorkerServer(_worker_catalogs(), secret=SECRET).start()
        try:
            expected = {
                "agg": local.execute(
                    "SELECT l_returnflag, count(*), sum(l_quantity) "
                    "FROM lineitem GROUP BY 1 ORDER BY 1"
                ).rows,
                "join": local.execute(
                    "SELECT count(*) FROM lineitem JOIN orders "
                    "ON l_orderkey = o_orderkey"
                ).rows,
            }
            results = {}
            errors = []

            def run_one(i):
                try:
                    dist = DistributedQueryRunner(
                        Session(catalog="tpch", schema="sf0_0005"),
                        n_workers=2,
                        worker_urls=[f"http://{w.address}"],
                        secret=SECRET,
                    )
                    dist.catalogs.register(
                        "tpch", TpchConnector(scale=SCALE, split_target_rows=512)
                    )
                    dist.session.set("query_max_memory_bytes", 64 << 20)
                    kind = "agg" if i % 2 == 0 else "join"
                    sql = (
                        "SELECT l_returnflag, count(*), sum(l_quantity) "
                        "FROM lineitem GROUP BY 1 ORDER BY 1"
                        if kind == "agg"
                        else "SELECT count(*) FROM lineitem JOIN orders "
                        "ON l_orderkey = o_orderkey"
                    )
                    results[i] = (kind, dist.execute(sql).rows)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=run_one, args=(i,)) for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not errors, errors
            assert len(results) == 8
            for kind, rows in results.values():
                assert rows == expected[kind]
        finally:
            w.stop()


class TestFairExecutor:
    """Quanta-style fairness at task granularity
    (TimeSharingTaskExecutor.java:84 / MultilevelSplitQueue analogue)."""

    def test_short_query_not_starved_by_long_backlog(self):
        import time

        from trino_tpu.server.worker import FairTaskExecutor

        ex = FairTaskExecutor(n_threads=2)
        try:
            finished = {}

            def work(q, dur):
                def fn():
                    time.sleep(dur)
                    finished[q] = time.monotonic()

                return fn

            t0 = time.monotonic()
            for i in range(14):
                ex.submit("longq", f"longq_f{i}_p0", work(f"longq{i}", 0.08))
            time.sleep(0.02)  # the long query occupies both threads
            ex.submit("shortq", "shortq_f0_p0", work("short", 0.01))
            deadline = time.monotonic() + 5
            while "short" not in finished and time.monotonic() < deadline:
                time.sleep(0.01)
            assert "short" in finished
            # FIFO would drain ~14*0.08/2 = 0.56s of backlog first; the fair
            # queue runs the short query at the next free slot
            assert finished["short"] - t0 < 0.4
        finally:
            ex.stop()

    def test_scheduling_stats_surface_in_status(self):
        import json
        import time

        from trino_tpu.server.worker import Task, _status_json

        t = Task("q_f0_p0")
        t.queued_at = time.monotonic() - 0.5
        t.started_at = t.queued_at + 0.2
        t.ended_at = t.started_at + 0.1
        st = json.loads(_status_json(t))
        assert 0.15 < st["queuedSecs"] < 0.25
        assert 0.05 < st["runSecs"] < 0.15

    def test_fte_tasks_ride_the_fair_pool(self):
        from trino_tpu.connectors.tpch import TpchConnector
        from trino_tpu.metadata import CatalogManager, Session
        from trino_tpu.parallel.runner import DistributedQueryRunner
        from trino_tpu.server.worker import WorkerServer

        secret = "fair-secret"
        c = CatalogManager()
        c.register("tpch", TpchConnector(scale=0.0005, split_target_rows=512))
        w = WorkerServer(c, secret=secret).start()
        try:
            dist = DistributedQueryRunner(
                Session(catalog="tpch", schema="sf0_0005"),
                n_workers=1,
                worker_urls=[f"http://{w.address}"],
                secret=secret,
            )
            dist.catalogs.register(
                "tpch", TpchConnector(scale=0.0005, split_target_rows=512)
            )
            dist.session.set("retry_policy", "TASK")  # FTE: fair-pool tasks
            dist.session.set("distributed_sort", False)
            assert dist.execute("SELECT count(*) FROM nation").rows == [(25,)]
            # the query's tasks were accounted against its fair-queue usage
            usage = w.tasks.executor._usage
            assert usage and all(v >= 0 for v in usage.values())
        finally:
            w.stop()


class TestLocalExchange:
    def test_colocated_pull_skips_http(self):
        from trino_tpu.connectors.tpch import TpchConnector
        from trino_tpu.metadata import CatalogManager, Session
        from trino_tpu.parallel.runner import DistributedQueryRunner
        from trino_tpu.server.worker import WorkerServer

        secret = "localex-secret"
        c = CatalogManager()
        c.register("tpch", TpchConnector(scale=0.0005, split_target_rows=512))
        w = WorkerServer(c, secret=secret).start()
        try:
            dist = DistributedQueryRunner(
                Session(catalog="tpch", schema="sf0_0005"),
                n_workers=2,
                worker_urls=[f"http://{w.address}"],
                secret=secret,
            )
            dist.catalogs.register(
                "tpch", TpchConnector(scale=0.0005, split_target_rows=512)
            )
            # pipelined tier: producer and consumer tasks land on the ONE
            # worker, so their exchange edges hand off in-process
            res = dist.execute(
                "SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1 ORDER BY 1"
            )
            assert len(res.rows) == 3
            assert w.tasks.local_exchange_pages > 0
        finally:
            w.stop()
