"""DECIMAL(p>18) — the Int128 long-decimal representation, vs exact oracles.

ref: spi/type/Int128.java:23, Int128Math.java, DecimalType MAX_PRECISION 38,
operator/aggregation/DecimalSumAggregation. TPU formulation: two int64 limbs
on a trailing axis (ops/int128.py); aggregation decomposes to four exact
32-bit limb sums at plan time (planner/rules.py
decompose_long_decimal_aggregates).
"""

import decimal
import random

import pytest

from trino_tpu.runtime import LocalQueryRunner

D = decimal.Decimal


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


class TestLiteralsAndArithmetic:
    def test_literal_roundtrip(self, runner):
        assert q(runner, "SELECT 12345678901234567890123456.78") == [
            (D("12345678901234567890123456.78"),)
        ]

    def test_add_carries_across_limb(self, runner):
        # 10**20 - 0.01 + 0.01 crosses the 2**64 boundary
        assert q(runner, "SELECT 99999999999999999999.99 + 0.01") == [
            (D("100000000000000000000.00"),)
        ]

    def test_subtract_negative(self, runner):
        assert q(
            runner, "SELECT 1.00 - 99999999999999999999.99"
        ) == [(D("-99999999999999999999998.99").scaleb(0) + D("99999999999999999900000.00"),)] or q(
            runner, "SELECT 1.00 - 99999999999999999999.99"
        ) == [(D("-99999999999999999998.99"),)]

    def test_multiply_exact_128bit(self, runner):
        got = q(runner, "SELECT 12345678901234567890.55 * 1000000000.1")
        assert got == [(D("12345678902469135780673456789.055"),)]

    def test_mixed_short_long(self, runner):
        got = q(
            runner,
            "SELECT CAST(2 AS bigint) * x FROM (VALUES (99999999999999999999.99)) t(x)",
        )
        assert got == [(D("199999999999999999999.98"),)]

    def test_negate_abs(self, runner):
        got = q(
            runner,
            "SELECT abs(x), -x FROM (VALUES (-12345678901234567890.55)) t(x)",
        )
        assert got == [
            (D("12345678901234567890.55"), D("12345678901234567890.55"))
        ]

    def test_random_arithmetic_vs_python(self, runner):
        rng = random.Random(42)
        for _ in range(8):
            a = rng.randrange(-(10**24), 10**24)
            b = rng.randrange(-(10**24), 10**24)
            got = q(runner, f"SELECT {a}.0 + {b}.0, {a}.0 - {b}.0")
            assert got == [(D(a + b), D(a - b))]


class TestComparisonsAndOrdering:
    def test_filter_and_compare(self, runner):
        got = q(
            runner,
            "SELECT x FROM (VALUES (123456789012345678901.5), (2.5), "
            "(-99999999999999999999999.5)) t(x) WHERE x > 100.0",
        )
        assert got == [(D("123456789012345678901.5"),)]

    def test_order_by_long_decimal(self, runner):
        got = q(
            runner,
            "SELECT x FROM (VALUES (123456789012345678901.5), (2.5), "
            "(-99999999999999999999999.5), (CAST(NULL AS decimal(25,1)))) t(x) "
            "ORDER BY x DESC NULLS LAST",
        )
        assert got == [
            (D("123456789012345678901.5"),),
            (D("2.5"),),
            (D("-99999999999999999999999.5"),),
            (None,),
        ]

    def test_group_by_long_decimal_key(self, runner):
        got = q(
            runner,
            "SELECT x, count(*) FROM (VALUES (123456789012345678901.5), "
            "(123456789012345678901.5), (2.5)) t(x) GROUP BY x ORDER BY x",
        )
        assert got == [(D("2.5"), 1), (D("123456789012345678901.5"), 2)]


class TestAggregation:
    def test_sum_beyond_int64(self, runner):
        # 3 * 8e18 overflows int64; the limb decomposition must not
        vals = ",".join(["(8000000000000000000.00)"] * 3)
        got = q(
            runner,
            f"SELECT sum(CAST(x AS decimal(38,2))) FROM (VALUES {vals}) t(x)",
        )
        assert got == [(D("24000000000000000000.00"),)]

    def test_sum_avg_grouped(self, runner):
        got = q(
            runner,
            "SELECT k, sum(CAST(x AS decimal(38,2))), avg(CAST(x AS decimal(38,2))) "
            "FROM (VALUES (1, 1.00), (1, 2.00), (2, 5.55)) t(k, x) "
            "GROUP BY k ORDER BY k",
        )
        assert got == [(1, D("3.00"), D("1.50")), (2, D("5.55"), D("5.55"))]

    def test_sum_nulls_and_empty(self, runner):
        got = q(
            runner,
            "SELECT sum(x) FROM (VALUES (99999999999999999999.99), "
            "(CAST(NULL AS decimal(22,2)))) t(x)",
        )
        assert got == [(D("99999999999999999999.99"),)]
        got = q(
            runner,
            "SELECT sum(x) FROM (VALUES (99999999999999999999.99)) t(x) WHERE x < 0.0",
        )
        assert got == [(None,)]

    def test_min_max_global_and_grouped(self, runner):
        got = q(
            runner,
            "SELECT max(x), min(x) FROM (VALUES (123456789012345678901.5), "
            "(2.5), (-99999999999999999999999.5)) t(x)",
        )
        assert got == [
            (D("123456789012345678901.5"), D("-99999999999999999999999.5"))
        ]
        got = q(
            runner,
            "SELECT k, max(x), min(x) FROM (VALUES (1, 123456789012345678901.5), "
            "(1, 2.5), (2, -99999999999999999999999.5)) t(k, x) "
            "GROUP BY k ORDER BY k",
        )
        assert got == [
            (1, D("123456789012345678901.5"), D("2.5")),
            (2, D("-99999999999999999999999.5"), D("-99999999999999999999999.5")),
        ]

    def test_random_sums_vs_python(self, runner):
        rng = random.Random(7)
        vals = [rng.randrange(-(10**22), 10**22) for _ in range(40)]
        rows = ",".join(f"({v}.00)" for v in vals)
        got = q(runner, f"SELECT sum(x) FROM (VALUES {rows}) t(x)")
        assert got == [(D(sum(vals)).scaleb(0).quantize(D("0.01")),)]

    def test_distributed_partial_final_split(self, runner):
        # the limb sums must survive the partial/final exchange split
        from trino_tpu.parallel.runner import DistributedQueryRunner

        dist = DistributedQueryRunner.tpch(scale=0.001, n_workers=2)
        got = dist.execute(
            "SELECT sum(CAST(l_extendedprice AS decimal(38,2)) * 1000000000000.0) "
            "FROM lineitem"
        ).rows
        local = LocalQueryRunner.tpch(scale=0.001)
        exp = local.execute(
            "SELECT sum(CAST(l_extendedprice AS decimal(38,2)) * 1000000000000.0) "
            "FROM lineitem"
        ).rows
        assert got == exp
        assert got[0][0] is not None and abs(got[0][0]) > 10**18


class TestCastsAndFunctions:
    def test_cast_long_to_short_and_back(self, runner):
        got = q(
            runner,
            "SELECT CAST(CAST(123456.78 AS decimal(38,2)) AS decimal(10,2))",
        )
        assert got == [(123456.78,)]

    def test_cast_long_to_double_bigint(self, runner):
        got = q(
            runner,
            "SELECT CAST(x AS double), CAST(x AS bigint) FROM "
            "(VALUES (CAST(1234567.49 AS decimal(38,2)))) t(x)",
        )
        assert got == [(1234567.49, 1234567)]

    def test_long_rescale(self, runner):
        got = q(
            runner,
            "SELECT CAST(x AS decimal(38,4)) FROM "
            "(VALUES (99999999999999999999.99)) t(x)",
        )
        assert got == [(D("99999999999999999999.9900"),)]

    def test_case_and_coalesce(self, runner):
        got = q(
            runner,
            "SELECT CASE WHEN x > 0.0 THEN x ELSE -x END, "
            "coalesce(CAST(NULL AS decimal(38,2)), 12345678901234567890123456.78) "
            "FROM (VALUES (-99999999999999999999999.5)) t(x)",
        )
        assert got == [
            (D("99999999999999999999999.5"), D("12345678901234567890123456.78"))
        ]

    def test_out_of_range_narrowing_is_null(self, runner):
        # long -> short casts of unrepresentable values yield NULL, never a
        # silently truncated number (Trino raises; documented deviation)
        got = q(
            runner,
            "SELECT try_like_marker FROM (SELECT CAST(99999999999999999999.99 "
            "AS decimal(18,2)) AS try_like_marker) t",
        )
        assert got == [(None,)]
