"""approx_distinct (HyperLogLog) + approx_percentile correctness.

Model: the reference's TestApproximateCountDistinct /
AbstractTestAggregations (testing/trino-testing) — approximate aggregates are
validated within their published error bounds against exact answers.
"""

import numpy as np
import pytest

from tests.oracle import tpch_df

SCALE = 0.002


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


class TestApproxDistinct:
    def test_global_high_cardinality(self, runner):
        res = runner.execute("SELECT approx_distinct(l_orderkey) FROM lineitem")
        exact = tpch_df("lineitem", SCALE).l_orderkey.nunique()
        got = res.rows[0][0]
        # m=2048 registers -> sigma ~2.3%; allow 5 sigma
        assert abs(got - exact) <= max(3, 0.115 * exact), (got, exact)

    def test_small_cardinality_is_exact(self, runner):
        # linear-counting range: tiny distinct counts come back exact
        res = runner.execute("SELECT approx_distinct(l_linestatus) FROM lineitem")
        assert res.rows[0][0] == tpch_df("lineitem", SCALE).l_linestatus.nunique()

    def test_grouped(self, runner):
        res = runner.execute(
            "SELECT l_returnflag, approx_distinct(l_partkey) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag"
        )
        li = tpch_df("lineitem", SCALE)
        exact = li.groupby("l_returnflag").l_partkey.nunique().sort_index()
        assert [r[0] for r in res.rows] == list(exact.index)
        for (_, got), (_, want) in zip(res.rows, exact.items()):
            assert abs(got - want) <= max(3, 0.115 * want), (got, want)

    def test_null_only_group_is_zero(self, runner):
        res = runner.execute(
            "SELECT approx_distinct(CASE WHEN l_quantity < 0 THEN l_orderkey END) "
            "FROM lineitem"
        )
        assert res.rows == [(0,)]


class TestApproxPercentile:
    def test_global_median(self, runner):
        res = runner.execute(
            "SELECT approx_percentile(l_quantity, 0.5) FROM lineitem"
        )
        li = tpch_df("lineitem", SCALE)
        want = np.quantile(li.l_quantity.to_numpy(), 0.5, method="lower")
        assert float(res.rows[0][0]) == pytest.approx(float(want), abs=1.0)

    def test_extremes_match_min_max(self, runner):
        res = runner.execute(
            "SELECT approx_percentile(l_extendedprice, 0.0), "
            "approx_percentile(l_extendedprice, 1.0), "
            "min(l_extendedprice), max(l_extendedprice) FROM lineitem"
        )
        p0, p1, mn, mx = res.rows[0]
        assert p0 == mn and p1 == mx

    def test_grouped(self, runner):
        res = runner.execute(
            "SELECT l_returnflag, approx_percentile(l_quantity, 0.9) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag"
        )
        li = tpch_df("lineitem", SCALE)
        want = (
            li.groupby("l_returnflag")
            .l_quantity.apply(lambda s: np.quantile(s.to_numpy(), 0.9, method="lower"))
            .sort_index()
        )
        for (flag, got), (wflag, w) in zip(res.rows, want.items()):
            assert flag == wflag
            assert float(got) == pytest.approx(float(w), abs=1.0), flag
