"""Map-valued aggregates, listagg, aggregate ORDER BY, INTERSECT/EXCEPT ALL.

Model: the reference's TestMapAggAggregation / TestMultimapAggAggregation /
TestHistogram / listagg tests (operator/aggregation/) and
TestSetOperations INTERSECT ALL / EXCEPT ALL coverage (Trino lowers those via
rule/ImplementIntersectAll + ImplementExceptAll — row_number vs counts; the
planner here uses the same formulation).
"""

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def rows(runner, sql):
    return runner.execute(sql).rows


def one(runner, sql):
    r = rows(runner, sql)
    assert len(r) == 1
    return r[0]


class TestMapAgg:
    def test_grouped(self, runner):
        got = rows(
            runner,
            "SELECT k, map_agg(k2, v) FROM (VALUES ('a','x',1),('a','y',2),"
            "('b','x',3)) t(k,k2,v) GROUP BY k ORDER BY k",
        )
        assert got == [("a", {"x": 1, "y": 2}), ("b", {"x": 3})]

    def test_duplicate_keys_keep_one(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1),('x',9)) t(k,v)",
        )
        assert set(m.keys()) == {"x"} and m["x"] in (1, 9)

    def test_null_keys_skipped_and_empty_is_null(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1),(NULL,2)) t(k,v)",
        )
        assert m == {"x": 1}
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES ('x',1)) t(k,v) WHERE k='zz'",
        )
        assert m is None

    def test_bigint_keys(self, runner):
        (m,) = one(
            runner,
            "SELECT map_agg(k, v) FROM (VALUES (10,'a'),(20,'b')) t(k,v)",
        )
        assert m == {10: "a", 20: "b"}


class TestHistogram:
    def test_basic(self, runner):
        (m,) = one(
            runner,
            "SELECT histogram(k) FROM (VALUES ('a'),('b'),('a'),(NULL)) t(k)",
        )
        assert m == {"a": 2, "b": 1}

    def test_grouped_numeric(self, runner):
        got = rows(
            runner,
            "SELECT g, histogram(v) FROM (VALUES (1,5),(1,5),(1,6),(2,7)) "
            "t(g,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, {5: 2, 6: 1}), (2, {7: 1})]


class TestMultimapAgg:
    def test_basic(self, runner):
        (m,) = one(
            runner,
            "SELECT multimap_agg(k, v) FROM (VALUES ('x',1),('x',2),('y',3)) t(k,v)",
        )
        assert m == {"x": [1, 2], "y": [3]}

    def test_grouped(self, runner):
        got = rows(
            runner,
            "SELECT g, multimap_agg(k, v) FROM (VALUES (1,'x',1),(1,'x',2),"
            "(2,'y',3)) t(g,k,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, {"x": [1, 2]}), (2, {"y": [3]})]


class TestListagg:
    def test_within_group(self, runner):
        got = rows(
            runner,
            "SELECT k, listagg(v, ',') WITHIN GROUP (ORDER BY v) FROM "
            "(VALUES ('g1','b'),('g1','a'),('g2','z')) t(k,v) GROUP BY k ORDER BY k",
        )
        assert got == [("g1", "a,b"), ("g2", "z")]

    def test_default_separator_and_nulls_skipped(self, runner):
        (s,) = one(
            runner,
            "SELECT listagg(v) WITHIN GROUP (ORDER BY v) FROM "
            "(VALUES ('b'),('a'),(NULL)) t(v)",
        )
        assert s == "ab"

    def test_desc_order(self, runner):
        (s,) = one(
            runner,
            "SELECT listagg(v, '-') WITHIN GROUP (ORDER BY v DESC) FROM "
            "(VALUES ('a'),('c'),('b')) t(v)",
        )
        assert s == "c-b-a"


class TestArrayAggOrderBy:
    def test_order_by_other_column(self, runner):
        (a,) = one(
            runner,
            "SELECT array_agg(v ORDER BY s DESC) FROM "
            "(VALUES ('p','a'),('q','b'),('r','c')) t(v,s)",
        )
        assert a == ["r", "q", "p"]

    def test_grouped_order_by(self, runner):
        got = rows(
            runner,
            "SELECT g, array_agg(v ORDER BY v) FROM "
            "(VALUES (1,3),(1,1),(2,5),(1,2)) t(g,v) GROUP BY g ORDER BY g",
        )
        assert got == [(1, [1, 2, 3]), (2, [5])]


class TestIntersectExceptAll:
    def test_intersect_all(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1),(1),(2),(3)) a(x) INTERSECT ALL "
            "SELECT y FROM (VALUES (1),(1),(1),(2)) b(y) ORDER BY x",
        )
        assert got == [(1,), (1,), (2,)]

    def test_except_all(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1),(1),(1),(2),(4)) a(x) EXCEPT ALL "
            "SELECT y FROM (VALUES (1),(2),(3)) b(y) ORDER BY x",
        )
        assert got == [(1,), (1,), (4,)]

    def test_intersect_all_strings(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES ('a'),('a'),('b')) a(x) INTERSECT ALL "
            "SELECT y FROM (VALUES ('a'),('c')) b(y)",
        )
        assert got == [("a",)]

    def test_except_all_empty_result(self, runner):
        got = rows(
            runner,
            "SELECT x FROM (VALUES (1)) a(x) EXCEPT ALL "
            "SELECT y FROM (VALUES (1),(1)) b(y)",
        )
        assert got == []


class TestRound3Aggregates:
    """min_by/max_by, two-column statistics, central moments, checksum
    (ref: operator/aggregation/minmaxby/, CorrelationAggregation,
    CentralMomentsAggregation, ChecksumAggregationFunction)."""

    def test_min_by_max_by(self, runner):
        rows = runner.execute(
            "SELECT n_regionkey, min_by(n_name, n_nationkey), "
            "max_by(n_name, n_nationkey) FROM nation "
            "GROUP BY n_regionkey ORDER BY n_regionkey"
        ).rows
        import pandas as pd
        from tests.oracle import tpch_df

        df = tpch_df("nation", 0.0005)
        for rk, lo_name, hi_name in rows:
            g = df[df.n_regionkey == rk]
            assert lo_name == g.loc[g.n_nationkey.idxmin()].n_name
            assert hi_name == g.loc[g.n_nationkey.idxmax()].n_name

    def test_min_by_global_and_null_keys(self, runner):
        ((v,),) = runner.execute(
            "SELECT max_by(o_orderkey, o_totalprice) FROM orders"
        ).rows
        from tests.oracle import tpch_df

        df = tpch_df("orders", 0.0005)
        assert v == int(df.loc[df.o_totalprice.idxmax()].o_orderkey)

    def test_corr_and_covar(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        rows = runner.execute(
            "SELECT corr(l_extendedprice, l_quantity), "
            "covar_pop(l_extendedprice, l_quantity), "
            "covar_samp(l_extendedprice, l_quantity) FROM lineitem"
        ).rows
        df = tpch_df("lineitem", 0.0005)
        y = df.l_extendedprice.to_numpy()
        x = df.l_quantity.to_numpy()
        want_corr = np.corrcoef(y, x)[0, 1]
        want_cp = np.cov(y, x, bias=True)[0, 1]
        want_cs = np.cov(y, x, bias=False)[0, 1]
        (c, cp, cs), = rows
        assert abs(c - want_corr) < 1e-9
        assert abs(cp - want_cp) < 1e-6 * abs(want_cp)
        assert abs(cs - want_cs) < 1e-6 * abs(want_cs)

    def test_regr_slope_intercept(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        ((slope, intercept),) = runner.execute(
            "SELECT regr_slope(l_extendedprice, l_quantity), "
            "regr_intercept(l_extendedprice, l_quantity) FROM lineitem"
        ).rows
        df = tpch_df("lineitem", 0.0005)
        y = df.l_extendedprice.to_numpy()
        x = df.l_quantity.to_numpy()
        ws, wi = np.polyfit(x, y, 1)
        assert abs(slope - ws) < 1e-6 * abs(ws)
        assert abs(intercept - wi) < 1e-6 * max(1.0, abs(wi))

    def test_skewness_kurtosis(self, runner):
        import numpy as np

        ((sk, ku),) = runner.execute(
            "SELECT skewness(l_quantity), kurtosis(l_quantity) FROM lineitem"
        ).rows
        from tests.oracle import tpch_df

        x = tpch_df("lineitem", 0.0005).l_quantity.to_numpy().astype(float)
        n = len(x)
        m = x.mean()
        M2 = ((x - m) ** 2).sum()
        M3 = ((x - m) ** 3).sum()
        M4 = ((x - m) ** 4).sum()
        want_sk = np.sqrt(n) * M3 / M2**1.5
        want_ku = (n * (n + 1) / ((n - 1) * (n - 2) * (n - 3))) * (
            n * M4 / (M2 * M2)
        ) - 3 * (n - 1) ** 2 / ((n - 2) * (n - 3))
        assert abs(sk - want_sk) < 1e-6 * max(1, abs(want_sk))
        assert abs(ku - want_ku) < 1e-6 * max(1, abs(want_ku))

    def test_geometric_mean(self, runner):
        import numpy as np

        ((g,),) = runner.execute(
            "SELECT geometric_mean(l_quantity) FROM lineitem WHERE l_quantity > 0"
        ).rows
        from tests.oracle import tpch_df

        x = tpch_df("lineitem", 0.0005).l_quantity.to_numpy().astype(float)
        x = x[x > 0]
        want = float(np.exp(np.log(x).mean()))
        assert abs(g - want) < 1e-9 * max(1, abs(want))

    def test_checksum_order_insensitive(self, runner):
        ((a,),) = runner.execute(
            "SELECT checksum(l_orderkey) FROM lineitem"
        ).rows
        ((b,),) = runner.execute(
            "SELECT checksum(l_orderkey) FROM "
            "(SELECT l_orderkey FROM lineitem ORDER BY l_extendedprice)"
        ).rows
        assert a == b
        ((c,),) = runner.execute(
            "SELECT checksum(l_orderkey) FROM lineitem WHERE l_orderkey > 10"
        ).rows
        assert c != a

    def test_grouped_two_column_stats(self, runner):
        rows = runner.execute(
            "SELECT l_returnflag, corr(l_extendedprice, l_quantity) "
            "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows
        import numpy as np
        from tests.oracle import tpch_df

        df = tpch_df("lineitem", 0.0005)
        for flag, c in rows:
            g = df[df.l_returnflag == flag]
            want = np.corrcoef(g.l_extendedprice, g.l_quantity)[0, 1]
            assert abs(c - want) < 1e-9


class TestChecksumNullSemantics:
    def test_all_null_group_nonnull_checksum(self, runner):
        # NULL rows update the checksum state (PRIME64 term) — only a
        # zero-row group returns NULL (ref ChecksumAggregationFunction)
        rows = runner.execute(
            "SELECT checksum(x) FROM (VALUES CAST(NULL AS bigint)) t(x)"
        ).rows
        assert rows[0][0] is not None


class TestRegressionFamily:
    """regr_* beyond slope/intercept (RegressionAggregation full family)."""

    def test_full_family_vs_numpy(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        rows = runner.execute(
            "SELECT regr_count(l_quantity, l_extendedprice),"
            " regr_avgx(l_quantity, l_extendedprice),"
            " regr_avgy(l_quantity, l_extendedprice),"
            " regr_sxx(l_quantity, l_extendedprice),"
            " regr_syy(l_quantity, l_extendedprice),"
            " regr_sxy(l_quantity, l_extendedprice),"
            " regr_r2(l_quantity, l_extendedprice) FROM lineitem"
        ).rows
        n, avgx, avgy, sxx, syy, sxy, r2 = rows[0]
        df = tpch_df("lineitem", 0.0005)
        x, y = df.l_extendedprice.to_numpy(), df.l_quantity.to_numpy()
        assert n == len(df)
        assert abs(avgx - x.mean()) < 1e-6 * abs(x.mean())
        assert abs(avgy - y.mean()) < 1e-9 * max(1, abs(y.mean()))
        wsxx = ((x - x.mean()) ** 2).sum()
        wsyy = ((y - y.mean()) ** 2).sum()
        wsxy = ((x - x.mean()) * (y - y.mean())).sum()
        assert abs(sxx - wsxx) < 1e-6 * wsxx
        assert abs(syy - wsyy) < 1e-6 * wsyy
        assert abs(sxy - wsxy) < 1e-6 * abs(wsxy)
        assert abs(r2 - (wsxy * wsxy) / (wsxx * wsyy)) < 1e-9

    def test_r2_constant_y_is_one(self, runner):
        rows = runner.execute(
            "SELECT regr_r2(y, x) FROM (VALUES (1.0, 1.0), (1.0, 2.0), (1.0, 3.0)) t(y, x)"
        ).rows
        assert rows[0][0] == 1.0

    def test_r2_constant_x_is_null(self, runner):
        rows = runner.execute(
            "SELECT regr_r2(y, x) FROM (VALUES (1.0, 2.0), (2.0, 2.0)) t(y, x)"
        ).rows
        assert rows[0][0] is None


class TestEntropy:
    def test_matches_formula(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        ((e,),) = runner.execute("SELECT entropy(l_linenumber) FROM lineitem").rows
        c = tpch_df("lineitem", 0.0005).l_linenumber.to_numpy().astype(float)
        s = c.sum()
        want = np.log2(s) - (c * np.log2(c)).sum() / s
        assert abs(e - want) < 1e-9

    def test_empty_is_null(self, runner):
        rows = runner.execute(
            "SELECT entropy(l_linenumber) FROM lineitem WHERE l_orderkey < 0"
        ).rows
        assert rows[0][0] is None


class TestBitwiseAggregates:
    def test_global_vs_numpy(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        rows = runner.execute(
            "SELECT bitwise_and_agg(l_orderkey), bitwise_or_agg(l_orderkey),"
            " bitwise_xor_agg(l_orderkey) FROM lineitem"
        ).rows
        o = tpch_df("lineitem", 0.0005).l_orderkey.to_numpy().astype(int)
        assert rows[0] == (
            int(np.bitwise_and.reduce(o)),
            int(np.bitwise_or.reduce(o)),
            int(np.bitwise_xor.reduce(o)),
        )

    def test_grouped_vs_numpy(self, runner):
        import numpy as np
        from tests.oracle import tpch_df

        rows = runner.execute(
            "SELECT l_returnflag, bitwise_xor_agg(l_orderkey), bitwise_and_agg(l_linenumber)"
            " FROM lineitem GROUP BY 1 ORDER BY 1"
        ).rows
        df = tpch_df("lineitem", 0.0005)
        for flag, x, a in rows:
            g = df[df.l_returnflag == flag]
            assert x == int(np.bitwise_xor.reduce(g.l_orderkey.to_numpy().astype(int)))
            assert a == int(np.bitwise_and.reduce(g.l_linenumber.to_numpy().astype(int)))

    def test_nulls_ignored_and_empty_null(self, runner):
        rows = runner.execute(
            "SELECT bitwise_or_agg(x) FROM (VALUES 1, NULL, 4) t(x)"
        ).rows
        assert rows == [(5,)]
        rows = runner.execute(
            "SELECT bitwise_or_agg(x) FROM (VALUES CAST(NULL AS bigint)) t(x)"
        ).rows
        assert rows == [(None,)]
