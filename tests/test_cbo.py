"""Cost-based optimization: stats estimation + join reordering plan tests.

ref: cost/StatsCalculator.java, FilterStatsCalculator, JoinStatsRule,
rule/ReorderJoins.java — Q5/Q8/Q9-class comma joins must come out of the
optimizer as connected, selectivity-ordered join trees without hand-written
plan shapes (the PlanTester-style assertions of SURVEY.md §4).
"""

import pytest

from trino_tpu.planner.plan import JoinKind, JoinNode, PlanNode, TableScanNode, visit_plan


SCALE = 0.002


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def optimized_plan(runner, sql):
    return runner.plan_sql(sql)


def join_tree_info(plan):
    crosses, joins, leaves = [], [], []

    def walk(n: PlanNode):
        if isinstance(n, JoinNode):
            joins.append(n)
            if n.kind == JoinKind.CROSS or not n.criteria:
                crosses.append(n)
        if isinstance(n, TableScanNode):
            leaves.append(n.table.schema_table.table)

    visit_plan(plan.root, walk)
    return crosses, joins, leaves


Q5 = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01' AND o_orderdate < DATE '1995-01-01'
GROUP BY n_name ORDER BY revenue DESC
"""

Q9 = """
SELECT nation, o_year, sum(amount) AS sum_profit
FROM (SELECT n_name AS nation, extract(YEAR FROM o_orderdate) AS o_year,
             l_extendedprice * (1 - l_discount)
               - ps_supplycost * l_quantity AS amount
      FROM part, supplier, lineitem, partsupp, orders, nation
      WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
        AND ps_partkey = l_partkey AND p_partkey = l_partkey
        AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
        AND p_name LIKE '%green%') AS profit
GROUP BY nation, o_year ORDER BY nation, o_year DESC
"""

Q8 = """
SELECT o_year, sum(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
       / sum(volume) AS mkt_share
FROM (SELECT extract(YEAR FROM o_orderdate) AS o_year,
             l_extendedprice * (1 - l_discount) AS volume,
             n2.n_name AS nation
      FROM part, supplier, lineitem, orders, customer,
           nation n1, nation n2, region
      WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey
        AND l_orderkey = o_orderkey AND o_custkey = c_custkey
        AND c_nationkey = n1.n_nationkey
        AND n1.n_regionkey = r_regionkey AND r_name = 'AMERICA'
        AND s_nationkey = n2.n_nationkey
        AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
        AND p_type = 'ECONOMY ANODIZED STEEL') AS all_nations
GROUP BY o_year ORDER BY o_year
"""


class TestJoinReordering:
    @pytest.mark.parametrize("sql,n_tables", [(Q5, 6), (Q9, 6), (Q8, 8)])
    def test_no_cross_products(self, runner, sql, n_tables):
        plan = optimized_plan(runner, sql)
        crosses, joins, leaves = join_tree_info(plan)
        assert len(leaves) == n_tables
        assert not crosses, "comma joins must lower to equi joins, no cross products"
        assert len(joins) == n_tables - 1

    def test_q5_starts_from_most_selective(self, runner):
        # the greedy order starts with the smallest filtered relation —
        # region (5 rows, r_name = 'ASIA') — never the fact table
        plan = optimized_plan(runner, Q5)
        _, joins, _ = join_tree_info(plan)
        deepest = joins[-1]

        def leaf_tables(n):
            out = []
            visit_plan(n, lambda x: out.append(x.table.schema_table.table)
                       if isinstance(x, TableScanNode) else None)
            return out

        first_two = leaf_tables(deepest.left) + leaf_tables(deepest.right)
        assert "lineitem" not in first_two[:2]
        assert set(first_two[:2]) & {"region", "nation", "supplier", "customer"}


class TestStatsEstimator:
    def test_scan_and_filter_selectivity(self, runner):
        from trino_tpu.planner.stats import StatsEstimator

        plan = runner.plan_sql(
            "SELECT * FROM lineitem WHERE l_quantity < 10"
        )
        est = StatsEstimator(runner.metadata, plan.types)
        scans = []
        visit_plan(plan.root, lambda n: scans.append(n)
                   if isinstance(n, TableScanNode) else None)
        total = est.rows(scans[0])
        assert total and total > 1000
        # l_quantity uniform in [1, 50] -> < 10 keeps < 25%
        filtered = est.rows(plan.root)
        assert filtered is not None and filtered < total * 0.35

    def test_join_ndv_formula(self, runner):
        from trino_tpu.planner.stats import StatsEstimator

        plan = runner.plan_sql(
            "SELECT count(*) FROM lineitem, orders WHERE l_orderkey = o_orderkey"
        )
        est = StatsEstimator(runner.metadata, plan.types)
        joins = []
        visit_plan(plan.root, lambda n: joins.append(n)
                   if isinstance(n, JoinNode) else None)
        assert joins
        rows = est.rows(joins[0])
        li = est.rows(joins[0].left)
        # FK join: |L ⋈ O| ≈ |lineitem|
        other = est.rows(joins[0].right)
        bigger = max(li or 0, other or 0)
        assert rows is not None and 0.5 * bigger <= rows <= 2.0 * bigger

    def test_groupby_ndv_cap(self, runner):
        from trino_tpu.planner.stats import StatsEstimator
        from trino_tpu.planner.plan import AggregationNode

        plan = runner.plan_sql(
            "SELECT l_linenumber, count(*) FROM lineitem GROUP BY l_linenumber"
        )
        est = StatsEstimator(runner.metadata, plan.types)
        aggs = []
        visit_plan(plan.root, lambda n: aggs.append(n)
                   if isinstance(n, AggregationNode) else None)
        rows = est.rows(aggs[0])
        assert rows is not None and rows <= 7
