"""Views + information_schema tests.

Coverage model: the reference's TestViews / AbstractTestViews and
TestInformationSchemaConnector (connector/informationschema/) — view
round-trip through DDL, expansion inside queries, cycle detection, and
metadata discovery through plain SQL.
"""

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


class TestViews:
    def test_create_select_drop(self, runner):
        runner.execute(
            "CREATE VIEW v1 AS SELECT n_name, n_regionkey FROM nation WHERE n_nationkey < 3"
        )
        rows = runner.execute("SELECT * FROM v1 ORDER BY n_name").rows
        assert [r[0] for r in rows] == ["ALGERIA", "ARGENTINA", "BRAZIL"]
        runner.execute("DROP VIEW v1")
        with pytest.raises(Exception, match="not found"):
            runner.execute("SELECT * FROM v1")

    def test_view_in_join_and_aggregation(self, runner):
        runner.execute(
            "CREATE VIEW big_regions AS SELECT r_regionkey, r_name FROM region"
        )
        rows = runner.execute(
            "SELECT br.r_name, count(*) FROM nation n "
            "JOIN big_regions br ON n.n_regionkey = br.r_regionkey "
            "GROUP BY br.r_name ORDER BY br.r_name"
        ).rows
        assert len(rows) == 5
        assert all(r[1] == 5 for r in rows)

    def test_or_replace(self, runner):
        runner.execute("CREATE VIEW v2 AS SELECT 1 AS x")
        with pytest.raises(Exception, match="already exists"):
            runner.execute("CREATE VIEW v2 AS SELECT 2 AS x")
        runner.execute("CREATE OR REPLACE VIEW v2 AS SELECT 2 AS x")
        assert runner.execute("SELECT x FROM v2").rows == [(2,)]

    def test_drop_if_exists(self, runner):
        runner.execute("DROP VIEW IF EXISTS nope")
        with pytest.raises(Exception, match="not found"):
            runner.execute("DROP VIEW nope")

    def test_view_on_view(self, runner):
        runner.execute("CREATE VIEW base_v AS SELECT n_nationkey k FROM nation")
        runner.execute("CREATE VIEW over_v AS SELECT max(k) mk FROM base_v")
        assert runner.execute("SELECT mk FROM over_v").rows == [(24,)]

    def test_view_cycle_detected(self, runner):
        runner.execute("CREATE VIEW a_v AS SELECT 1 AS x")
        # redefine a_v to reference b_v which references a_v
        runner.execute("CREATE VIEW b_v AS SELECT x FROM a_v")
        runner.execute("CREATE OR REPLACE VIEW a_v AS SELECT x FROM b_v")
        with pytest.raises(Exception, match="cycle"):
            runner.execute("SELECT * FROM a_v")

    def test_invalid_view_body_fails_at_create(self, runner):
        with pytest.raises(Exception):
            runner.execute("CREATE VIEW bad_v AS SELECT no_such_col FROM nation")

    def test_show_create_view(self, runner):
        runner.execute("CREATE VIEW sc_v AS SELECT 42 AS answer")
        text = runner.execute("SHOW CREATE VIEW sc_v").rows[0][0]
        assert "CREATE VIEW" in text and "SELECT 42 AS answer" in text

    def test_view_uses_defining_schema(self, runner):
        # view defined while session schema is sf0_01; body uses unqualified
        # 'nation' — must still resolve after the session moves elsewhere
        runner.execute("CREATE VIEW vfix AS SELECT count(*) c FROM nation")
        runner.session.schema = "tiny"
        try:
            assert runner.execute("SELECT c FROM tpch.sf0_01.vfix").rows == [(25,)]
        finally:
            runner.session.schema = "sf0_01"


class TestInformationSchema:
    def test_tables_listing(self, runner):
        rows = runner.execute(
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_schema = 'sf0_01' ORDER BY table_name"
        ).rows
        assert [r[0] for r in rows] == [
            "customer", "lineitem", "nation", "orders",
            "part", "partsupp", "region", "supplier",
        ]

    def test_views_appear_in_tables(self, runner):
        runner.execute("CREATE VIEW iv AS SELECT 1 AS one")
        rows = runner.execute(
            "SELECT table_name, table_type FROM information_schema.tables "
            "WHERE table_type = 'VIEW'"
        ).rows
        assert ("iv", "VIEW") in [tuple(r) for r in rows]

    def test_columns(self, runner):
        rows = runner.execute(
            "SELECT column_name, ordinal_position, data_type "
            "FROM information_schema.columns "
            "WHERE table_schema = 'sf0_01' AND table_name = 'region' "
            "ORDER BY ordinal_position"
        ).rows
        assert rows == [
            ("r_regionkey", 1, "bigint"),
            ("r_name", 2, "varchar(25)"),
            ("r_comment", 3, "varchar(152)"),
        ]

    def test_schemata(self, runner):
        rows = runner.execute(
            "SELECT schema_name FROM information_schema.schemata"
        ).rows
        names = [r[0] for r in rows]
        assert "information_schema" in names and "sf0_01" in names

    def test_view_definition_exposed(self, runner):
        runner.execute("CREATE VIEW defv AS SELECT 7 AS seven")
        rows = runner.execute(
            "SELECT view_definition FROM information_schema.views "
            "WHERE table_name = 'defv'"
        ).rows
        assert rows == [("SELECT 7 AS seven",)]

    def test_info_schema_joins_with_data(self, runner):
        # metadata flows through the same engine: join it against itself
        rows = runner.execute(
            "SELECT count(*) FROM information_schema.tables t "
            "JOIN information_schema.columns c ON t.table_name = c.table_name "
            "AND t.table_schema = c.table_schema "
            "WHERE t.table_schema = 'sf0_01' AND t.table_name = 'nation'"
        ).rows
        assert rows == [(4,)]


class TestStatementSurface:
    """USE / SHOW FUNCTIONS / EXPLAIN (TYPE DISTRIBUTED) (ref: sql/tree/Use,
    ShowFunctions; planprinter distributed output)."""

    def test_use_statement(self, runner):
        from trino_tpu.connectors.memory import MemoryConnector

        runner.register_catalog("memory", MemoryConnector())
        old_catalog, old_schema = runner.session.catalog, runner.session.schema
        try:
            runner.execute("USE memory.default")
            assert runner.session.catalog == "memory"
            runner.execute("CREATE TABLE u1 AS SELECT 7 AS x")
            assert runner.execute("SELECT x FROM u1").rows == [(7,)]
            with pytest.raises(Exception, match="catalog not found"):
                runner.execute("USE nope.default")
        finally:
            runner.session.catalog, runner.session.schema = old_catalog, old_schema

    def test_show_functions(self, runner):
        rows = runner.execute("SHOW FUNCTIONS").rows
        names = {r[0] for r in rows}
        assert {"sum", "approx_distinct", "substr", "week"} <= names
        runner.execute("CREATE FUNCTION sf_probe() RETURNS bigint RETURN 1")
        rows = runner.execute("SHOW FUNCTIONS").rows
        assert ("sf_probe", "sql routine") in rows
        runner.execute("DROP FUNCTION sf_probe")

    def test_explain_distributed(self, runner):
        lines = [r[0] for r in runner.execute(
            "EXPLAIN (TYPE DISTRIBUTED) SELECT l_returnflag, count(*) "
            "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag"
        ).rows]
        text = "\n".join(lines)
        assert "Fragment 0 [SOURCE]" in text
        assert "FIXED_HASH" in text
        assert "PARTIAL" in text and "FINAL" in text
