"""End-to-end SQL correctness vs the pandas oracle.

Coverage model: Trino's AbstractTestQueries / AbstractTestEngineOnlyQueries
(testing/trino-testing, SURVEY.md §4) — engine semantics exercised over the
deterministic tpch fixture and checked against an independent implementation.
"""

import datetime

import numpy as np
import pandas as pd
import pytest

from tests.oracle import tpch_df, assert_rows_equal

SCALE = 0.0005
EPOCH = datetime.date(1970, 1, 1)


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=SCALE)


def days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso) - EPOCH).days


class TestScanFilterProject:
    def test_count_star(self, runner):
        res = runner.execute("SELECT count(*) FROM lineitem")
        assert res.rows == [(len(tpch_df("lineitem", SCALE)),)]

    def test_filter_arithmetic(self, runner):
        res = runner.execute(
            "SELECT count(*), sum(l_extendedprice * l_discount) FROM lineitem "
            "WHERE l_quantity < 10 AND l_discount > 0.05"
        )
        li = tpch_df("lineitem", SCALE)
        m = li[(li.l_quantity < 10) & (li.l_discount > 0.05)]
        assert_rows_equal(
            res.rows, [(len(m), round((m.l_extendedprice * m.l_discount).sum(), 4))],
            float_tol=1e-9,
        )

    def test_date_filter(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_shipdate >= DATE '1995-01-01' "
            "AND l_shipdate < DATE '1996-01-01'"
        )
        li = tpch_df("lineitem", SCALE)
        m = li[(li.l_shipdate >= days("1995-01-01")) & (li.l_shipdate < days("1996-01-01"))]
        assert res.rows == [(len(m),)]

    def test_string_predicates(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute("SELECT count(*) FROM lineitem WHERE l_shipmode = 'AIR'")
        assert res.rows == [(int((li.l_shipmode == "AIR").sum()),)]
        res = runner.execute("SELECT count(*) FROM lineitem WHERE l_shipmode > 'RAIL'")
        assert res.rows == [(int((li.l_shipmode > "RAIL").sum()),)]
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_shipmode IN ('AIR', 'SHIP')"
        )
        assert res.rows == [(int(li.l_shipmode.isin(["AIR", "SHIP"]).sum()),)]

    def test_like(self, runner):
        c = tpch_df("customer", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM customer WHERE c_comment LIKE '%express%'"
        )
        assert res.rows == [(int(c.c_comment.str.contains("express").sum()),)]
        res = runner.execute(
            "SELECT count(*) FROM customer WHERE c_comment NOT LIKE '%express%'"
        )
        assert res.rows == [(int((~c.c_comment.str.contains("express")).sum()),)]

    def test_between(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_discount BETWEEN 0.02 AND 0.04"
        )
        assert res.rows == [(int(li.l_discount.between(0.02, 0.04).sum()),)]

    def test_case(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT sum(CASE WHEN l_quantity > 25 THEN 1 ELSE 0 END) FROM lineitem"
        )
        assert res.rows == [(int((li.l_quantity > 25).sum()),)]

    def test_projection_select(self, runner):
        res = runner.execute(
            "SELECT l_orderkey, l_quantity * 2 q2 FROM lineitem "
            "WHERE l_orderkey <= 3 ORDER BY l_orderkey, l_linenumber"
        )
        li = tpch_df("lineitem", SCALE)
        m = li[li.l_orderkey <= 3].sort_values(["l_orderkey", "l_linenumber"])
        assert_rows_equal(
            res.rows, [(int(r.l_orderkey), r.l_quantity * 2) for r in m.itertuples()]
        )

    def test_extract_year(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE EXTRACT(YEAR FROM l_shipdate) = 1995"
        )
        years = pd.to_datetime(
            li.l_shipdate, unit="D", origin="unix"
        ).dt.year
        assert res.rows == [(int((years == 1995).sum()),)]


class TestAggregation:
    def test_global_aggregates(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT count(*), sum(l_quantity), avg(l_extendedprice), "
            "min(l_shipdate), max(l_shipdate), count(l_orderkey) FROM lineitem"
        )
        assert_rows_equal(
            res.rows,
            [
                (
                    len(li),
                    li.l_quantity.sum(),
                    round(li.l_extendedprice.mean(), 2),  # decimal avg keeps scale
                    int(li.l_shipdate.min()),
                    int(li.l_shipdate.max()),
                    len(li),
                )
            ],
            float_tol=1e-2,
        )

    def test_group_by(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT l_returnflag, l_linestatus, count(*) c, sum(l_quantity) s "
            "FROM lineitem GROUP BY 1, 2 ORDER BY 1, 2"
        )
        exp = (
            li.groupby(["l_returnflag", "l_linestatus"])
            .agg(c=("l_orderkey", "count"), s=("l_quantity", "sum"))
            .reset_index()
            .sort_values(["l_returnflag", "l_linestatus"])
        )
        assert_rows_equal(res.rows, [tuple(r) for r in exp.itertuples(index=False)])

    def test_having(self, runner):
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT o_custkey, count(*) c FROM orders GROUP BY o_custkey "
            "HAVING count(*) >= 4 ORDER BY c DESC, o_custkey LIMIT 5"
        )
        exp = (
            o.groupby("o_custkey").size().reset_index(name="c").query("c >= 4")
            .sort_values(["c", "o_custkey"], ascending=[False, True]).head(5)
        )
        assert_rows_equal(res.rows, [tuple(r) for r in exp.itertuples(index=False)])

    def test_distinct(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute("SELECT count(*) FROM (SELECT DISTINCT l_suppkey FROM lineitem) t")
        assert res.rows == [(li.l_suppkey.nunique(),)]

    def test_count_distinct(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute("SELECT count(DISTINCT l_partkey) FROM lineitem")
        assert res.rows == [(li.l_partkey.nunique(),)]

    def test_grouped_count_distinct(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT l_returnflag, count(DISTINCT l_shipmode) FROM lineitem GROUP BY 1 ORDER BY 1"
        )
        exp = li.groupby("l_returnflag")["l_shipmode"].nunique().reset_index()
        assert_rows_equal(res.rows, [tuple(r) for r in exp.itertuples(index=False)])

    def test_agg_filter_clause(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT count(*) FILTER (WHERE l_quantity > 40) FROM lineitem"
        )
        assert res.rows == [(int((li.l_quantity > 40).sum()),)]

    def test_stddev_variance(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute("SELECT stddev(l_quantity), variance(l_quantity) FROM lineitem")
        assert_rows_equal(
            res.rows, [(li.l_quantity.std(ddof=1), li.l_quantity.var(ddof=1))], float_tol=1e-9
        )

    def test_empty_group_result(self, runner):
        res = runner.execute(
            "SELECT l_returnflag, count(*) FROM lineitem WHERE l_quantity > 10000 GROUP BY 1"
        )
        assert res.rows == []

    def test_global_agg_over_empty(self, runner):
        res = runner.execute(
            "SELECT count(*), sum(l_quantity) FROM lineitem WHERE l_quantity > 10000"
        )
        assert res.rows == [(0, None)]


class TestJoins:
    def test_inner_join(self, runner):
        li = tpch_df("lineitem", SCALE)
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT count(*), sum(o_totalprice) FROM lineitem "
            "JOIN orders ON l_orderkey = o_orderkey WHERE l_quantity < 5"
        )
        m = li[li.l_quantity < 5].merge(o, left_on="l_orderkey", right_on="o_orderkey")
        assert_rows_equal(
            res.rows, [(len(m), round(m.o_totalprice.sum(), 2))], float_tol=1e-9
        )

    def test_three_way_join(self, runner):
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        n = tpch_df("nation", SCALE)
        res = runner.execute(
            "SELECT n_name, count(*) c FROM customer "
            "JOIN orders ON c_custkey = o_custkey "
            "JOIN nation ON c_nationkey = n_nationkey "
            "GROUP BY n_name ORDER BY n_name"
        )
        m = c.merge(o, left_on="c_custkey", right_on="o_custkey").merge(
            n, left_on="c_nationkey", right_on="n_nationkey"
        )
        exp = m.groupby("n_name").size().reset_index(name="c").sort_values("n_name")
        assert_rows_equal(res.rows, [tuple(r) for r in exp.itertuples(index=False)])

    def test_left_join_counts(self, runner):
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT count(*), count(o_orderkey) FROM customer "
            "LEFT JOIN orders ON c_custkey = o_custkey"
        )
        m = c.merge(o, left_on="c_custkey", right_on="o_custkey", how="left")
        assert res.rows == [(len(m), int(m.o_orderkey.notna().sum()))]

    def test_right_join(self, runner):
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT count(*), count(c_custkey) FROM orders "
            "RIGHT JOIN customer ON o_custkey = c_custkey"
        )
        m = o.merge(c, left_on="o_custkey", right_on="c_custkey", how="right")
        assert res.rows == [(len(m), len(m))]

    def test_cross_join(self, runner):
        res = runner.execute("SELECT count(*) FROM nation, region")
        assert res.rows == [(25 * 5,)]

    def test_join_with_duplicates_on_build(self, runner):
        # orders per customer > 1: build side (orders) has duplicate keys
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM customer JOIN orders ON c_custkey = o_custkey"
        )
        m = c.merge(o, left_on="c_custkey", right_on="o_custkey")
        assert res.rows == [(len(m),)]

    def test_non_equi_residual(self, runner):
        li = tpch_df("lineitem", SCALE)
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM lineitem JOIN orders "
            "ON l_orderkey = o_orderkey AND l_shipdate > o_orderdate"
        )
        m = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        assert res.rows == [(int((m.l_shipdate > m.o_orderdate).sum()),)]

    def test_semi_join(self, runner):
        li = tpch_df("lineitem", SCALE)
        o = tpch_df("orders", SCALE)
        big = o[o.o_totalprice > 300000].o_orderkey
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_orderkey IN "
            "(SELECT o_orderkey FROM orders WHERE o_totalprice > 300000)"
        )
        assert res.rows == [(int(li.l_orderkey.isin(big).sum()),)]

    def test_anti_join(self, runner):
        li = tpch_df("lineitem", SCALE)
        o = tpch_df("orders", SCALE)
        big = o[o.o_totalprice > 300000].o_orderkey
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_orderkey NOT IN "
            "(SELECT o_orderkey FROM orders WHERE o_totalprice > 300000)"
        )
        assert res.rows == [(int((~li.l_orderkey.isin(big)).sum()),)]

    def test_scalar_subquery(self, runner):
        li = tpch_df("lineitem", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM lineitem WHERE l_quantity > (SELECT avg(l_quantity) FROM lineitem)"
        )
        assert res.rows == [(int((li.l_quantity > li.l_quantity.mean()).sum()),)]

    def test_string_key_join(self, runner):
        n = tpch_df("nation", SCALE)
        res = runner.execute(
            "SELECT count(*) FROM nation a JOIN nation b ON a.n_name = b.n_name"
        )
        assert res.rows == [(25,)]


class TestSortLimit:
    def test_order_by_multiple(self, runner):
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT o_orderkey, o_totalprice FROM orders "
            "ORDER BY o_totalprice DESC, o_orderkey LIMIT 10"
        )
        exp = o.sort_values(["o_totalprice", "o_orderkey"], ascending=[False, True]).head(10)
        assert_rows_equal(
            res.rows, [(int(r.o_orderkey), r.o_totalprice) for r in exp.itertuples()]
        )

    def test_limit_offset(self, runner):
        res = runner.execute("SELECT n_nationkey FROM nation ORDER BY n_nationkey LIMIT 5 OFFSET 10")
        assert [r[0] for r in res.rows] == [10, 11, 12, 13, 14]

    def test_order_by_string(self, runner):
        n = tpch_df("nation", SCALE)
        res = runner.execute("SELECT n_name FROM nation ORDER BY n_name DESC LIMIT 3")
        exp = sorted(n.n_name, reverse=True)[:3]
        assert [r[0] for r in res.rows] == exp

    def test_nulls_ordering(self, runner):
        res = runner.execute(
            "SELECT x FROM (VALUES (1), (NULL), (3), (2)) AS t(x) ORDER BY x DESC NULLS LAST"
        )
        assert [r[0] for r in res.rows] == [3, 2, 1, None]


class TestSetOps:
    def test_union_all(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM (SELECT n_nationkey FROM nation UNION ALL SELECT r_regionkey FROM region) t"
        )
        assert res.rows == [(30,)]

    def test_union_distinct(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM (SELECT n_regionkey FROM nation UNION SELECT r_regionkey FROM region) t"
        )
        assert res.rows == [(5,)]

    def test_values(self, runner):
        res = runner.execute("SELECT a, b FROM (VALUES (1, 'x'), (2, 'y')) AS t(a, b) ORDER BY a")
        assert res.rows == [(1, "x"), (2, "y")]

    def test_with_cte(self, runner):
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "WITH big AS (SELECT * FROM orders WHERE o_totalprice > 400000) "
            "SELECT count(*) FROM big"
        )
        assert res.rows == [(int((o.o_totalprice > 400000).sum()),)]


class TestWindow:
    def test_row_number(self, runner):
        res = runner.execute(
            "SELECT n_name, row_number() OVER (PARTITION BY n_regionkey ORDER BY n_name) rn "
            "FROM nation ORDER BY n_name LIMIT 5"
        )
        n = tpch_df("nation", SCALE)
        n = n.sort_values("n_name")
        n["rn"] = n.groupby("n_regionkey").cumcount() + 1
        exp = n.sort_values("n_name").head(5)
        assert_rows_equal(res.rows, [(r.n_name, r.rn) for r in exp.itertuples()])

    def test_rank_dense_rank(self, runner):
        res = runner.execute(
            "SELECT x, rank() OVER (ORDER BY x) r, dense_rank() OVER (ORDER BY x) dr "
            "FROM (VALUES (10), (10), (20), (30), (30), (30)) AS t(x) ORDER BY x, r"
        )
        assert res.rows == [
            (10, 1, 1), (10, 1, 1), (20, 3, 2), (30, 4, 3), (30, 4, 3), (30, 4, 3)
        ]

    def test_sum_over_partition(self, runner):
        o = tpch_df("orders", SCALE)
        res = runner.execute(
            "SELECT o_orderkey, sum(o_totalprice) OVER (PARTITION BY o_custkey) s "
            "FROM orders ORDER BY o_orderkey LIMIT 5"
        )
        o = o.copy()
        o["s"] = o.groupby("o_custkey")["o_totalprice"].transform("sum")
        exp = o.sort_values("o_orderkey").head(5)
        assert_rows_equal(
            res.rows, [(int(r.o_orderkey), round(r.s, 2)) for r in exp.itertuples()],
            float_tol=1e-9,
        )


class TestNullSemantics:
    def test_null_comparison(self, runner):
        res = runner.execute("SELECT count(*) FROM (VALUES (1), (NULL)) t(x) WHERE x > 0")
        assert res.rows == [(1,)]

    def test_kleene_or(self, runner):
        # NULL OR TRUE = TRUE
        res = runner.execute(
            "SELECT count(*) FROM (VALUES (NULL)) t(x) WHERE x > 0 OR TRUE"
        )
        assert res.rows == [(1,)]

    def test_coalesce(self, runner):
        res = runner.execute("SELECT coalesce(NULL, 5)")
        assert res.rows == [(5,)]

    def test_is_null(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM (VALUES (1), (NULL), (3)) t(x) WHERE x IS NULL"
        )
        assert res.rows == [(1,)]

    def test_null_in_aggregation_keys(self, runner):
        res = runner.execute(
            "SELECT x, count(*) FROM (VALUES (1), (NULL), (NULL), (1)) t(x) GROUP BY x ORDER BY x"
        )
        assert res.rows == [(1, 2), (None, 2)]


class TestSetOpsExtended:
    def test_intersect(self, runner):
        res = runner.execute(
            "SELECT n_regionkey FROM nation INTERSECT SELECT r_regionkey FROM region"
        )
        assert sorted(r[0] for r in res.rows) == [0, 1, 2, 3, 4]

    def test_except(self, runner):
        res = runner.execute(
            "SELECT r_regionkey FROM region EXCEPT "
            "SELECT n_regionkey FROM nation WHERE n_regionkey < 3"
        )
        assert sorted(r[0] for r in res.rows) == [3, 4]

    def test_intersect_multi_column(self, runner):
        res = runner.execute(
            "SELECT * FROM (VALUES (1, 'a'), (2, 'b'), (3, 'c')) x(i, s) "
            "INTERSECT SELECT * FROM (VALUES (2, 'b'), (3, 'z')) y(i, s)"
        )
        assert res.rows == [(2, "b")]


class TestDatetimeFunctions:
    def test_date_trunc(self, runner):
        res = runner.execute(
            "SELECT date_trunc('month', DATE '1995-07-17'), "
            "date_trunc('year', DATE '1995-07-17'), "
            "date_trunc('quarter', DATE '1995-08-17'), "
            "date_trunc('week', DATE '2026-07-29')"
        )
        row = res.rows[0]
        assert str(row[0]) == "1995-07-01"
        assert str(row[1]) == "1995-01-01"
        assert str(row[2]) == "1995-07-01"
        assert str(row[3]) == "2026-07-27"  # Monday

    def test_date_add(self, runner):
        res = runner.execute(
            "SELECT date_add('month', 1, DATE '1995-01-31'), "
            "date_add('day', 10, DATE '1995-12-28'), "
            "date_add('year', -1, DATE '1996-02-29')"
        )
        row = res.rows[0]
        assert str(row[0]) == "1995-02-28"  # clamped
        assert str(row[1]) == "1996-01-07"
        assert str(row[2]) == "1995-02-28"  # leap day clamped

    def test_date_diff(self, runner):
        res = runner.execute(
            "SELECT date_diff('day', DATE '1995-01-01', DATE '1995-03-01'), "
            "date_diff('month', DATE '1995-01-15', DATE '1996-03-01'), "
            "date_diff('year', DATE '1990-06-01', DATE '1995-02-01')"
        )
        assert res.rows[0] == (59, 14, 4)

    def test_date_trunc_on_column(self, runner):
        res = runner.execute(
            "SELECT count(DISTINCT date_trunc('year', o_orderdate)) FROM orders"
        )
        assert res.rows[0][0] == 7  # 1992..1998


class TestGroupingSets:
    def test_rollup(self, runner):
        res = runner.execute(
            "SELECT l_returnflag, l_linestatus, count(*) c FROM lineitem "
            "GROUP BY ROLLUP(l_returnflag, l_linestatus) ORDER BY 1, 2"
        )
        li = tpch_df("lineitem", SCALE)
        detail = li.groupby(["l_returnflag", "l_linestatus"]).size()
        subtotal = li.groupby("l_returnflag").size()
        assert (None, None, len(li)) in res.rows
        for (rf, ls), c in detail.items():
            assert (rf, ls, c) in res.rows
        for rf, c in subtotal.items():
            assert (rf, None, c) in res.rows
        assert len(res.rows) == len(detail) + len(subtotal) + 1

    def test_cube(self, runner):
        res = runner.execute(
            "SELECT l_returnflag, l_shipmode, count(*) FROM lineitem "
            "GROUP BY CUBE(l_returnflag, l_shipmode)"
        )
        li = tpch_df("lineitem", SCALE)
        n_detail = li.groupby(["l_returnflag", "l_shipmode"]).ngroups
        n_rf = li.l_returnflag.nunique()
        n_sm = li.l_shipmode.nunique()
        assert len(res.rows) == n_detail + n_rf + n_sm + 1
        assert (None, None, len(li)) in res.rows

    def test_grouping_sets(self, runner):
        res = runner.execute(
            "SELECT n_regionkey, count(*) FROM nation "
            "GROUP BY GROUPING SETS ((n_regionkey), ()) ORDER BY 1"
        )
        assert res.rows == [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5), (None, 25)]

    def test_rollup_with_aggregate_of_key(self, runner):
        # aggregate args must see base rows even when the key is nulled out
        res = runner.execute(
            "SELECT n_regionkey, max(n_regionkey) FROM nation "
            "GROUP BY ROLLUP(n_regionkey) ORDER BY 1"
        )
        assert (None, 4) in res.rows  # grand total still aggregates real values


class TestFullOuterJoin:
    def test_full_join_counts(self, runner):
        res = runner.execute(
            "SELECT count(*), count(c_custkey), count(o_orderkey) FROM customer "
            "FULL JOIN orders ON c_custkey = o_custkey"
        )
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        m = c.merge(o, left_on="c_custkey", right_on="o_custkey", how="outer")
        assert res.rows == [
            (len(m), int(m.c_custkey.notna().sum()), int(m.o_orderkey.notna().sum()))
        ]

    def test_full_join_values(self, runner):
        res = runner.execute(
            "SELECT a, b FROM (VALUES (1), (2), (3)) x(a) "
            "FULL JOIN (VALUES (2), (3), (4)) y(b) ON a = b ORDER BY a NULLS LAST, b"
        )
        assert res.rows == [(1, None), (2, 2), (3, 3), (None, 4)]


class TestLeftJoinResidual:
    def test_left_join_with_cross_side_residual(self, runner):
        res = runner.execute(
            "SELECT count(*), count(o_orderkey) FROM customer "
            "LEFT JOIN orders ON c_custkey = o_custkey AND o_totalprice > c_acctbal * 10"
        )
        c = tpch_df("customer", SCALE)
        o = tpch_df("orders", SCALE)
        m = c.merge(o, left_on="c_custkey", right_on="o_custkey", how="left")
        ok = m.o_totalprice > m.c_acctbal * 10
        kept = m[ok]
        lost = set(c.c_custkey) - set(kept.c_custkey)
        total = len(kept) + len(lost)
        assert res.rows == [(total, len(kept))]

    def test_left_join_residual_values(self, runner):
        res = runner.execute(
            "SELECT a, b FROM (VALUES (1), (2), (3)) x(a) "
            "LEFT JOIN (VALUES (1), (2), (20)) y(b) ON a = b AND b < 2 "
            "ORDER BY a, b"
        )
        # only a=1 keeps its match; a=2 and a=3 re-emit null rows
        assert res.rows == [(1, 1), (2, None), (3, None)]


class TestRegexAndStringFunctions:
    """regex + padded/reversed string functions via dictionary LUT transforms
    (ref: operator/scalar regex family; Trino evaluates per row with joni,
    dictionaries collapse that to O(|vocab|) host work at compile time)."""

    def test_regexp_like(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM nation WHERE regexp_like(n_name, '^A')"
        )
        n = tpch_df("nation", SCALE)
        assert res.rows == [(int(n.n_name.str.match("A").sum()),)]

    def test_regexp_extract_groups_and_null(self, runner):
        res = runner.execute(
            "SELECT regexp_extract(n_name, '^(.)(.)', 2) FROM nation "
            "ORDER BY n_name LIMIT 2"
        )
        assert res.rows == [("L",), ("R",)]
        res2 = runner.execute(
            "SELECT count(regexp_extract(n_name, 'ZZZ')) FROM nation"
        )
        assert res2.rows == [(0,)]  # no match -> NULL -> count skips

    def test_regexp_replace(self, runner):
        res = runner.execute(
            "SELECT regexp_replace(n_name, '[AEIOU]', '_') FROM nation "
            "ORDER BY n_name LIMIT 1"
        )
        assert res.rows == [("_LG_R__",)]

    def test_reverse_lpad_rpad(self, runner):
        res = runner.execute(
            "SELECT reverse('abc'), lpad('7', 3, '0'), rpad('ab', 4, 'xy')"
        )
        assert res.rows == [("cba", "007", "abxy")]
