"""Device mesh management.

Reference blueprint: the role of io.trino.metadata.InternalNodeManager + the
worker set in NodePartitioningManager (SURVEY.md §2.6 "Node placement") — but on
TPU the "worker set" inside one pod is a jax.sharding.Mesh and stage-to-stage
data movement is XLA collectives over ICI rather than HTTP (SURVEY.md §3.3 "TPU
mapping"). Cross-pod/DCN distribution keeps a Trino-style control plane (later
rounds); this module owns the intra-pod mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n: Optional[int] = None, axis_name: str = "workers") -> Mesh:
    """A 1-D mesh of query "workers" (each device = one Trino worker-task slot)."""
    devices = jax.devices()
    if n is not None:
        if n > len(devices):
            raise ValueError(f"requested {n} devices, have {len(devices)}")
        devices = devices[:n]
    return Mesh(np.asarray(devices), (axis_name,))
