"""ORC / CSV / JSON file connector tests.

Coverage model: lib/trino-orc's reader tests (stripe-granular reads,
type round-trips) and lib/trino-hive-formats line-codec tests, at the
connector-conformance level of BaseConnectorTest: scan, predicate, join,
aggregation over each format.
"""

import datetime
import os

import numpy as np
import pyarrow as pa
import pytest

from trino_tpu.connectors.files import FileFormatConnector
from trino_tpu.metadata import Session
from trino_tpu.runtime import LocalQueryRunner


def _orders_table():
    return pa.table(
        {
            "id": pa.array(range(1, 101), type=pa.int64()),
            "price": pa.array([float(i) * 1.5 for i in range(1, 101)]),
            "region": pa.array(["east", "west", "north"][i % 3] for i in range(100)),
            "day": pa.array(
                [datetime.date(2024, 1, 1) + datetime.timedelta(days=i % 30)
                 for i in range(100)]
            ),
        }
    )


def _items_table():
    return pa.table(
        {
            "id": pa.array(range(1, 51), type=pa.int64()),
            "name": pa.array([f"item{i:03d}" for i in range(1, 51)]),
        }
    )


@pytest.fixture(scope="module")
def orc_runner(tmp_path_factory):
    import pyarrow.orc as orc

    root = tmp_path_factory.mktemp("orc_data")
    os.makedirs(root / "orders")
    os.makedirs(root / "items")
    # two files, small stripes to exercise stripe-granular splits
    t = _orders_table()
    orc.write_table(t.slice(0, 60), str(root / "orders" / "a.orc"),
                    stripe_size=1024)
    orc.write_table(t.slice(60), str(root / "orders" / "b.orc"), stripe_size=1024)
    orc.write_table(_items_table(), str(root / "items" / "a.orc"))
    r = LocalQueryRunner(Session(catalog="orc", schema="default"))
    r.register_catalog("orc", FileFormatConnector(str(root), "orc"))
    return r


@pytest.fixture(scope="module")
def csv_runner(tmp_path_factory):
    import pyarrow.csv as pacsv

    root = tmp_path_factory.mktemp("csv_data")
    os.makedirs(root / "orders")
    t = _orders_table()
    pacsv.write_csv(t.slice(0, 50), str(root / "orders" / "a.csv"))
    pacsv.write_csv(t.slice(50), str(root / "orders" / "b.csv"))
    r = LocalQueryRunner(Session(catalog="csv", schema="default"))
    r.register_catalog("csv", FileFormatConnector(str(root), "csv"))
    return r


@pytest.fixture(scope="module")
def json_runner(tmp_path_factory):
    root = tmp_path_factory.mktemp("json_data")
    os.makedirs(root / "events")
    with open(root / "events" / "a.json", "w") as f:
        for i in range(20):
            f.write('{"user": "u%d", "n": %d, "score": %s}\n' % (i % 4, i, i * 0.5))
    r = LocalQueryRunner(Session(catalog="json", schema="default"))
    r.register_catalog("json", FileFormatConnector(str(root), "json"))
    return r


class TestOrc:
    def test_scan_and_count(self, orc_runner):
        assert orc_runner.execute("SELECT count(*) FROM orders").rows == [(100,)]

    def test_stripes_become_splits(self, orc_runner):
        conn = orc_runner.catalogs.get("orc")
        meta = conn.metadata()
        tables = [t.table for t in meta.list_tables()]
        assert tables == ["items", "orders"]

    def test_filter_and_strings(self, orc_runner):
        rows = orc_runner.execute(
            "SELECT region, count(*) FROM orders WHERE id <= 30 "
            "GROUP BY region ORDER BY region"
        ).rows
        assert sum(r[1] for r in rows) == 30
        assert [r[0] for r in rows] == ["east", "north", "west"]

    def test_dates_and_doubles(self, orc_runner):
        ((lo, hi, s),) = orc_runner.execute(
            "SELECT min(day), max(day), sum(price) FROM orders"
        ).rows
        assert lo == datetime.date(2024, 1, 1)
        assert hi == datetime.date(2024, 1, 30)
        assert abs(s - sum(float(i) * 1.5 for i in range(1, 101))) < 1e-6

    def test_join_across_tables(self, orc_runner):
        ((n,),) = orc_runner.execute(
            "SELECT count(*) FROM orders JOIN items ON orders.id = items.id"
        ).rows
        assert n == 50

    def test_order_by_and_limit(self, orc_runner):
        rows = orc_runner.execute(
            "SELECT id FROM orders ORDER BY price DESC LIMIT 3"
        ).rows
        assert [r[0] for r in rows] == [100, 99, 98]


class TestCsv:
    def test_scan_across_files(self, csv_runner):
        assert csv_runner.execute("SELECT count(*) FROM orders").rows == [(100,)]

    def test_aggregate_strings(self, csv_runner):
        rows = csv_runner.execute(
            "SELECT region, sum(price) FROM orders GROUP BY region ORDER BY region"
        ).rows
        assert len(rows) == 3


class TestJson:
    def test_scan_and_group(self, json_runner):
        rows = json_runner.execute(
            "SELECT user, count(*), sum(n) FROM events GROUP BY user ORDER BY user"
        ).rows
        assert len(rows) == 4
        assert sum(r[1] for r in rows) == 20

    def test_double_column(self, json_runner):
        ((s,),) = json_runner.execute("SELECT sum(score) FROM events").rows
        assert abs(s - sum(i * 0.5 for i in range(20))) < 1e-9


class TestHivePartitionedLayout:
    """Hive-style key=value directories: partition columns, pruning
    (ref: plugin/trino-hive HivePartitionManager + HivePageSource
    prefilled partition blocks)."""

    @pytest.fixture(scope="class")
    def part_runner(self, tmp_path_factory):
        import pyarrow.parquet as pq

        root = tmp_path_factory.mktemp("hive_data")
        t = _orders_table()
        for year, geo, lo, hi in [
            (2023, "emea", 0, 30), (2023, "amer", 30, 60),
            (2024, "emea", 60, 80), (2024, "amer", 80, 100),
        ]:
            d = root / "sales" / f"year={year}" / f"geo={geo}"
            os.makedirs(d)
            pq.write_table(t.slice(lo, hi - lo), str(d / "part.parquet"))
        r = LocalQueryRunner(Session(catalog="hive", schema="default"))
        r.register_catalog("hive", FileFormatConnector(str(root), "parquet"))
        return r

    def test_partition_columns_visible(self, part_runner):
        rows = part_runner.execute(
            "SELECT year, geo, count(*) FROM sales GROUP BY 1, 2 ORDER BY 1, 2"
        ).rows
        assert rows == [(2023, "amer", 30), (2023, "emea", 30),
                        (2024, "amer", 20), (2024, "emea", 20)]

    def test_partition_pruning(self, part_runner):
        conn = part_runner.catalogs.get("hive")
        meta = part_runner.metadata
        # count splits actually produced under a partition predicate
        from trino_tpu.spi.predicate import Domain, TupleDomain

        from trino_tpu.sql.tree import QualifiedName

        handle, _ = meta.resolve_table(
            part_runner.session, QualifiedName(parts=("hive", "default", "sales"))
        )
        constraint = TupleDomain.from_dict({"year": Domain.single(2024)})
        pruned = conn.metadata().apply_filter(handle, constraint)
        splits = conn.split_manager().get_splits(pruned)
        assert len(splits) == 2  # only year=2024 directories
        ((n,),) = part_runner.execute(
            "SELECT count(*) FROM sales WHERE year = 2024"
        ).rows
        assert n == 40

    def test_mixed_file_and_partition_predicates(self, part_runner):
        rows = part_runner.execute(
            "SELECT geo, sum(price) FROM sales "
            "WHERE year = 2023 AND id <= 45 GROUP BY geo ORDER BY geo"
        ).rows
        assert [r[0] for r in rows] == ["amer", "emea"]
        ((n,),) = part_runner.execute(
            "SELECT count(*) FROM sales WHERE geo = 'emea'"
        ).rows
        assert n == 50
