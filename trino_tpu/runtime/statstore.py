"""Estimate<->actual statistics feedback plane.

Reference blueprint: Presto's history-based optimization (HBO —
presto-main's HistoryBasedPlanStatisticsCalculator keyed on canonicalized
plan fragments) and Trino's anticipated `EXPLAIN ANALYZE` estimate/actual
rendering. The round-7 observability plane attributes *time*; this module
closes the loop on *cardinality*:

- **actuals collection**: executors stash each plan node's output ``active``
  mask (one dict store per operator per page — no device op, no host sync on
  the hot path); :func:`observe_query` folds them into the per-query
  ``QueryStatsCollector`` once the query has drained.
- **history store**: per-node estimate-vs-actual records persisted under the
  capstore structural plan fingerprint (``$TRINO_TPU_STATS_HISTORY`` file,
  atomic-rename merge-on-write; bounded in-process dict otherwise). Entries
  are content-addressed two ways so the next planning of a matching shape
  can find them:

  * ``s:<sha>`` — exact structural subtree fingerprint (plancodec encoding,
    the capstore contract), and
  * ``l:<sha>`` — a canonical *filtered-leaf* key (table + conjuncts over
    COLUMN names), robust against symbol renaming, column pruning, and
    constraint absorption — the key join reordering looks up mid-optimize,
    before the final plan shape exists.

- **mis-estimate detection**: every folded node computes a smoothed q-error
  ``max(est, act) / min(est, act)`` (floored at 1 row); nodes past the
  ``qerror_threshold`` session knob emit ``cardinality_misestimate`` flight
  events and Prometheus counters/histograms. Recent per-node rows land in a
  bounded process ring surfaced as ``system.runtime.operator_stats``; the
  history store itself is ``system.optimizer.stats_history``.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from .. import knobs

ENV_VAR = "TRINO_TPU_STATS_HISTORY"

# ------------------------------------------------------------ query identity

_qid_tls = threading.local()


def current_query_id() -> Optional[str]:
    return getattr(_qid_tls, "qid", None)


class query_id_scope:
    """Install a query id on this thread (the QueryManager wraps execution
    in one) so operator-stats rows join against system.runtime.queries;
    embedded runs without a manager fall back to the trace id."""

    def __init__(self, query_id: str):
        self.query_id = query_id

    def __enter__(self):
        self._prev = getattr(_qid_tls, "qid", None)
        _qid_tls.qid = self.query_id
        return self

    def __exit__(self, *exc):
        _qid_tls.qid = self._prev
        return False

# in-process fallback store, bounded (oldest fingerprints evicted) so a
# long-lived coordinator recording every query shape cannot grow unbounded
_MAX_MEMORY_ENTRIES = 4096
_lock = threading.Lock()
_memory_store: "Dict[str, dict]" = {}

# bounded ring of recent per-node actuals: system.runtime.operator_stats
_OP_STATS: deque = deque(maxlen=4096)
_OP_STATS_LOCK = threading.Lock()


# --------------------------------------------------------------------------- #
# q-error
# --------------------------------------------------------------------------- #


def q_error(estimate: Optional[float], actual: Optional[float]) -> Optional[float]:
    """Smoothed multiplicative estimation error: max(e, a) / min(e, a) with
    both sides floored at one row — always finite, 1.0 = perfect."""
    if estimate is None or actual is None:
        return None
    e = max(float(estimate), 1.0)
    a = max(float(actual), 1.0)
    return max(e / a, a / e)


# --------------------------------------------------------------------------- #
# canonical keys
# --------------------------------------------------------------------------- #


class _Uncanonical(Exception):
    """Expression/subtree outside the canonical grammar — no leaf key."""


def _canon_expr(expr, sym_to_col: Dict[str, str]) -> str:
    """Render an IR expression with symbols replaced by COLUMN names — the
    symbol-allocation-independent form two plannings of the same SQL agree
    on. Raises :class:`_Uncanonical` for shapes we can't translate."""
    from ..sql.ir import Call, CastExpr, Constant, InLut, Reference

    if isinstance(expr, Reference):
        col = sym_to_col.get(expr.symbol)
        if col is None:
            raise _Uncanonical(expr.symbol)
        return f"@{col}"
    if isinstance(expr, Constant):
        return repr(expr.value)
    if isinstance(expr, Call):
        args = ",".join(_canon_expr(a, sym_to_col) for a in expr.args)
        return f"{expr.name}({args})"
    if isinstance(expr, CastExpr):
        t = expr.type.display() if expr.type is not None else "?"
        return f"cast({_canon_expr(expr.value, sym_to_col)} as {t})"
    if isinstance(expr, InLut):
        # the LUT is dictionary-local; the description carries the predicate
        return f"inlut({_canon_expr(expr.value, sym_to_col)},{expr.description!r})"
    raise _Uncanonical(type(expr).__name__)


def _peel_to_scan(node):
    """Walk Filter/identity-Project chains down to a TableScan, collecting
    filter conjuncts along the way. Returns (scan, conjuncts) or None."""
    from ..planner.logical_planner import split_conjuncts
    from ..planner.plan import FilterNode, ProjectNode, TableScanNode

    conjuncts: List[object] = []
    cur = node
    while True:
        if isinstance(cur, TableScanNode):
            return cur, conjuncts
        if isinstance(cur, FilterNode):
            conjuncts.extend(split_conjuncts(cur.predicate))
            cur = cur.source
            continue
        if isinstance(cur, ProjectNode) and cur.is_identity():
            cur = cur.source
            continue
        return None


def leaf_key_for(leaf, extra_conjuncts: Sequence[object] = ()) -> Optional[str]:
    """Canonical key of a filtered scan: table + sorted conjuncts rendered
    over column names. ``extra_conjuncts`` lets join reordering ask about a
    (bare leaf + pending WHERE conjuncts) combination before the filter node
    exists. Ignores absorbed scan constraints and pruned column lists — both
    are derived from the same conjuncts, so the key stays stable across the
    optimizer passes that introduce them."""
    peeled = _peel_to_scan(leaf)
    if peeled is None:
        return None
    scan, conjuncts = peeled
    conjuncts = list(conjuncts) + list(extra_conjuncts)
    sym_to_col = {s: c for s, c in scan.assignments}
    try:
        parts = sorted(_canon_expr(c, sym_to_col) for c in conjuncts)
    except _Uncanonical:
        return None
    h = scan.table
    text = f"{h.catalog}.{h.schema_table}"
    if scan.limit is not None:
        text += f"|limit={scan.limit}"
    # an ABSORBED constraint changes what the scan emits even when no
    # conjunct survives above it (connectors prune splits / render WHERE),
    # so it must key separately from a bare scan of the table — otherwise a
    # constrained scan's reduced actual would overlay unfiltered scans.
    # Frozen-dataclass reprs are deterministic, which is all a hash needs.
    domains = getattr(scan.constraint, "domains", ()) or ()
    if domains:
        text += "|" + ";".join(
            sorted(f"{col}={dom!r}" for col, dom in domains)
        )
    text += "|" + ";".join(parts)
    return "l:" + hashlib.sha256(text.encode()).hexdigest()[:16]


def node_fingerprint(node) -> str:
    """Exact structural subtree fingerprint (the capstore plan-fingerprint
    contract applied per node). Empty string when the subtree holds types
    outside the plancodec registry — no key, no persistence."""
    from .plancodec import fingerprint

    fp = fingerprint(node)
    return ("s:" + fp[:16]) if fp else ""


# --------------------------------------------------------------------------- #
# history store (capstore-modeled: env-pointed JSON file, atomic rename,
# merge-on-write; bounded in-process dict otherwise)
# --------------------------------------------------------------------------- #


def history_path() -> Optional[str]:
    return knobs.env_path(ENV_VAR)


# mtime-keyed read cache: make_estimator loads the history on every planned
# query (twice per optimize() — join reordering builds its own estimator);
# re-parsing the whole JSON file each time would scale planning cost with
# store size. Guarded by _lock.
_file_cache: "Dict[str, tuple]" = {}  # path -> (mtime_ns, data)


def _split_object(path: str):
    """(filesystem, key Location) for an ``object://`` history path."""
    from ..fs import Location
    from .objectstore import backend_for_root

    base, _, name = str(path).rstrip("/").rpartition("/")
    fs, _ = backend_for_root(base)
    return fs, Location("object", name)


def _read_object_locked(path: str) -> Dict[str, dict]:
    """Object-backend read: the etag plays the mtime's cache-key role (no
    stat on an object store — the GET returns content + etag together and
    per-key reads are strongly consistent)."""
    fs, loc = _split_object(path)
    try:
        raw, etag = fs.read_with_etag(loc)
    except OSError:
        return {}
    cached = _file_cache.get(path)
    if cached is not None and cached[0] == etag:
        return cached[1]
    try:
        data = json.loads(raw.decode())
    except ValueError:
        from .ha import note_torn_record

        note_torn_record()
        return {}
    if not isinstance(data, dict):
        return {}
    _file_cache.clear()
    _file_cache[path] = (etag, data)
    return data


def _read_file_locked(path: str) -> Dict[str, dict]:
    from .objectstore import is_object_uri

    if is_object_uri(path):
        return _read_object_locked(path)
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return {}
    cached = _file_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    try:
        with open(path, "r") as f:
            data = json.load(f)
    except OSError:
        return {}
    except ValueError:
        # a truncated store (killed mid-write before the atomic rename
        # landed, or external corruption): recover cold instead of crashing
        from .ha import note_torn_record

        note_torn_record()
        return {}
    if not isinstance(data, dict):
        return {}
    _file_cache.clear()  # one live path; a test switching files must not pin
    _file_cache[path] = (mtime, data)
    return data


def load_history() -> Dict[str, dict]:
    """Full key -> entry map (the overlay estimator and the system table
    both read it). A snapshot: mutations go through :func:`record_history`."""
    path = history_path()
    with _lock:
        if path is None:
            return dict(_memory_store)
        return dict(_read_file_locked(path))


def lookup(key: str) -> Optional[dict]:
    if not key:
        return None
    path = history_path()
    with _lock:
        if path is None:
            ent = _memory_store.get(key)
        else:
            ent = _read_file_locked(path).get(key)
        return dict(ent) if ent else None


def _evict_oldest(data: Dict[str, dict]) -> None:
    """Bound the store (memory AND file): beyond the cap, drop the
    least-recently-updated entries — unbounded growth in a long-lived
    coordinator recording every query shape is the failure mode."""
    if len(data) <= _MAX_MEMORY_ENTRIES:
        return
    by_age = sorted(data, key=lambda k: data[k].get("updated_at", 0.0))
    for key in by_age[: len(data) - _MAX_MEMORY_ENTRIES]:
        del data[key]


def record_history(entries: Dict[str, dict]) -> None:
    """Merge per-node records into the store. Existing entries keep their
    run counter; the latest actual wins (executions are deterministic, and
    the newest observation reflects the current catalog state)."""
    if not entries:
        return
    path = history_path()
    with _lock:
        if path is None:
            data = _memory_store
        else:
            data = dict(_read_file_locked(path))
        for key, ent in entries.items():
            prev = data.get(key)
            if prev:
                ent = dict(ent)
                ent["runs"] = int(prev.get("runs", 0)) + 1
            data[key] = ent
        _evict_oldest(data)
        if path is None:
            return
        from .objectstore import is_object_uri

        if is_object_uri(path):
            # CAS merge-on-write (mirrors capstore): a lost etag race
            # re-reads and re-merges, so concurrent recorders never drop
            # each other's keys on the rename-free substrate
            fs, loc = _split_object(path)
            for _ in range(16):
                body = json.dumps(data).encode()
                try:
                    _, etag = fs.read_with_etag(loc)
                except OSError:
                    etag = None
                if etag is None:
                    if fs.write_if_absent(loc, body):
                        break
                elif fs.write_if_match(loc, body, etag) is not None:
                    break
                merged = dict(_read_object_locked(path))
                merged.update(data)
                _evict_oldest(merged)
                data = merged
            _file_cache.clear()
            _file_cache[path] = (hashlib.md5(body).hexdigest(), data)
            return
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".statstore-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
            _file_cache.clear()
            try:
                _file_cache[path] = (os.stat(path).st_mtime_ns, data)
            except OSError:
                pass
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def clear_memory() -> None:
    """Test hook: drop the in-process store, read cache, and the
    operator-stats ring."""
    with _lock:
        _memory_store.clear()
        _file_cache.clear()
    with _OP_STATS_LOCK:
        _OP_STATS.clear()


# --------------------------------------------------------------------------- #
# operator-stats ring (system.runtime.operator_stats)
# --------------------------------------------------------------------------- #


def operator_stats_log() -> List[dict]:
    with _OP_STATS_LOCK:
        return list(_OP_STATS)


def _log_operator_stats(rows: List[dict]) -> None:
    with _OP_STATS_LOCK:
        _OP_STATS.extend(rows)


# --------------------------------------------------------------------------- #
# the feedback step
# --------------------------------------------------------------------------- #


def _session_float(session, name: str, default: float) -> float:
    try:
        return float(session.get(name))
    except (KeyError, TypeError, ValueError):
        return default


def _session_bool(session, name: str, default: bool) -> bool:
    try:
        return bool(session.get(name))
    except KeyError:
        return default


def merge_actuals(dst: Dict[int, dict], src: Dict[int, dict]) -> None:
    """Fold one executor's finalized actuals into a query-level rollup
    (fragment partitions sum; null fractions average weighted by rows)."""
    for key, ent in src.items():
        cur = dst.get(key)
        if cur is None:
            dst[key] = dict(ent)
            continue
        old_rows, new_rows = cur.get("rows", 0), ent.get("rows", 0)
        a, b = cur.get("null_frac"), ent.get("null_frac")
        if a is not None or b is not None:
            total = old_rows + new_rows
            cur["null_frac"] = (
                ((a or 0.0) * old_rows + (b or 0.0) * new_rows) / total
                if total else (a if a is not None else b)
            )
        cur["rows"] = old_rows + new_rows
        cur["capacity"] = cur.get("capacity", 0) + ent.get("capacity", 0)
        cur["bytes"] = cur.get("bytes", 0) + ent.get("bytes", 0)
        for k in ("dyn_pre", "dyn_post"):
            if k in cur or k in ent:
                cur[k] = cur.get(k, 0) + ent.get(k, 0)


def observe_query(
    plan,
    metadata,
    session,
    collector,
    actuals: Dict[int, dict],
    query_id: str = "",
    fragment: Optional[int] = None,
) -> None:
    """Fold executed per-node actuals into the collector, detect
    mis-estimates, and feed the history store.

    ``actuals``: id(plan node) -> {"rows", "capacity", "bytes",
    "null_frac", join-only "dyn_pre"/"dyn_post"} as produced by
    ``PlanExecutor.finalize_actuals`` (merged with :func:`merge_actuals`
    for multi-partition runs). ``fragment``: distributed callers observe
    once per fragment (actuals pre-aggregated across partitions and FTE
    attempts — only the winning attempt of a speculative pair was folded
    in). Runs once per query AFTER the result drained; never on the hot
    path.
    """
    from ..planner.plan import JoinNode, visit_plan
    from ..planner.stats import make_estimator
    from .observability import RECORDER

    if not actuals:
        return
    estimator = make_estimator(metadata, plan.types, session)
    threshold = _session_float(session, "qerror_threshold", 2.0)
    record = _session_bool(session, "statistics_feedback", True)
    now = time.time()

    ordered: List[object] = []
    visit_plan(plan.root, ordered.append)

    history: Dict[str, dict] = {}
    ring_rows: List[dict] = []
    misestimates = 0
    plan_fp = node_fingerprint(plan.root)

    with RECORDER.span("stats_feedback", "stats", query=query_id):
        for idx, node in enumerate(ordered):
            ent = actuals.get(id(node))
            if ent is None:
                continue
            kind = type(node).__name__
            act = int(ent.get("rows", 0))
            try:
                est = estimator.rows(node)
            except Exception:  # noqa: BLE001 — estimation must never fail a query
                est = None
            q = q_error(est, act)
            input_rows = sum(
                int(actuals[id(s)].get("rows", 0))
                for s in node.sources
                if id(s) in actuals
            )
            build_rows = None
            dyn_sel = None
            if isinstance(node, JoinNode):
                build = actuals.get(id(node.right))
                if build is not None:
                    build_rows = int(build.get("rows", 0))
                if ent.get("dyn_pre"):
                    dyn_sel = float(ent.get("dyn_post", 0)) / float(ent["dyn_pre"])
            key = f"{idx}:{kind}" if fragment is None else f"f{fragment}.{idx}:{kind}"
            collector.add_node(
                key,
                kind=kind,
                actual_rows=act,
                estimated_rows=est,
                q_error=q,
                input_rows=input_rows,
                output_bytes=int(ent.get("bytes", 0)),
                null_fraction=ent.get("null_frac"),
                build_rows=build_rows,
                dynamic_filter_selectivity=dyn_sel,
            )
            ring_rows.append({
                "query_id": query_id,
                "node_id": idx,
                "fragment": fragment,
                "kind": kind,
                "estimate": est,
                "actual": act,
                "input_rows": input_rows,
                "bytes": int(ent.get("bytes", 0)),
                "null_frac": ent.get("null_frac"),
                "build_rows": build_rows,
                "dyn_filter_sel": dyn_sel,
                "qerror": q,
                "ts": now,
            })
            if q is not None:
                _metric_histogram().observe(q)
                if q > threshold:
                    misestimates += 1
                    _metric_counter().inc()
                    RECORDER.instant(
                        "cardinality_misestimate", "stats",
                        node=key, estimate=est, actual=act,
                        q=round(q, 3), query=query_id,
                    )
            if record:
                h = node.table if kind == "TableScanNode" else None
                base = {
                    "kind": kind,
                    "plan": plan_fp,
                    "table": f"{h.catalog}.{h.schema_table}" if h else None,
                    "estimate": est,
                    "actual": act,
                    "qerror": q,
                    "runs": 1,
                    "updated_at": now,
                }
                fp = node_fingerprint(node)
                if fp:
                    history[fp] = dict(base)
                lk = leaf_key_for(node)
                if lk:
                    history[lk] = dict(base)
    _log_operator_stats(ring_rows)
    if record:
        record_history(history)


_metric_cache: Dict[str, object] = {}


def _metric_counter():
    m = _metric_cache.get("counter")
    if m is None:
        from .metrics import REGISTRY

        m = _metric_cache["counter"] = REGISTRY.counter(
            "trino_tpu_cardinality_misestimates_total",
            help="plan nodes whose actual rows exceeded the q-error threshold",
        )
    return m


def _metric_histogram():
    m = _metric_cache.get("histogram")
    if m is None:
        from .metrics import REGISTRY

        m = _metric_cache["histogram"] = REGISTRY.histogram(
            "trino_tpu_cardinality_qerror",
            help="per-node cardinality q-error (estimate vs actual)",
            buckets=(1.0, 1.5, 2.0, 4.0, 8.0, 16.0, 64.0, 256.0, 1024.0),
        )
    return m
