"""Cross-query/cross-session persistence of adaptively tuned capacities.

Round-4 verdict: AdaptiveQuery re-tunes per instance — Q18 paid 683 s of
tuning for a 34 s steady state, and every bench child process re-ran the
same grow/shrink compiles. The reference amortizes the analogous cost by
caching generated classes per expression (sql/gen/PageFunctionCompiler.java:103
result cache) and by reusing runtime stats across executions of a prepared
statement; we amortize by persisting the tuned per-node capacities keyed by
a structural plan fingerprint:

- fingerprint = sha256 of the schema'd JSON plan encoding (runtime/plancodec)
  — stable across processes for the same SQL over the same catalog, and it
  changes whenever the plan shape (and therefore the narrowing points)
  changes, so stale vectors can never be mis-applied.
- value = the capacity vector in canonical preorder over the narrowing
  candidates (the same `visit_plan` order `plan_capacities` enumerates).
- capacities are power-of-two bucketed (`_round_capacity`) BEFORE storing,
  so a store hit re-creates byte-identical program shapes and lands in the
  persistent XLA compilation cache (.jax_cache_tpu) — the warm path is one
  cached compile instead of a tuning loop.

The store is a single JSON file written via atomic rename (tempfile +
os.replace); concurrent bench children merge-on-write (read latest, update
own key, replace). Lost updates between two simultaneous writers cost a
re-tune later, never corruption. Location: $TRINO_TPU_CAP_STORE, else an
in-process dict (still deduplicates tuning within one session).

An ``object://`` $TRINO_TPU_CAP_STORE runs the same single-object store on
the retrying object backend — merge-on-write becomes an etag CAS loop
(``write_if_match``), which upgrades the local backend's lost-update window
into an actual read-modify-write: concurrent writers on the rename-free
substrate never drop each other's fingerprints.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Dict, List, Optional

from .. import knobs

_lock = threading.Lock()
_memory_store: Dict[str, List[Optional[int]]] = {}

ENV_VAR = "TRINO_TPU_CAP_STORE"


def capacity_class(n: int, base: int = 1024) -> int:
    """THE canonical 4x-spaced capacity class (1024, 4096, 16384, ...):
    the smallest class ``>= n`` — varying input sizes collapse into a
    handful of classes, so compiled-program caches key on the CLASS, not
    the row count (OOC bucket loops, the device-batching plane's batch
    keys, v2 serde frame landing).

    Boundary CONTRACT: ``n`` landing exactly on a class edge resolves to
    that class itself — ``capacity_class(4096) == 4096``, and only
    ``4097`` promotes to ``16384``. The function is a pure closed-form of
    ``n`` (no floats, no env, no process state), so two processes — or
    two runs of one process — always agree; a disagreement here would
    silently DOUBLE compiles (each side tracing its own shape) and defeat
    the device scheduler's batch keying, where lanes pack only when their
    inputs share a class. ``n <= 0`` resolves to ``base`` (the smallest
    class; zero-capacity arrays break downstream initializers).
    """
    cap = base
    while cap < n:
        cap *= 4
    return cap


def store_path() -> Optional[str]:
    return knobs.env_path(ENV_VAR)


def plan_fingerprint(plan) -> str:
    """Structural fingerprint of a logical plan (node types, symbols,
    expressions — everything the codec serializes). Delegates to the shared
    plancodec.fingerprint so the capacity store and the statistics history
    store (runtime/statstore.py) key on the SAME notion of plan identity."""
    from .plancodec import fingerprint

    return fingerprint(plan.root)


def _split_object(path: str):
    """(filesystem, key Location) for an ``object://`` store path."""
    from ..fs import Location
    from .objectstore import backend_for_root

    base, _, name = str(path).rstrip("/").rpartition("/")
    fs, _ = backend_for_root(base)
    return fs, Location("object", name)


def _read_file(path: str) -> Dict[str, List[Optional[int]]]:
    from .objectstore import is_object_uri

    if is_object_uri(path):
        fs, loc = _split_object(path)
        try:
            data = json.loads(fs.read(loc).decode())
            if isinstance(data, dict):
                return data
        except (OSError, ValueError):
            pass
        return {}
    try:
        with open(path, "r") as f:
            data = json.load(f)
        if isinstance(data, dict):
            return data
    except (OSError, ValueError):
        pass
    return {}


def _save_object(path: str, fingerprint: str, caps: List[Optional[int]]) -> None:
    """CAS merge-on-write: read latest (with etag), update our key,
    conditional put. A lost CAS re-reads and retries, so concurrent
    writers MERGE instead of clobbering."""
    fs, loc = _split_object(path)
    for _ in range(16):
        try:
            raw, etag = fs.read_with_etag(loc)
            data = json.loads(raw.decode())
            if not isinstance(data, dict):
                data = {}
        except (OSError, ValueError):
            data, etag = {}, None
        data[fingerprint] = list(caps)
        body = json.dumps(data).encode()
        if etag is None:
            if fs.write_if_absent(loc, body):
                return
        elif fs.write_if_match(loc, body, etag) is not None:
            return


def load(fingerprint: str) -> Optional[List[Optional[int]]]:
    if not fingerprint:
        return None
    path = store_path()
    with _lock:
        if path is None:
            vec = _memory_store.get(fingerprint)
        else:
            vec = _read_file(path).get(fingerprint)
    return list(vec) if vec is not None else None


def save(fingerprint: str, caps: List[Optional[int]]) -> None:
    if not fingerprint:
        return
    path = store_path()
    with _lock:
        if path is None:
            _memory_store[fingerprint] = list(caps)
            return
        from .objectstore import is_object_uri

        if is_object_uri(path):
            _save_object(path, fingerprint, caps)
            return
        data = _read_file(path)
        data[fingerprint] = list(caps)
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".capstore-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def clear_memory() -> None:
    """Test hook: drop the in-process store."""
    with _lock:
        _memory_store.clear()
