"""Multi-format file connector: ORC, CSV, and newline-delimited JSON tables.

Reference blueprint: lib/trino-orc (OrcReader.java:67 — stripe-granular
reading, createRecordReader:252), lib/trino-hive-formats (text/CSV/JSON line
codecs), and plugin/trino-hive's directory-per-table layout. Layout:
``root/<table>/*.{orc,csv,json}``; one catalog = one format.

Split granularity follows each format's natural unit, like the reference:
ORC splits one stripe at a time (the reference's stripe/rowgroup pruning
unit); CSV/JSON split per file (line formats have no internal index). Arrow
does the host-side decode (declared delegation, connectors/arrow_ingest.py);
everything above — splits, dictionaries, pages, pushdown — is this engine's.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Dictionary, Page
from ..spi.predicate import TupleDomain
from .arrow_ingest import arrow_table_to_page, arrow_to_type

_EXT = {"orc": ".orc", "csv": ".csv", "json": ".json", "parquet": ".parquet"}


def discover_partitioned_files(table_dir: str, ext: str):
    """Hive-layout discovery: ``table/key=value/.../file.ext`` -> ordered
    [(path, {key: value})] (ref: plugin/trino-hive's partition directory
    convention + HiveSplitManager partition enumeration). Non-partitioned
    tables are the flat special case ({} partition values)."""
    import urllib.parse

    out = []
    for root, dirs, files in os.walk(table_dir):
        dirs.sort()
        rel = os.path.relpath(root, table_dir)
        parts: Dict[str, str] = {}
        valid = True
        if rel != ".":
            for seg in rel.split(os.sep):
                k, eq, v = seg.partition("=")
                if not eq or not k:
                    valid = False
                    break
                parts[k] = urllib.parse.unquote(v)
        if not valid:
            continue
        for f in sorted(files):
            if f.endswith(ext):
                out.append((os.path.join(root, f), parts))
    return sorted(out)


def partition_schema(entries) -> List:
    """Partition column names + inferred types: BIGINT when every value is an
    integer literal, else VARCHAR (the metastore-less inference; the
    reference reads declared types from the metastore)."""
    from ..spi.types import BIGINT as _B, VarcharType as _V

    if not entries:
        return []
    keys = list(entries[0][1].keys())
    cols = []
    for k in keys:
        vals = [parts.get(k) for _, parts in entries]
        is_int = all(
            v is not None and (v.lstrip("-").isdigit() and v not in ("", "-"))
            for v in vals
        )
        cols.append((k, _B if is_int else _V()))
    return cols


class FileFormatConnector(Connector):
    """``root/<table>/*.<format>`` as a catalog schema (orc | csv | json)."""

    def __init__(self, root: str, format: str, schema: str = "default"):
        if format not in _EXT:
            raise ValueError(f"unsupported file format: {format}")
        self.root = root
        self.format = format
        self.schema = schema
        self.name = format
        self._meta = _Metadata(self)
        self._splits = _Splits(self)
        self._pages = _Pages(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    def table_files(self, table: str) -> List[str]:
        return [p for p, _ in self.table_entries(table)]

    def table_entries(self, table: str):
        """[(path, partition_values)] in hive layout (flat tables: {})."""
        d = os.path.join(self.root, table)
        if not os.path.isdir(d):
            return []
        return discover_partitioned_files(d, _EXT[self.format])

    def partition_columns(self, table: str):
        return partition_schema(self.table_entries(table))

    # ------------------------------------------------------------- decoding

    def read_split(self, path: str, part: int):
        """One split's rows as an Arrow table (ORC: one stripe; text: file)."""
        if self.format == "orc":
            import pyarrow as pa
            import pyarrow.orc as orc

            # read_stripe yields a RecordBatch; normalize to a Table so the
            # shared ingest sees one chunked-array interface
            return pa.Table.from_batches([orc.ORCFile(path).read_stripe(part)])
        if self.format == "csv":
            import pyarrow.csv as pacsv

            return pacsv.read_csv(path)
        if self.format == "parquet":
            import pyarrow.parquet as pq

            return pq.read_table(path)
        import pyarrow.json as pajson

        return pajson.read_json(path)

    def file_schema(self, path: str):
        if self.format == "orc":
            import pyarrow.orc as orc

            return orc.ORCFile(path).schema
        if self.format == "parquet":
            import pyarrow.parquet as pq

            return pq.read_schema(path)
        return self.read_split(path, 0).schema

    def split_parts(self, path: str) -> int:
        if self.format == "orc":
            import pyarrow.orc as orc

            return max(orc.ORCFile(path).nstripes, 1)
        return 1

    def file_rows(self, path: str) -> int:
        if self.format == "orc":
            import pyarrow.orc as orc

            return orc.ORCFile(path).nrows
        if self.format == "parquet":
            import pyarrow.parquet as pq

            return pq.ParquetFile(path).metadata.num_rows
        return self.read_split(path, 0).num_rows


class _Metadata(ConnectorMetadata):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector

    def list_schemas(self) -> List[str]:
        return [self.connector.schema]

    def list_tables(self, schema: Optional[str] = None):
        root = self.connector.root
        tables = [
            t
            for t in (sorted(os.listdir(root)) if os.path.isdir(root) else [])
            if self.connector.table_files(t)
        ]
        return [SchemaTableName(self.connector.schema, t) for t in tables]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        files = self.connector.table_files(name.table)
        if not files:
            return None
        schema = self.connector.file_schema(files[0])
        cols = []
        for field in schema:
            t = arrow_to_type(field)
            if t is not None:
                cols.append(ColumnMetadata(field.name, t))
        # hive convention: partition columns come AFTER the file columns
        for pname, ptype in self.connector.partition_columns(name.table):
            cols.append(ColumnMetadata(pname, ptype))
        return TableMetadata(name, tuple(cols))

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        rows = sum(
            self.connector.file_rows(f)
            for f in self.connector.table_files(handle.schema_table.table)
        )
        return TableStatistics(row_count=float(rows))

    def apply_filter(self, handle: TableHandle, domain: TupleDomain):
        return TableHandle(handle.catalog, handle.schema_table, connector_handle=domain)


class _Splits(ConnectorSplitManager):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        table = handle.schema_table.table
        constraint = handle.connector_handle
        pcols = dict(self.connector.partition_columns(table))
        entries = []
        for path, pvals in self.connector.table_entries(table):
            if isinstance(constraint, TupleDomain) and self._pruned(
                pvals, pcols, constraint
            ):
                continue
            for part in range(self.connector.split_parts(path)):
                entries.append((path, part, pvals))
        return [
            Split(handle, sid, len(entries), info=e) for sid, e in enumerate(entries)
        ]

    def _pruned(self, pvals, pcols, constraint: TupleDomain) -> bool:
        """Partition pruning: the hive connector's biggest lever — a
        directory whose key=value lies outside the pushed-down domain is
        never read (HivePartitionManager.getOrLoadPartitions analogue)."""
        from ..spi.types import VarcharType

        for col, dom in constraint.domains:
            if col not in pvals:
                continue
            v = pvals[col]
            if not isinstance(pcols.get(col), VarcharType):
                try:
                    v = int(v)
                except ValueError:
                    continue
            if not dom.contains_value(v):
                return True
        return False


class _Pages(ConnectorPageSourceProvider):
    def __init__(self, connector: FileFormatConnector):
        self.connector = connector
        self._dicts: Dict[tuple, Dictionary] = {}

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        import jax.numpy as jnp
        import numpy as np

        from ..spi.page import Column
        from ..spi.types import VarcharType

        path, part, pvals = split.info
        meta = self.connector.metadata().get_table_metadata(split.table.schema_table)
        wanted = [meta.columns[i] for i in column_indexes]
        file_cols = [c for c in wanted if c.name not in pvals]
        table = self.connector.read_split(path, part)
        # text formats may infer a wider schema per file; select by name
        table = table.select([c.name for c in file_cols])
        page = arrow_table_to_page(table, file_cols, self._dicts, (path, part))
        if len(file_cols) == len(wanted):
            return page
        # splice constant partition-value columns into the requested order
        # (HivePageSource prefilled partition-key blocks)
        n = page.capacity
        by_name = dict(zip((c.name for c in file_cols), page.columns))
        out = []
        for cm in wanted:
            if cm.name in by_name:
                out.append(by_name[cm.name])
                continue
            v = pvals[cm.name]
            if isinstance(cm.type, VarcharType):
                out.append(
                    Column.from_strings([v] * n, cm.type)
                )
            else:
                out.append(
                    Column(
                        cm.type,
                        jnp.full((n,), int(v), dtype=cm.type.storage_dtype),
                        jnp.ones((n,), dtype=jnp.bool_),
                    )
                )
        return Page(tuple(out), page.active)
